"""Fleet telemetry plane: federation, usage accounting, capacity signal.

Every replica exports deep LOCAL telemetry (/metrics, /debug/vitals,
/healthz) but the fleet has no assembled view — an operator, or the
ROADMAP item-4 elastic-capacity controller, would have to scrape N
replicas by hand. This module is the router-side assembly point:

  * `FleetScraper` — a background thread (same discipline as the
    router's probe loop: injectable clock, one socket seam, NEVER on
    the dispatch path) polling each replica's `/metrics` +
    `/debug/vitals` + `/healthz` on an interval. Scrape failures — dead
    replica, garbage body, hung socket — degrade to stale-marked
    generations counted in `dalle_fleet_scrape_errors_total{replica=}`;
    routing never waits on a scrape.
  * federation — `GET /fleet/metrics` re-exports every replica sample
    with a `replica=` label plus rollup families (`<name>:fleet_sum`
    for counters — reset-corrected since scraper start — sum/max for
    gauges, bucket-merged `<name>:fleet` histograms), and
    `GET /debug/fleet` the structured JSON view.
  * `UsageLedger` — per-tenant / per-priority chip-second and FLOP
    attribution from the router's own request accounting joined with
    the scraped ProgramCostTable rates
    (`dalle_fleet_chip_seconds_total{tenant=,priority=}`,
    `GET /debug/usage`); tenant cardinality is bounded with an
    `__other__` overflow bucket (the TL022 rule polices unbounded
    request-scoped labels for everyone else).
  * `CapacityModel.assess()` — a pure function over the latest scrape
    generation producing per-replica MFU headroom, queue depth, SLO
    burn, the fleet goodput fraction (useful decoded tokens vs
    re-decoded + preempted-discarded + warmup work), and the advisory
    `suggested_replicas` block item 4's controller will consume.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from dalle_pytorch_tpu.training.metrics import (
    MetricsRegistry,
    ParsedFamily,
    counter_delta,
    merge_histogram_points,
    parse_exposition,
    render_histogram_point,
    _fmt,
)


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n"
    )


def _render_labels(labels: List[Tuple[str, str]]) -> str:
    return ",".join(f'{k}="{_escape_label(v)}"' for k, v in labels)


# --------------------------------------------------------------- scrapes


class ReplicaScrape:
    """Latest known telemetry for one replica: parsed metric families,
    /healthz detail, a vitals summary, and freshness bookkeeping. A
    failed scrape keeps the previous payload and flips `stale` — a
    consumer must treat a stale generation as history, not truth."""

    __slots__ = (
        "name", "url", "generation", "ts", "stale", "error",
        "families", "health", "vitals", "monotonic",
    )

    def __init__(self, name: str, url: str):
        self.name, self.url = name, url
        self.generation = 0          # successful scrapes only
        self.ts: Optional[float] = None
        self.stale = True            # nothing scraped yet
        self.error: Optional[str] = None
        self.families: Dict[str, ParsedFamily] = {}
        self.health: Dict = {}
        self.vitals: Dict = {}
        #: reset-corrected per-series counter totals since scraper start
        #: ({(sample name, sorted labels): float})
        self.monotonic: Dict[Tuple, float] = {}


class FleetScraper:
    """Background poller assembling the fleet view. Lifecycle mirrors
    the router's probe loop: `start()`/`stop()` own a daemon thread,
    `scrape_once()` is the thread body and the test seam (drive it with
    a stubbed clock), `_fetch()` is the single socket touch."""

    def __init__(
        self,
        replicas: List[Tuple[str, str]],
        registry: Optional[MetricsRegistry] = None,
        usage: Optional["UsageLedger"] = None,
        interval_s: float = 2.0,
        timeout_s: float = 2.0,
        time_fn: Callable[[], float] = time.monotonic,
        log=None,
    ):
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.registry = registry if registry is not None else MetricsRegistry()
        self.usage = usage
        self.log = log
        self._now = time_fn
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._sweep = 0
        self._scrapes: Dict[str, ReplicaScrape] = {
            name: ReplicaScrape(name, url) for name, url in replicas
        }
        self._prev: Dict[Tuple, float] = {}  # (replica, series) → raw value
        r = self.registry
        self._m_scrapes = r.counter_family(
            "dalle_fleet_scrapes_total",
            "successful replica scrapes by the fleet telemetry poller",
            label_name="replica",
        )
        self._m_errors = r.counter_family(
            "dalle_fleet_scrape_errors_total",
            "failed replica scrapes (dead replica, garbage exposition "
            "body, timeout) — the generation goes stale, routing is "
            "unaffected",
            label_name="replica",
        )
        self._m_generation = r.gauge_family(
            "dalle_fleet_scrape_generation",
            "successful-scrape generation per replica",
            label_name="replica",
        )
        self._m_stale = r.gauge_family(
            "dalle_fleet_scrape_stale",
            "1 when the replica's latest scrape attempt failed and the "
            "carried generation is history, not truth",
            label_name="replica",
        )
        self._m_goodput = r.gauge(
            "dalle_fleet_goodput_fraction",
            "useful decoded tokens over total decode work (re-decoded, "
            "preempted-discarded, and warmup work are the waste terms)",
        )
        self._m_suggested = r.gauge(
            "dalle_fleet_suggested_replicas",
            "advisory replica count from the capacity model (the "
            "elastic-serving input signal; nothing acts on it yet)",
        )
        self._m_headroom = r.gauge_family(
            "dalle_fleet_mfu_headroom",
            "per-replica fraction of the serving-MFU ceiling still "
            "unused (1.0 = idle, 0.0 = at the ceiling)",
            label_name="replica",
        )

    # ---------------------------------------------------------- transport

    def _fetch(self, url: str, path: str) -> bytes:
        """The one scrape socket touch (stubbed in tests): GET url+path,
        return the body bytes. Raises on transport failure or non-200 —
        the caller converts that into a stale generation."""
        req = urllib.request.Request(url + path, method="GET")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            if resp.status != 200:
                raise urllib.error.HTTPError(
                    url + path, resp.status, "scrape failed", resp.headers,
                    None,
                )
            return resp.read()

    # ------------------------------------------------------------ sweeps

    def _scrape_one(self, scrape: ReplicaScrape, now: float) -> None:
        """Scrape one replica's three surfaces; commit atomically under
        the lock on success, mark stale (keeping the last good payload)
        on ANY failure."""
        try:
            metrics_body = self._fetch(scrape.url, "/metrics")
            families = parse_exposition(metrics_body.decode("utf-8"))
            health = json.loads(self._fetch(scrape.url, "/healthz") or b"{}")
            vitals = json.loads(
                self._fetch(scrape.url, "/debug/vitals?n=1") or b"{}"
            )
            if not isinstance(health, dict) or not isinstance(vitals, dict):
                raise ValueError("health/vitals body is not a JSON object")
        except urllib.error.HTTPError as exc:
            # /healthz answers 503 while draining/unhealthy — that is an
            # ANSWER for the prober, but for telemetry the payload may
            # be mid-shutdown; treat any non-200 as a failed scrape
            self._mark_failed(scrape, f"http {exc.code} on {exc.filename}")
            return
        except Exception as exc:
            self._mark_failed(scrape, repr(exc))
            return
        with self._lock:
            scrape.families = families
            scrape.health = health
            scrape.vitals = vitals
            scrape.ts = now
            scrape.stale = False
            scrape.error = None
            scrape.generation += 1
            for fam in families.values():
                if fam.type != "counter":
                    continue
                for s in fam.samples:
                    series = s.key()
                    prev = self._prev.get((scrape.name, series))
                    scrape.monotonic[series] = (
                        scrape.monotonic.get(series, 0.0)
                        + counter_delta(prev, s.value)
                    )
                    self._prev[(scrape.name, series)] = s.value
        self._m_scrapes.labels(scrape.name).inc()
        self._m_generation.labels(scrape.name).set(scrape.generation)
        self._m_stale.labels(scrape.name).set(0)

    def _mark_failed(self, scrape: ReplicaScrape, error: str) -> None:
        with self._lock:
            scrape.stale = True
            scrape.error = error
        self._m_errors.labels(scrape.name).inc()
        self._m_stale.labels(scrape.name).set(1)
        if self.log is not None:
            self.log.event(
                "fleet_scrape_failed", replica=scrape.name, error=error,
            )

    def scrape_once(self, now: Optional[float] = None) -> None:
        """One sweep over every replica — the scrape thread's body,
        callable directly from tests. Replicas are scraped CONCURRENTLY
        (sweep time = max fetch latency, not the sum), so one hung
        endpoint's timeout cannot starve the others' freshness."""
        now = self._now() if now is None else now
        with self._lock:
            scrapes = list(self._scrapes.values())
        if len(scrapes) == 1:
            self._scrape_one(scrapes[0], now)
        elif scrapes:
            threads = [
                threading.Thread(
                    target=self._scrape_one, args=(s, now),
                    name="dalle-fleet-scrape-one", daemon=True,
                )
                for s in scrapes
            ]
            for t in threads:
                t.start()
            for t in threads:
                # 3 fetches per replica, each bounded by timeout_s
                t.join(timeout=3.0 * self.timeout_s + 5.0)
        with self._lock:
            self._sweep += 1
        self._refresh_capacity_gauges()

    def _refresh_capacity_gauges(self) -> None:
        report = self.capacity_report()
        self._m_goodput.set(report["goodput"]["fraction"])
        self._m_suggested.set(report["suggested_replicas"])
        for name, rep in report["replicas"].items():
            headroom = rep.get("mfu_headroom")
            if headroom is not None:
                self._m_headroom.labels(name).set(headroom)

    # --------------------------------------------------------- lifecycle

    def start(self) -> "FleetScraper":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="dalle-fleet-scraper", daemon=True,
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.scrape_once()
            except Exception as exc:  # the scrape thread must never die;
                if self.log is not None:  # the stop-wait below is its
                    self.log.event(  # backoff before the retry
                        "fleet_sweep_error", error=repr(exc)
                    )
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=3.0 * self.timeout_s + 5.0)
            self._thread = None

    # ------------------------------------------------------------- reads

    def snapshot(self) -> Dict[str, ReplicaScrape]:
        """Shallow copy of the per-replica scrape map. The ReplicaScrape
        payloads are replaced wholesale on each successful sweep, so
        holding a reference across sweeps is safe for reading."""
        with self._lock:
            return dict(self._scrapes)

    def fleet_totals(self, sample_name: str) -> float:
        """Reset-corrected fleet total for one counter series name,
        summed across replicas and label sets, since scraper start."""
        total = 0.0
        with self._lock:
            for scrape in self._scrapes.values():
                for (name, _labels), v in scrape.monotonic.items():
                    if name == sample_name:
                        total += v
        return total

    def capacity_report(self) -> Dict:
        usage_summary = self.usage.summary() if self.usage is not None \
            else None
        return CapacityModel.assess(
            self.snapshot(),
            fleet_decoded_tokens=self.fleet_totals(
                "dalle_serving_decoded_tokens_total"
            ),
            fleet_resumed_tokens=self.fleet_totals(
                "dalle_serving_resumed_tokens_total"
            ),
            usage=usage_summary,
        )

    def fleet_detail(self) -> Dict:
        """The `GET /debug/fleet` JSON: per-replica freshness + health
        summary (including the prefix-cache Bloom digest each replica
        advertises), the capacity/goodput report, and usage totals."""
        now = self._now()
        with self._lock:
            sweep = self._sweep
            scrapes = dict(self._scrapes)
        replicas = {}
        for name, s in sorted(scrapes.items()):
            health = s.health or {}
            kv = health.get("kv") or {}
            entry = {
                "url": s.url,
                "generation": s.generation,
                "stale": s.stale,
                "age_s": (
                    round(now - s.ts, 3) if s.ts is not None else None
                ),
                "status": health.get("status"),
                "queue_depth_rows": health.get("queue_depth_rows"),
                "slots_active": health.get("slots_active"),
                "uptime_s": health.get("uptime_s"),
            }
            if s.error:
                entry["error"] = s.error
            if health.get("work"):
                entry["work"] = health["work"]
            bloom = (kv.get("prefix_cache") or {}).get("bloom")
            if bloom is not None:
                # first observable slice of item-3 prefix-affine routing:
                # the seen-keys digest a future placer will intersect
                entry["prefix_bloom"] = bloom
            replicas[name] = entry
        out = {
            "sweep": sweep,
            "interval_s": self.interval_s,
            "replicas": replicas,
            "capacity": self.capacity_report(),
        }
        if self.usage is not None:
            out["usage"] = self.usage.summary()
        return out

    # -------------------------------------------------------- federation

    def federated_render(self) -> str:
        """The `GET /fleet/metrics` body: every replica sample re-tagged
        `replica="name"`, one HELP/TYPE header per family, plus rollup
        families — `<name>:fleet_sum` (counters: reset-corrected since
        scraper start; gauges: sum of latest values), `<name>:fleet_max`
        (gauges), and `<name>:fleet` bucket-merged histograms. Parseable
        by this project's own `parse_exposition`."""
        scrapes = self.snapshot()
        by_family: Dict[str, List[Tuple[str, ParsedFamily]]] = {}
        for name, scrape in sorted(scrapes.items()):
            for fam_name, fam in scrape.families.items():
                by_family.setdefault(fam_name, []).append((name, fam))
        lines: List[str] = []
        for fam_name in sorted(by_family):
            rows = by_family[fam_name]
            ftype, fhelp = rows[0][1].type, rows[0][1].help
            lines.append(f"# HELP {fam_name} {fhelp}")
            lines.append(f"# TYPE {fam_name} {ftype}")
            for replica, fam in rows:
                for s in fam.samples:
                    labels = [("replica", replica)] + sorted(
                        s.labels.items()
                    )
                    lines.append(
                        f"{s.name}{{{_render_labels(labels)}}} "
                        f"{_fmt(s.value)}"
                    )
            lines.extend(self._rollup_lines(fam_name, ftype, rows, scrapes))
        lines.extend(self._scrape_meta_lines(scrapes))
        return "\n".join(lines) + "\n"

    def _rollup_lines(self, fam_name: str, ftype: str, rows, scrapes):
        lines: List[str] = []
        if ftype == "counter":
            # per label set, summed across replicas, reset-corrected
            totals: Dict[Tuple, float] = {}
            with self._lock:
                for replica, fam in rows:
                    mono = scrapes[replica].monotonic
                    for s in fam.samples:
                        key = s.key()
                        totals[key] = totals.get(key, 0.0) + mono.get(
                            key, 0.0
                        )
            lines.append(f"# TYPE {fam_name}:fleet_sum counter")
            for (name, labels), v in sorted(totals.items()):
                suffix = f"{{{_render_labels(list(labels))}}}" if labels \
                    else ""
                lines.append(f"{fam_name}:fleet_sum{suffix} {_fmt(v)}")
        elif ftype == "gauge":
            grouped: Dict[Tuple, List[float]] = {}
            for _replica, fam in rows:
                for s in fam.samples:
                    grouped.setdefault(s.key(), []).append(s.value)
            for agg, fn in (("fleet_sum", sum), ("fleet_max", max)):
                lines.append(f"# TYPE {fam_name}:{agg} gauge")
                for (name, labels), vs in sorted(grouped.items()):
                    suffix = f"{{{_render_labels(list(labels))}}}" \
                        if labels else ""
                    lines.append(
                        f"{fam_name}:{agg}{suffix} {_fmt(fn(vs))}"
                    )
        elif ftype == "histogram":
            merged: Dict[Tuple, List[Dict]] = {}
            for _replica, fam in rows:
                for labels_key, point in fam.histogram_series().items():
                    merged.setdefault(labels_key, []).append(point)
            lines.append(f"# TYPE {fam_name}:fleet histogram")
            for labels_key, points in sorted(merged.items()):
                lines.extend(render_histogram_point(
                    f"{fam_name}:fleet",
                    merge_histogram_points(points),
                    labels=_render_labels(list(labels_key)),
                ))
        return lines

    def _scrape_meta_lines(self, scrapes) -> List[str]:
        """Scrape freshness rides the federated body itself, so a
        consumer of /fleet/metrics alone can tell truth from history."""
        lines = [
            "# HELP dalle_fleet_scrape_stale 1 when the replica's "
            "latest scrape failed and its samples are carried history",
            "# TYPE dalle_fleet_scrape_stale gauge",
        ]
        for name, s in sorted(scrapes.items()):
            lines.append(
                f'dalle_fleet_scrape_stale{{replica="{name}"}} '
                f"{int(s.stale)}"
            )
        lines.append("# TYPE dalle_fleet_scrape_generation gauge")
        for name, s in sorted(scrapes.items()):
            lines.append(
                f'dalle_fleet_scrape_generation{{replica="{name}"}} '
                f"{s.generation}"
            )
        return lines


# ------------------------------------------------------------ usage ledger


class UsageLedger:
    """Per-tenant / per-priority usage attribution from the router's own
    request accounting: rows, decoded/resumed tokens (from the replica's
    response `usage` block), and chip-seconds (the replica-side dispatch
    wall clock — one chip per replica; `chips_per_replica` scales a
    sharded fleet). FLOPs are attributed at the scraped ProgramCostTable
    rate (`note_flops_rate`, FLOP/s per chip) current at record time.

    Tenant cardinality is BOUNDED: after `max_tenants` distinct tenants,
    new ones fold into the `__other__` bucket — a metric label fed from
    an unbounded request string is exactly the cardinality leak TL022
    polices.
    """

    OTHER = "__other__"
    #: label charset clamp: anything else becomes "_" (tenant strings
    #: come from request bodies; a label value must not explode the
    #: exposition syntax)
    _SAFE = frozenset(
        "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789"
        "-_.:"
    )

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        max_tenants: int = 32,
        chips_per_replica: int = 1,
    ):
        self.max_tenants = int(max_tenants)
        self.chips_per_replica = int(chips_per_replica)
        self._lock = threading.Lock()
        self._rows: Dict[Tuple[str, str], Dict] = {}
        self._tenants: set = set()
        self._flops_per_s = 0.0
        self._m_chip = None
        if registry is not None:
            self._m_chip = registry.counter_family(
                "dalle_fleet_chip_seconds_total",
                "chip-seconds attributed per tenant and priority class "
                "(replica dispatch wall x chips per replica)",
                label_name="tenant",
            )

    def note_flops_rate(self, flops_per_second: float) -> None:
        """Latest fleet-average FLOP/s per chip from the scraped
        ProgramCostTable rows; converts chip-seconds into est. FLOPs."""
        with self._lock:
            self._flops_per_s = max(0.0, float(flops_per_second))

    def _bounded_tenant(self, tenant: Optional[str]) -> str:
        """Clamp a request-supplied tenant string into the bounded label
        space: sanitized charset, length-capped, folded into `__other__`
        once the tenant map is full."""
        raw = str(tenant) if tenant else "anonymous"
        safe = "".join(
            ch if ch in self._SAFE else "_" for ch in raw[:64]
        ) or "anonymous"
        if safe in self._tenants:
            return safe
        if len(self._tenants) >= self.max_tenants:
            return self.OTHER
        self._tenants.add(safe)
        return safe

    def record(
        self,
        tenant: Optional[str],
        priority: str,
        rows: int,
        wall_s: float,
        decoded_tokens: int = 0,
        resumed_tokens: int = 0,
        replica: Optional[str] = None,
    ) -> None:
        chip_s = max(0.0, float(wall_s)) * self.chips_per_replica
        with self._lock:
            label = self._bounded_tenant(tenant)
            key = (label, str(priority))
            row = self._rows.get(key)
            if row is None:
                row = self._rows[key] = {
                    "requests": 0, "rows": 0, "decoded_tokens": 0,
                    "resumed_tokens": 0, "chip_seconds": 0.0,
                    "est_flops": 0.0,
                }
            row["requests"] += 1
            row["rows"] += int(rows)
            row["decoded_tokens"] += int(decoded_tokens)
            row["resumed_tokens"] += int(resumed_tokens)
            row["chip_seconds"] += chip_s
            row["est_flops"] += chip_s * self._flops_per_s
        if self._m_chip is not None:
            self._m_chip.labels_extra(label, priority=str(priority)).inc(
                chip_s
            )

    def summary(self) -> Dict:
        """The `GET /debug/usage` JSON (and the capacity model's
        useful-work input): per-(tenant, priority) rows plus totals."""
        with self._lock:
            rows = [
                {
                    "tenant": tenant, "priority": priority,
                    "requests": r["requests"], "rows": r["rows"],
                    "decoded_tokens": r["decoded_tokens"],
                    "resumed_tokens": r["resumed_tokens"],
                    "chip_seconds": round(r["chip_seconds"], 4),
                    "est_flops": float(f'{r["est_flops"]:.4g}'),
                }
                for (tenant, priority), r in sorted(self._rows.items())
            ]
            flops_per_s = self._flops_per_s
        return {
            "tenants": rows,
            "distinct_tenants": len({r["tenant"] for r in rows}),
            "max_tenants": self.max_tenants,
            "chips_per_replica": self.chips_per_replica,
            "flops_per_chip_second": flops_per_s,
            "totals": {
                "requests": sum(r["requests"] for r in rows),
                "rows": sum(r["rows"] for r in rows),
                "decoded_tokens": sum(r["decoded_tokens"] for r in rows),
                "resumed_tokens": sum(r["resumed_tokens"] for r in rows),
                "chip_seconds": round(
                    sum(r["chip_seconds"] for r in rows), 4
                ),
            },
        }


# --------------------------------------------------------- capacity model


class CapacityModel:
    """Pure functions over a scrape generation — no sockets, no clocks,
    no state: the exact block ROADMAP item 4's elastic controller will
    consume, testable with synthetic snapshots."""

    #: realistic serving-MFU ceiling for headroom math: decode is
    #: latency-bound and never reaches the matmul roofline, so headroom
    #: against 1.0 would read perpetually idle
    MFU_CEILING = 0.35
    #: mean fresh-replica utilization above which the advisory signal
    #: asks for one more replica / below which it releases one
    UTIL_HIGH = 0.85
    UTIL_LOW = 0.30

    @staticmethod
    def _num(v) -> Optional[float]:
        """Coerce a scraped health field to float, or None — /healthz
        payloads cross a process boundary, so junk must degrade to
        "unknown", never raise out of the scrape loop."""
        try:
            f = float(v)
        except (TypeError, ValueError):
            return None
        return f if f == f else None  # NaN is not a measurement

    @staticmethod
    def replica_assessment(scrape: ReplicaScrape) -> Dict:
        """Per-replica slice: MFU headroom (from the scraped
        `dalle_serving_mfu` gauge family), queue depth, slot
        utilization, and worst SLO burn (from /healthz)."""
        health = scrape.health if isinstance(scrape.health, dict) else {}
        out: Dict = {
            "stale": scrape.stale,
            "generation": scrape.generation,
            "status": health.get("status"),
        }
        mfu_fam = scrape.families.get("dalle_serving_mfu")
        if mfu_fam is not None and mfu_fam.samples:
            mfu = max(s.value for s in mfu_fam.samples)
            headroom = max(0.0, 1.0 - mfu / CapacityModel.MFU_CEILING)
            out["mfu"] = float(f"{mfu:.4g}")
            out["mfu_headroom"] = float(f"{headroom:.4g}")
        num = CapacityModel._num
        queue = num(health.get("queue_depth_rows"))
        slots = num(health.get("slots_active"))
        work = health.get("work") if isinstance(health.get("work"), dict) \
            else {}
        max_batch = num(work.get("max_batch"))
        out["queue_depth_rows"] = queue
        out["slots_active"] = slots
        burn = 0.0
        for slo in health.get("slo") or ():
            if isinstance(slo, dict):
                burn = max(burn, num(slo.get("burn_rate")) or 0.0)
        out["slo_burn"] = burn
        util = None
        if max_batch:
            util = (slots or 0.0) / max_batch
            if queue:
                # a standing queue beyond ~4 batches reads as saturated
                util = max(util, min(1.0, queue / (4.0 * max_batch)))
        elif queue is not None:
            util = min(1.0, queue / 16.0)
        if util is not None:
            out["utilization"] = float(f"{util:.4g}")
        return out

    @staticmethod
    def assess(
        scrapes: Dict[str, ReplicaScrape],
        fleet_decoded_tokens: float = 0.0,
        fleet_resumed_tokens: float = 0.0,
        usage: Optional[Dict] = None,
    ) -> Dict:
        """Fleet capacity/goodput report over the latest generation.

        Goodput: `useful / (useful + waste)` where useful is the decode
        work delivered to completed requests (the usage ledger's decoded
        tokens — each token counted once, resumes excluded) and waste is
        (a) decode work the fleet performed beyond that (re-decoded
        after failover, preempted-then-discarded, shed mid-flight) plus
        (b) warmup decode work estimated from each replica's
        `work.warmup_batches x image_seq_len x max_batch`.
        """
        replicas = {
            name: CapacityModel.replica_assessment(s)
            for name, s in sorted(scrapes.items())
        }
        fresh = [r for r in replicas.values() if not r["stale"]]
        utils = [
            r["utilization"] for r in fresh if r.get("utilization") is not None
        ]
        mean_util = sum(utils) / len(utils) if utils else 0.0
        max_burn = max((r["slo_burn"] for r in fresh), default=0.0)

        num = CapacityModel._num
        warmup_tokens = 0.0
        for s in scrapes.values():
            health = s.health if isinstance(s.health, dict) else {}
            work = health.get("work") if isinstance(health.get("work"),
                                                    dict) else {}
            warmup_tokens += (
                (num(work.get("warmup_batches")) or 0.0)
                * (num(work.get("image_seq_len")) or 0.0)
                * (num(work.get("max_batch")) or 1.0)
            )
        useful = float(
            (usage or {}).get("totals", {}).get("decoded_tokens", 0)
        )
        wasted = max(0.0, fleet_decoded_tokens - useful) + warmup_tokens
        denom = useful + wasted
        goodput = useful / denom if denom > 0 else 1.0

        n = len(scrapes)
        suggested = n
        if n:
            if max_burn > 1.0 or mean_util > CapacityModel.UTIL_HIGH:
                suggested = n + 1
            elif (
                mean_util < CapacityModel.UTIL_LOW
                and max_burn == 0.0
                and n > 1
                and fresh
            ):
                suggested = n - 1
        return {
            "replicas": replicas,
            "fresh_replicas": len(fresh),
            "mean_utilization": float(f"{mean_util:.4g}"),
            "max_slo_burn": float(f"{max_burn:.4g}"),
            "goodput": {
                "useful_tokens": int(useful),
                "fleet_decoded_tokens": int(fleet_decoded_tokens),
                "fleet_resumed_tokens": int(fleet_resumed_tokens),
                "warmup_tokens": int(warmup_tokens),
                "wasted_tokens": int(wasted),
                "fraction": float(f"{goodput:.4g}"),
            },
            "suggested_replicas": suggested,
        }
