"""Fleet trace collector: joins spans from N processes into one trace.

The receiving half of `obs/aggregate.py`: every serving process (and the
bench client, and the future replica router) ships finished traces as
JSONL; the collector joins them on `trace_id` and assembles ONE merged
Perfetto trace per request — one track per process identity
(`site`+`host`+`pid`), cross-process parent edges rendered as flow
arrows — plus fleet-wide critical-path analytics.

Join semantics (the part distributed tracing systems get wrong first):

  * out-of-order — spans join by `trace_id` whenever they arrive; the
    server's half landing before the client's (or vice versa) assembles
    identically (test-pinned both ways);
  * duplicate — span records dedupe on `(process identity, run, span
    id)`, where `run` is the exporter's per-trace-instance nonce (an
    exporter retry that half-landed re-sends its batch with the SAME
    run → first copy wins, counted, never double-rendered — while a
    client RETRYING a request with the same x-dalle-trace header mints
    a fresh run, so the second attempt's spans are kept, not discarded
    as duplicates of the first);
  * late — a trace is `settling` until it has been idle for `grace_s`,
    then `sealed`; arrivals during settling merge silently, arrivals
    after sealing still merge (one trace, not two) but are counted in
    `late_spans` so a fleet with a slow exporter is visible;
  * bounded — at most `max_traces` bundles are retained, evicted
    oldest-first; a span for an evicted trace starts a fresh bundle
    (counted, documented, and harmless: the ring is sized for the
    debugging window, not for history).

Run it standalone:

    python -m dalle_pytorch_tpu.obs.collector --port 9500

or embed it in-process (bench/tests): construct `TraceCollector` and
call `ingest_lines` directly, or wrap it in a `CollectorServer` bound to
port 0.

HTTP surface (stdlib, same idioms as serving/server.py):

  POST /ingest         JSONL trace records -> {"accepted": n, "rejected": m}
  GET  /traces         merged Perfetto trace_event JSON of retained
                       traces; `?trace_id=` exact lookup (404 once
                       evicted), `?n=` most recent n
  GET  /critical_path  fleet-wide per-stage p50/p95 + dominant-critical-
                       path stage attribution (`?n=` bounds the window)
  GET  /healthz        {"status": "ok", ...ingest counters...}
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Tuple


def _percentile(values: List[float], q: float) -> Optional[float]:
    if not values:
        return None
    ordered = sorted(values)
    return ordered[min(len(ordered) - 1, max(0, int(q * len(ordered))))]


def _span_uid(proc_info: Dict, sid: int) -> str:
    """Collector-side reconstruction of a producer's span UID from an
    ingested record's identity fields — built by the SAME
    `aggregate.span_uid_for` every producer uses, so the join format
    cannot drift."""
    from dalle_pytorch_tpu.obs.aggregate import span_uid_for

    return span_uid_for(
        proc_info["site"], proc_info["host"], proc_info["pid"], sid
    )


class _Bundle:
    """All spans seen so far for one trace_id, across processes."""

    __slots__ = (
        "trace_id", "procs", "spans", "first_at", "last_at", "sealed",
        "late_spans",
    )

    def __init__(self, trace_id: str, now: float):
        self.trace_id = trace_id
        #: proc_key -> {"site", "host", "pid", "outcome", "parent_uid"}
        self.procs: Dict[str, Dict] = {}
        #: (proc_key, run, sid) -> span record (first copy wins)
        self.spans: Dict[Tuple[str, str, int], Dict] = {}
        self.first_at = now
        self.last_at = now
        self.sealed = False
        self.late_spans = 0

    def span_t0(self) -> Optional[float]:
        return min((s["t0"] for s in self.spans.values()), default=None)


# tracelint: threads
class TraceCollector:
    """Embeddable span-joining store + analytics (no sockets here; the
    HTTP face is `CollectorServer`). All methods are thread-safe: ingest
    runs on handler threads while exports read."""

    def __init__(self, grace_s: float = 2.0, max_traces: int = 512):
        self.grace_s = float(grace_s)
        self.max_traces = int(max_traces)
        self._lock = threading.Lock()
        self._bundles: "OrderedDict[str, _Bundle]" = OrderedDict()
        self.started_at = time.time()
        # ingest counters (healthz + tests)
        self.records_ingested = 0
        self.spans_ingested = 0
        self.duplicate_spans = 0
        self.late_spans = 0
        self.bad_records = 0
        self.bad_spans = 0
        self.traces_evicted = 0

    # -------------------------------------------------------------- ingest

    def ingest_lines(self, payload, now: Optional[float] = None) -> Dict:
        """Parse a JSONL payload (bytes/str/iterable of lines) and ingest
        every record. Malformed lines are counted, never fatal — one bad
        exporter must not poison the batch."""
        if isinstance(payload, bytes):
            payload = payload.decode("utf-8", errors="replace")
        if isinstance(payload, str):
            lines: Iterable[str] = payload.splitlines()
        else:
            lines = payload
        accepted = rejected = 0
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                rec = None
            if self.ingest_record(rec, now=now):
                accepted += 1
            else:
                rejected += 1
        return {"accepted": accepted, "rejected": rejected}

    def ingest_record(self, rec, now: Optional[float] = None) -> bool:
        """Join one exporter record into its trace bundle. `now` is a
        monotonic override for deterministic grace-window tests."""
        now = time.monotonic() if now is None else now
        if not isinstance(rec, dict):
            with self._lock:  # handler threads ingest concurrently
                self.bad_records += 1
            return False
        trace_id = rec.get("trace_id")
        site = rec.get("site")
        spans = rec.get("spans")
        if (
            not isinstance(trace_id, str) or not trace_id
            or not isinstance(site, str) or not site
            or not isinstance(spans, list)
        ):
            with self._lock:
                self.bad_records += 1
            return False
        pid = rec.get("pid", 0)
        host = rec.get("host", "")
        run = rec.get("run")
        run = run if isinstance(run, str) else ""
        proc_key = f"{site}@{host}:{pid}"
        with self._lock:
            bundle = self._bundles.get(trace_id)
            if bundle is None:
                bundle = _Bundle(trace_id, now)
                self._bundles[trace_id] = bundle
                while len(self._bundles) > self.max_traces:
                    self._bundles.popitem(last=False)
                    self.traces_evicted += 1
            elif not bundle.sealed and now - bundle.last_at >= self.grace_s:
                # targeted O(1) seal check of THIS bundle only (a full
                # sweep per record is O(max_traces) inside the lock per
                # line of a batch); reads and sweep() still seal the rest
                bundle.sealed = True
            was_sealed = bundle.sealed
            proc = bundle.procs.setdefault(proc_key, {
                "site": site, "host": host, "pid": pid,
                "outcome": None, "parent_uid": None,
            })
            if rec.get("outcome") is not None:
                proc["outcome"] = rec["outcome"]
            if rec.get("parent_uid") is not None:
                proc["parent_uid"] = rec["parent_uid"]
            merged = 0
            for s in spans:
                if not isinstance(s, dict):
                    self.bad_spans += 1
                    continue
                sid = s.get("sid")
                t0, t1 = s.get("t0"), s.get("t1")
                if (
                    not isinstance(sid, int)
                    or not isinstance(s.get("name"), str)
                    or not isinstance(t0, (int, float))
                    or not isinstance(t1, (int, float))
                ):
                    self.bad_spans += 1
                    continue
                key = (proc_key, run, sid)
                if key in bundle.spans:
                    self.duplicate_spans += 1
                    continue
                parent = s.get("parent")
                bundle.spans[key] = {
                    "sid": sid,
                    "run": run,
                    "parent": parent if isinstance(parent, int) else None,
                    "name": s["name"],
                    "t0": float(t0),
                    "t1": float(t1),
                    "args": s.get("args") if isinstance(s.get("args"), dict)
                    else {},
                    "proc": proc_key,
                }
                merged += 1
            if was_sealed and merged:
                # one trace, not two — but a post-grace arrival means an
                # exporter is lagging the window; make that visible
                bundle.late_spans += merged
                self.late_spans += merged
            bundle.last_at = now
            self.records_ingested += 1
            self.spans_ingested += merged
        return True

    # --------------------------------------------------------- grace window

    def _sweep_locked(self, now: float) -> int:
        sealed = 0
        for bundle in self._bundles.values():
            if not bundle.sealed and now - bundle.last_at >= self.grace_s:
                bundle.sealed = True
                sealed += 1
        return sealed

    def sweep(self, now: Optional[float] = None) -> int:
        """Seal every bundle idle past the grace window; returns how many
        sealed this call. Runs implicitly on ingest and reads — public
        for deterministic tests."""
        with self._lock:
            return self._sweep_locked(
                time.monotonic() if now is None else now
            )

    # -------------------------------------------------------------- queries

    @staticmethod
    def _snapshot_locked(bundle: _Bundle) -> _Bundle:
        """Read-consistent clone (caller holds the lock): the containers
        are copied, the span records shared — they are never mutated
        after insertion. Exporters iterate the clone while ingest keeps
        mutating the live bundle on handler threads."""
        snap = _Bundle(bundle.trace_id, bundle.first_at)
        snap.procs = {k: dict(v) for k, v in bundle.procs.items()}
        snap.spans = dict(bundle.spans)
        snap.last_at = bundle.last_at
        snap.sealed = bundle.sealed
        snap.late_spans = bundle.late_spans
        return snap

    def _select(self, trace_id: Optional[str], n: Optional[int],
                now: Optional[float] = None) -> List[_Bundle]:
        with self._lock:
            self._sweep_locked(time.monotonic() if now is None else now)
            if trace_id is not None:
                bundle = self._bundles.get(trace_id)
                return (
                    [self._snapshot_locked(bundle)]
                    if bundle is not None else []
                )
            bundles = list(self._bundles.values())
            if n is not None:
                bundles = bundles[-n:]
            return [self._snapshot_locked(b) for b in bundles]

    def find(self, trace_id: str) -> Optional[_Bundle]:
        """LIVE bundle reference (existence probes, single-threaded test
        introspection) — concurrent-safe iteration goes through the
        exporters, which read `_select`'s snapshots."""
        with self._lock:
            return self._bundles.get(trace_id)

    def __len__(self) -> int:
        with self._lock:
            return len(self._bundles)

    def reset(self) -> None:
        """Drop every bundle (bench: analytics over the measured window
        only). Counters keep accumulating — they are process-lifetime."""
        with self._lock:
            self._bundles.clear()

    # ------------------------------------------------------ perfetto export

    def trace_events(self, trace_id: Optional[str] = None,
                     n: Optional[int] = None) -> Dict:
        """Merged Chrome/Perfetto `trace_event` JSON: per trace, one
        synthetic Perfetto process PER EXPORTING PROCESS (named
        `site (host:pid)`), every span a `ph:"X"` event on its process's
        track, and a flow arrow (`ph:"s"`/`ph:"f"`) from each propagated
        parent span to the remote root it parented — the header hop is
        visible in the UI, not just in args. Timestamps are microseconds
        from the bundle's earliest span."""
        events: List[Dict] = []
        pid_counter = 0
        flow_id = 0
        for bundle in self._select(trace_id, n):
            base = bundle.span_t0() or 0.0
            # stable track order: processes by their earliest span, so
            # the caller (bench client / router) renders above the
            # servers it fanned into
            proc_first: Dict[str, float] = {}
            for span in bundle.spans.values():
                k = span["proc"]
                proc_first[k] = min(proc_first.get(k, span["t0"]), span["t0"])
            proc_pids: Dict[str, int] = {}
            uid_to_span: Dict[str, Dict] = {}
            # bucket once per bundle — the inner loop must not re-sort
            # the whole span dict per process (hundreds of chunk spans
            # per continuous trace, on the endpoint's hot path)
            by_proc: Dict[str, List[Tuple[int, Dict]]] = {}
            for (pk, _run, sid), span in sorted(bundle.spans.items()):
                by_proc.setdefault(pk, []).append((sid, span))
            for proc_key in sorted(proc_first, key=proc_first.get):
                pid_counter += 1
                proc_pids[proc_key] = pid_counter
                info = bundle.procs[proc_key]
                events.append({
                    "ph": "M", "name": "process_name",
                    "pid": pid_counter, "tid": 1,
                    "args": {"name": f"{info['site']} "
                             f"({info['host']}:{info['pid']})"},
                })
                for sid, span in by_proc.get(proc_key, ()):
                    uid = _span_uid(info, sid)
                    uid_to_span[uid] = span
                    events.append({
                        "name": span["name"],
                        "cat": "fleet",
                        "ph": "X",
                        "ts": round((span["t0"] - base) * 1e6, 1),
                        "dur": round((span["t1"] - span["t0"]) * 1e6, 1),
                        "pid": pid_counter,
                        "tid": 1,
                        "args": {
                            "trace_id": bundle.trace_id,
                            "uid": uid,
                            **span["args"],
                        },
                    })
            # cross-process parent edges: proc root -> remote parent span
            for proc_key, info in bundle.procs.items():
                parent_uid = info.get("parent_uid")
                parent = uid_to_span.get(parent_uid) if parent_uid else None
                if parent is None:
                    continue
                roots = [
                    s for (pk, _, _), s in bundle.spans.items()
                    if pk == proc_key and s["parent"] is None
                ]
                if not roots:
                    continue
                child_root = min(roots, key=lambda s: s["t0"])
                flow_id += 1
                ts = round((child_root["t0"] - base) * 1e6, 1)
                events.append({
                    "ph": "s", "id": flow_id, "name": "propagate",
                    "cat": "fleet", "pid": proc_pids[parent["proc"]],
                    "tid": 1, "ts": max(
                        round((parent["t0"] - base) * 1e6, 1), 0.0
                    ),
                })
                events.append({
                    "ph": "f", "bp": "e", "id": flow_id, "name": "propagate",
                    "cat": "fleet", "pid": proc_pids[proc_key], "tid": 1,
                    "ts": ts,
                })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    # ----------------------------------------------------------- analytics

    @staticmethod
    def _leaves(bundle: _Bundle) -> List[Dict]:
        """Spans with no children — the stage spans. Parents (per-process
        roots, enclosing request spans) cover their children's time and
        would double-count."""
        has_child = set()
        uid_of = {}
        for (pk, run, sid), span in bundle.spans.items():
            uid_of[(pk, run, sid)] = _span_uid(bundle.procs[pk], sid)
        uids = set(uid_of.values())
        for (pk, run, sid), span in bundle.spans.items():
            if span["parent"] is not None:
                # parent linkage is within one trace INSTANCE: a retry's
                # spans parent among themselves, never across attempts
                has_child.add((pk, run, span["parent"]))
        for pk, info in bundle.procs.items():
            parent_uid = info.get("parent_uid")
            if parent_uid and parent_uid in uids:
                for key, uid in uid_of.items():
                    if uid == parent_uid:
                        has_child.add(key)
        return [
            span for key, span in bundle.spans.items()
            if key not in has_child
        ]

    @staticmethod
    def _critical_cover(root_t0: float, root_t1: float,
                        leaves: List[Dict]) -> Dict[str, float]:
        """Greedy interval cover of the request window by leaf spans: at
        each point pick the already-started span reaching furthest, and
        attribute the covered stretch to its stage. Gaps (host time no
        span claims) are attributed to "(untraced)" so the percentages
        always total the end-to-end latency."""
        out: Dict[str, float] = {}
        spans = sorted(
            (s for s in leaves if s["t1"] > root_t0 and s["t0"] < root_t1),
            key=lambda s: s["t0"],
        )
        t = root_t0
        i = 0
        started: List[Tuple[float, str]] = []  # (t1, name) candidates
        while t < root_t1:
            while i < len(spans) and spans[i]["t0"] <= t:
                started.append((spans[i]["t1"], spans[i]["name"]))
                i += 1
            started = [(t1, nm) for t1, nm in started if t1 > t]
            if started:
                t1, nm = max(started)
                end = min(t1, root_t1)
                out[nm] = out.get(nm, 0.0) + (end - t)
                t = end
            elif i < len(spans):
                gap_end = min(spans[i]["t0"], root_t1)
                out["(untraced)"] = out.get("(untraced)", 0.0) + (gap_end - t)
                t = gap_end
            else:
                out["(untraced)"] = out.get("(untraced)", 0.0) + (root_t1 - t)
                break
        return out

    def critical_path(self, n: Optional[int] = None,
                      trace_id: Optional[str] = None) -> Dict:
        """Fold assembled traces into fleet-wide per-stage latency and
        dominant-critical-path attribution:

          * `stages`: per-trace stage TOTALS (all leaf spans of that
            name summed — many chunk spans count once per trace), with
            fleet p50/p95/mean over traces that saw the stage;
          * `critical_path.attributed_ms`: per-stage time ON the greedy
            critical cover of each trace's end-to-end window;
          * `critical_path.dominant`: per stage, how many traces (and
            what fraction) had that stage as their largest critical-path
            contributor — "where does the fleet's latency live".
        """
        stage_totals: Dict[str, List[float]] = {}
        crit_totals: Dict[str, List[float]] = {}
        dominant: Dict[str, int] = {}
        bundles = self._select(trace_id, n)
        traced = 0
        for bundle in bundles:
            if not bundle.spans:
                continue
            traced += 1
            leaves = self._leaves(bundle)
            per_stage: Dict[str, float] = {}
            for s in leaves:
                per_stage[s["name"]] = (
                    per_stage.get(s["name"], 0.0) + (s["t1"] - s["t0"])
                )
            for name, total in per_stage.items():
                stage_totals.setdefault(name, []).append(total)
            roots = [s for s in bundle.spans.values() if s["parent"] is None]
            root = min(roots or bundle.spans.values(), key=lambda s: s["t0"])
            if not leaves:
                continue
            # the attribution window runs root-start -> LAST LEAF end
            # (clamped by the root): a client that finishes its trace
            # late — the bench harvests completions after the whole
            # arrival replay — must not smear an artificial untraced
            # tail over the cover; for a server trace the respond leaf
            # ends at the root anyway, so the clamp is a no-op
            window_end = min(root["t1"], max(s["t1"] for s in leaves))
            cover = self._critical_cover(root["t0"], window_end, leaves)
            for name, covered in cover.items():
                crit_totals.setdefault(name, []).append(covered)
            if cover:
                top = max(cover.items(), key=lambda kv: kv[1])[0]
                dominant[top] = dominant.get(top, 0) + 1

        def pct_block(values: List[float]) -> Dict:
            return {
                "count": len(values),
                "p50_ms": round(1000.0 * _percentile(values, 0.5), 3),
                "p95_ms": round(1000.0 * _percentile(values, 0.95), 3),
                "mean_ms": round(1000.0 * sum(values) / len(values), 3),
            }

        return {
            "traces": traced,
            "stages": {
                name: pct_block(vals)
                for name, vals in sorted(stage_totals.items())
            },
            "critical_path": {
                "attributed_ms": {
                    name: pct_block(vals)
                    for name, vals in sorted(crit_totals.items())
                },
                "dominant": {
                    name: {
                        "traces": count,
                        "fraction": round(count / traced, 3),
                    }
                    for name, count in sorted(
                        dominant.items(), key=lambda kv: -kv[1]
                    )
                },
            },
        }

    # -------------------------------------------------------------- status

    def stats(self) -> Dict:
        with self._lock:
            self._sweep_locked(time.monotonic())
            sealed = sum(1 for b in self._bundles.values() if b.sealed)
            total = len(self._bundles)
            # counters are bumped under the lock by concurrent ingest
            # handler threads — snapshot them coherently here too
            counters = {
                "records_ingested": self.records_ingested,
                "spans_ingested": self.spans_ingested,
                "duplicate_spans": self.duplicate_spans,
                "late_spans": self.late_spans,
                "bad_records": self.bad_records,
                "bad_spans": self.bad_spans,
                "traces_evicted": self.traces_evicted,
            }
        return {
            "traces": total,
            "sealed": sealed,
            "settling": total - sealed,
            "grace_s": self.grace_s,
            "max_traces": self.max_traces,
            **counters,
        }


# --------------------------------------------------------------- HTTP face


#: ingest batches are many traces x many spans; far roomier than the
#: serving server's prompt bound, still finite
MAX_INGEST_BYTES = 32 << 20


def _build_handler():
    """Handler class built lazily inside CollectorServer so embedding a
    bare TraceCollector never touches http.server."""
    from http.server import BaseHTTPRequestHandler
    from urllib.parse import parse_qs

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        timeout = 120

        def log_message(self, fmt, *args):
            if self.server.owner.verbose:
                super().log_message(fmt, *args)

        def _reply(self, code: int, payload: dict) -> None:
            body = json.dumps(payload, default=str).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if code >= 400:
                # undrained request bytes must not corrupt keep-alive
                self.send_header("Connection", "close")
                self.close_connection = True
            self.end_headers()
            try:
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError):
                pass

        def do_POST(self):
            collector = self.server.owner.collector
            path = self.path.partition("?")[0]
            if path != "/ingest":
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                if not 0 < length <= MAX_INGEST_BYTES:
                    raise ValueError(f"bad Content-Length {length}")
            except ValueError as exc:
                self._reply(400, {"error": f"bad request: {exc}"})
                return
            body = self.rfile.read(length)
            self._reply(200, collector.ingest_lines(body))

        def do_GET(self):
            collector = self.server.owner.collector
            path, _, query = self.path.partition("?")
            params = parse_qs(query)
            n_param = params.get("n", [None])[0]
            try:
                n = None if n_param is None else int(n_param)
                if n is not None and n <= 0:
                    raise ValueError(n)
            except ValueError:
                self._reply(400, {"error": "n must be a positive integer"})
                return
            trace_id = params.get("trace_id", [None])[0]
            if path == "/traces":
                if trace_id is not None and collector.find(trace_id) is None:
                    self._reply(404, {
                        "error": f"trace {trace_id} not retained "
                        "(evicted or never ingested)"
                    })
                    return
                self._reply(200, collector.trace_events(trace_id, n))
            elif path == "/critical_path":
                self._reply(200, collector.critical_path(n, trace_id))
            elif path == "/healthz":
                self._reply(200, {"status": "ok", **collector.stats()})
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

    return Handler


class CollectorServer:
    """Stdlib HTTP wrapper around a TraceCollector (the `python -m`
    service, and the in-process collector bench/tests bind to port 0).
    Same lifecycle shape as ServingServer: `start()` serves on a daemon
    thread, `serve_forever()` blocks for the CLI, `shutdown()` closes."""

    def __init__(
        self,
        collector: Optional[TraceCollector] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        verbose: bool = False,
        grace_s: float = 2.0,
        max_traces: int = 512,
    ):
        from http.server import ThreadingHTTPServer

        self.collector = (
            collector if collector is not None
            else TraceCollector(grace_s=grace_s, max_traces=max_traces)
        )
        self.verbose = verbose

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._httpd = _Server((host, port), _build_handler())
        self._httpd.owner = self
        self._thread: Optional[threading.Thread] = None
        self._state_lock = threading.Lock()
        self._serving = False
        self._closed = False

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host = self._httpd.server_address[0]
        return f"http://{host}:{self.port}"

    def start(self) -> "CollectorServer":
        assert self._thread is None, "already started"
        with self._state_lock:
            assert not self._closed, "collector already shut down"
            self._serving = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.05},
            name="dalle-collector-http",
            daemon=True,
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        assert self._thread is None, "already started in background"
        with self._state_lock:
            if self._closed:
                return
            self._serving = True
        self._httpd.serve_forever(poll_interval=0.05)

    def shutdown(self) -> None:
        with self._state_lock:
            first_close = not self._closed
            self._closed = True
            serving = self._serving
        if serving:
            self._httpd.shutdown()
            self._serving = False
        if first_close:
            self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None


def main(argv=None) -> int:
    import argparse
    import signal
    import sys as _sys

    p = argparse.ArgumentParser(
        description="fleet trace collector (see obs/collector.py)"
    )
    p.add_argument("--host", type=str, default="127.0.0.1")
    p.add_argument("--port", type=int, default=9500,
                   help="0 picks a free port")
    p.add_argument("--grace_s", type=float, default=2.0,
                   help="idle seconds before a trace seals (late spans "
                   "after that still merge, but are counted)")
    p.add_argument("--max_traces", type=int, default=512,
                   help="retained trace bound; evicted oldest-first")
    p.add_argument("--verbose", action="store_true", help="HTTP access logs")
    args = p.parse_args(argv)

    server = CollectorServer(
        host=args.host, port=args.port, verbose=args.verbose,
        grace_s=args.grace_s, max_traces=args.max_traces,
    )

    def _stop(signum, frame):
        # shutdown() joins the serve loop; run it off the main thread
        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGINT, _stop)
    signal.signal(signal.SIGTERM, _stop)
    # parseable readiness line, like serve.py's
    print(f"[collector] listening on http://{args.host}:{server.port} "
          f"(grace_s={args.grace_s}, max_traces={args.max_traces})",
          flush=True)
    server.serve_forever()
    print("[collector] shutdown complete", flush=True)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())

