"""Cross-process trace export: context codec + background span shipper.

PR 5's tracer is strictly single-process — a trace lives and dies in one
server's ring buffer, and a fleet (N engine replicas behind a router,
each replica a sharded mesh) debugs through CROSS-process request traces
or not at all (Vortex-style serving stacks and pjit/TPUv4-scale
deployments, PAPERS.md). Two pieces live here; the service that joins
them is `obs/collector.py`:

  * the `x-dalle-trace` context codec — `format_trace_header` /
    `parse_trace_header`. The header is `<trace_id>/<parent_uid>`:
    `trace_id` is the fleet-wide join key (16-hex, minted at the FIRST
    ingress — a bench client, the future replica router, or a server
    that saw no header), `parent_uid` the globally-unique reference
    (`site:host:pid:span_id`) of the caller's span that the receiving
    process's root span parents into. Parsing is strict and total:
    anything malformed returns None and the receiver mints a fresh
    context — a hostile or corrupted header can never poison the
    collector's join key space.

  * `TraceExporter` — a per-process background thread that ships
    finished traces to the collector as batched JSONL over HTTP
    (`POST /ingest`). The serving-path contract is absolute: a request
    thread's `Trace.finish()` does ONE bounded-deque append (oldest
    trace dropped, counted in `dalle_obs_export_dropped_total`, when the
    buffer is full) and never blocks, serializes, or touches a socket —
    all of that happens on the exporter thread, behind exponential
    backoff while the collector is down or slow. Serving is therefore
    provably unaffected by collector health (test-pinned: every request
    completes, memory stays bounded at `max_buffer` traces, drops are
    counted). With no exporter attached the tracer holds the shared
    `NULL_EXPORTER` no-op, so the off path is counter-gated
    zero-allocation exactly like NULL_TRACE.

Span wire schema (one JSON object per trace, one line per object):

    {"schema": 1, "trace_id": str, "site": str, "pid": int, "host": str,
     "run": str, "outcome": str|null, "parent_uid": str|null,
     "spans": [{"sid": int, "parent": int|null, "name": str,
                "t0": unix_s, "t1": unix_s, "args": {...}}]}

(`run` is a per-trace-instance nonce: the collector dedupes exporter
retries on it without discarding a client RETRY that legitimately
reuses its x-dalle-trace header.)

Timestamps are unix seconds (`Tracer.to_unix`), so the collector can
order spans from N processes on one axis; cross-host skew is NTP-grade,
which is fine for stage attribution and honest about ordering.
"""

from __future__ import annotations

import json
import os
import re
import socket
import threading
import time
import urllib.request
from collections import deque
from typing import Dict, List, Optional, Tuple

from dalle_pytorch_tpu.obs.tracing import Span, Trace

#: the one propagation header; lowercase (http.server title-cases lookups
#: case-insensitively, clients should send it as-is)
TRACE_HEADER = "x-dalle-trace"

SCHEMA_VERSION = 1

_TRACE_ID_RE = re.compile(r"^[0-9a-f]{8,32}$")
_SPAN_UID_RE = re.compile(r"^[A-Za-z0-9_.:\-]{1,128}$")


def format_trace_header(trace_id: str, parent_uid: Optional[str] = None) -> str:
    """`x-dalle-trace` value for an outbound hop: the trace ID alone, or
    `<trace_id>/<parent_uid>` when the caller has a span for the callee's
    root to parent into."""
    return trace_id if parent_uid is None else f"{trace_id}/{parent_uid}"


def parse_trace_header(value) -> Optional[Tuple[str, Optional[str]]]:
    """Parse an inbound `x-dalle-trace` header -> (trace_id, parent_uid).

    Total and strict: None (mint a fresh context) for a missing header,
    a non-hex trace ID, an over-long or character-escaping span UID —
    the join key space of the whole fleet collector rides on this, so
    garbage is rejected rather than propagated."""
    if not value or not isinstance(value, str):
        return None
    trace_id, sep, parent_uid = value.strip().partition("/")
    if not _TRACE_ID_RE.match(trace_id):
        return None
    if not sep:
        return trace_id, None
    if not _SPAN_UID_RE.match(parent_uid):
        return None
    return trace_id, parent_uid


def sanitize_site(site: str) -> str:
    """Clamp a site name to the span-UID alphabet (no '/', no spaces,
    no ':') so minted UIDs always round-trip through the header codec —
    an unparseable parent_uid would silently disable cross-process
    joining fleet-wide, with zero diagnostics at either end."""
    return re.sub(r"[^A-Za-z0-9_.\-]", "-", str(site))[:64] or "proc"


def default_site() -> str:
    """Stable default process site name: the DALLE_TRACE_SITE env, else
    the hostname, sanitized."""
    return sanitize_site(
        os.environ.get("DALLE_TRACE_SITE") or socket.gethostname() or "proc"
    )


def span_uid_for(site: str, host: str, pid: int, span_id: int) -> str:
    """THE span-UID identity format (`site:host:pid:span_id`) — the one
    definition every producer (TraceExporter, the replica router) and
    the collector's join reconstruction share, so the format cannot
    drift between them (a drift silently stops parent edges resolving
    fleet-wide, with zero diagnostics). Host is part of the identity:
    two containerized replicas sharing a site both run as pid 1."""
    return f"{site}:{host}:{pid}:{span_id}"


class TraceExporter:
    """Background JSONL shipper from one process's tracer to a collector.

    `TraceExporter(url, site=...).attach(tracer)` starts the thread and
    hooks `Tracer._record`; every finished trace is enqueued (O(1),
    bounded) and shipped in batches of up to `max_batch` traces per POST.
    Transport failures retry with exponential backoff (`backoff_s`
    doubling to `backoff_max_s`, reset on success); the unsent batch goes
    back to the FRONT of the buffer so arrival order survives a retry,
    and whatever the bound then evicts is dropped oldest-first with a
    counter. `stop()` is called at server shutdown and makes one final
    best-effort flush (bounded by the transport timeout).

    The `_post` seam is the only socket touch — tests stub it for
    deterministic backoff/overflow coverage, and `flush()` drives the
    same `_flush_once` the thread runs for synchronous draining.
    """

    def __init__(
        self,
        url: str,
        site: Optional[str] = None,
        registry=None,
        max_buffer: int = 256,
        max_batch: int = 64,
        flush_interval_s: float = 0.5,
        backoff_s: float = 0.5,
        backoff_max_s: float = 30.0,
        timeout_s: float = 2.0,
        thread: bool = True,
    ):
        self.url = str(url).rstrip("/")
        self.site = sanitize_site(site) if site else default_site()
        self.pid = os.getpid()
        # sanitized like site: the host rides inside span UIDs, which
        # must stay within the header codec's alphabet
        self.host = sanitize_site(socket.gethostname() or "localhost")
        self.max_buffer = int(max_buffer)
        self.max_batch = int(max_batch)
        self.flush_interval_s = float(flush_interval_s)
        self.backoff_base_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.timeout_s = float(timeout_s)
        #: thread=False skips the shipper thread entirely — intake and
        #: overflow accounting run unchanged on export(), and callers
        #: drive delivery synchronously via `flush()`/`_flush_once()`.
        #: The deterministic mode tests use so their timing budgets
        #: never ride on thread-scheduling under CPU contention.
        self._thread_enabled = bool(thread)
        self.enabled = True
        self._buf: deque = deque()
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._tracer = None
        # batches popped from the buffer but not yet posted/re-queued:
        # flush() must wait these out — "buffer empty" alone races the
        # shipper thread mid-POST and under-reports delivery
        self._inflight_batches = 0
        # live state the tests (and /debug introspection) read
        self.spans_serialized = 0
        self.traces_sent = 0
        self.posts_sent = 0
        self.dropped = 0
        self.retries = 0
        self.consecutive_failures = 0
        self.current_backoff_s = 0.0
        self.last_error: Optional[str] = None
        self._m_dropped = self._m_sent = self._m_retries = None
        if registry is not None:
            self._m_dropped = registry.counter(
                "dalle_obs_export_dropped_total",
                "finished traces dropped because the export buffer was "
                "full (collector down/slow; serving is unaffected)",
            )
            self._m_sent = registry.counter(
                "dalle_obs_export_traces_total",
                "finished traces shipped to the trace collector",
            )
            self._m_retries = registry.counter(
                "dalle_obs_export_retries_total",
                "export POST failures (each schedules a backoff retry)",
            )

    def __bool__(self) -> bool:
        return True

    # ------------------------------------------------------------ identity

    def span_uid(self, span: Span) -> str:
        """Globally-unique reference for one of THIS process's spans —
        what an outbound `x-dalle-trace` header carries as parent_uid and
        what the collector joins against (`span_uid_for`, the shared
        format definition)."""
        return span_uid_for(self.site, self.host, self.pid, span.span_id)

    def context_header(self, trace: Trace, span: Span) -> str:
        """Ready-to-send `x-dalle-trace` value parenting the callee's
        root into `span` of `trace`."""
        return format_trace_header(trace.trace_id, self.span_uid(span))

    # ----------------------------------------------------------- lifecycle

    def attach(self, tracer) -> "TraceExporter":
        """Hook a tracer's finish path and start the shipper thread."""
        self._tracer = tracer
        tracer.exporter = self
        if self._thread is None and self._thread_enabled:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="dalle-trace-export", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, final_flush: bool = True) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout_s + 5.0)
            self._thread = None
        if final_flush:
            # best-effort FULL drain, not one batch: stop on the first
            # transport failure (a dead collector costs exactly one POST
            # timeout) and bound the healthy-path drain by a deadline so
            # a slow collector cannot wedge shutdown either
            deadline = time.monotonic() + max(self.timeout_s * 4, 5.0)
            while self.buffered and time.monotonic() < deadline:
                if not self._flush_once():
                    break
        if self._tracer is not None and self._tracer.exporter is self:
            from dalle_pytorch_tpu.obs.tracing import NULL_EXPORTER

            self._tracer.exporter = NULL_EXPORTER

    # -------------------------------------------------------------- intake

    def export(self, trace: Trace) -> None:
        """Called from `Trace.finish()` on request threads: ONE bounded
        append, never a socket, never serialization — the serving path
        must be unaffected however sick the collector is."""
        with self._lock:
            if len(self._buf) >= self.max_buffer:
                self._buf.popleft()  # oldest out: fresh traces win
                self.dropped += 1
                if self._m_dropped is not None:
                    self._m_dropped.inc()
            self._buf.append(trace)
            full_batch = len(self._buf) >= self.max_batch
        if full_batch:
            # wake early only when a full batch is ready; otherwise the
            # interval tick ships the partial batch. Waking per trace
            # would turn a 50 req/s replica into 50 POSTs/s of
            # single-trace batches — the batching exists to keep
            # collector socket churn proportional to batches, not
            # fleet request rate.
            self._wake.set()

    @property
    def buffered(self) -> int:
        with self._lock:
            return len(self._buf)

    # ------------------------------------------------------------ shipping

    def serialize_trace(self, trace: Trace) -> Dict:
        """One wire record for one finished trace (exporter thread only).
        `closed_spans()` is the tracer's consistent snapshot; finish()
        already closed every span (abandoned ones included), so the
        snapshot is total for any trace that reaches the exporter."""
        tracer = trace._tracer
        # per-trace-INSTANCE nonce, minted lazily (only exporter-attached
        # traces pay) and cached so an exporter retry re-sends the same
        # value: the collector dedupes on (process, run, sid). Without
        # it, a client retrying a timed-out request with the SAME
        # x-dalle-trace header against the same server would have the
        # second attempt's spans discarded as duplicates of the first
        # (both attempts' span ids start at 0).
        run = getattr(trace, "_export_run", None)
        if run is None:
            import uuid

            run = uuid.uuid4().hex[:8]
            try:
                trace._export_run = run
            except AttributeError:  # exotic trace stand-ins: ship uncached
                pass
        spans: List[Dict] = []
        for s in trace.closed_spans():
            spans.append({
                "sid": s.span_id,
                "parent": s.parent_id,
                "name": s.name,
                "t0": round(tracer.to_unix(s.t0), 6),
                "t1": round(tracer.to_unix(s.t1), 6),
                "args": s.args,
            })
        with self._lock:  # flush() callers run concurrently with the
            self.spans_serialized += len(spans)  # shipper thread
        return {
            "schema": SCHEMA_VERSION,
            "trace_id": trace.trace_id,
            "site": self.site,
            "pid": self.pid,
            "host": self.host,
            "run": run,
            "outcome": trace.outcome,
            "parent_uid": trace.parent_uid,
            "spans": spans,
        }

    def _post(self, body: bytes) -> None:
        """The one socket touch (stubbed in tests): POST the JSONL batch
        to the collector's /ingest. Raises on any transport failure."""
        req = urllib.request.Request(
            self.url + "/ingest",
            data=body,
            headers={"Content-Type": "application/x-ndjson"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            resp.read()

    def _flush_once(self) -> bool:
        """Ship one batch. True when the batch landed (or nothing was
        buffered); False schedules a backoff in the thread loop."""
        with self._lock:
            n = min(len(self._buf), self.max_batch)
            batch = [self._buf.popleft() for _ in range(n)]
            if batch:
                self._inflight_batches += 1
        if not batch:
            return True
        try:
            # default=str like StructuredLog for odd scalar types, plus a
            # per-trace guard for what default= cannot rescue (circular
            # refs): one poisoned trace drops WITH a counter instead of
            # killing the batch — or worse, the shipper thread
            lines, shippable = [], []
            for t in batch:
                try:
                    lines.append(
                        json.dumps(self.serialize_trace(t), default=str)
                    )
                    shippable.append(t)
                except Exception as exc:
                    with self._lock:
                        self.last_error = repr(exc)
                        self.dropped += 1
                    if self._m_dropped is not None:
                        self._m_dropped.inc()
            if not lines:
                return True
            body = ("\n".join(lines) + "\n").encode("utf-8")
            try:
                self._post(body)
            except Exception as exc:
                if self._m_retries is not None:
                    self._m_retries.inc()
                # bookkeeping + requeue under ONE lock hold: flush()
                # callers race the shipper thread on every counter here,
                # and the backoff derivation must read its own increment
                with self._lock:
                    self.last_error = repr(exc)
                    self.retries += 1
                    self.consecutive_failures += 1
                    self.current_backoff_s = min(
                        self.backoff_base_s
                        * (2 ** (self.consecutive_failures - 1)),
                        self.backoff_max_s,
                    )
                    # unsent batch back to the FRONT (arrival order
                    # survives the retry); the bound still holds —
                    # overflow drops oldest-first
                    for trace in reversed(shippable):
                        self._buf.appendleft(trace)
                    dropped_now = 0
                    while len(self._buf) > self.max_buffer:
                        self._buf.popleft()
                        self.dropped += 1
                        dropped_now += 1
                if dropped_now and self._m_dropped is not None:
                    self._m_dropped.inc(dropped_now)
                return False
        finally:
            with self._lock:
                self._inflight_batches -= 1
        with self._lock:
            self.consecutive_failures = 0
            self.current_backoff_s = 0.0
            self.last_error = None
            self.traces_sent += len(shippable)
            self.posts_sent += 1
        if self._m_sent is not None:
            self._m_sent.inc(len(shippable))
        return True

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.flush_interval_s)
            self._wake.clear()
            while not self._stop.is_set():
                try:
                    ok = self._flush_once()
                except Exception as exc:
                    # belt and braces: the shipper thread must NEVER die
                    # — a dead shipper silently turns every future trace
                    # into an overflow drop for the process lifetime
                    self.last_error = repr(exc)
                    break
                if not ok:
                    # backoff on the STOP event so shutdown never waits
                    # out a 30s backoff window
                    self._stop.wait(self.current_backoff_s)
                    continue
                if self.buffered == 0:
                    break

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Synchronously drain the buffer AND wait out batches the
        shipper thread already holds (bench/tests): True only when
        everything enqueued so far has been delivered. Drives the same
        `_flush_once` the thread runs — concurrent calls are safe,
        batches just interleave."""
        deadline = time.monotonic() + float(timeout_s)
        while time.monotonic() < deadline:
            done = self._flush_once()
            with self._lock:
                idle = not self._buf and self._inflight_batches == 0
            if done and idle:
                return True
            time.sleep(min(0.05, self.current_backoff_s or 0.05))
        with self._lock:
            return not self._buf and self._inflight_batches == 0

    # ------------------------------------------------------------- detail

    def detail(self) -> Dict:
        with self._lock:
            # counters are mutated under the lock from both the shipper
            # thread and export() callers — snapshot them coherently
            # (self.buffered would re-acquire the non-reentrant lock, so
            # read the buffer length directly here)
            counters = {
                "buffered": len(self._buf),
                "traces_sent": self.traces_sent,
                "spans_serialized": self.spans_serialized,
                "dropped": self.dropped,
                "retries": self.retries,
                "consecutive_failures": self.consecutive_failures,
            }
        return {
            "url": self.url,
            "site": self.site,
            "pid": self.pid,
            "host": self.host,
            "max_buffer": self.max_buffer,
            **counters,
            "current_backoff_s": self.current_backoff_s,
            "last_error": self.last_error,
        }
