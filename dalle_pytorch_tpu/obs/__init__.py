"""Request-scoped observability for the serving stack.

The debugging surface production LM serving systems are tuned off
(Orca's iteration-level scheduling, vLLM's continuous batching) is the
per-request stage breakdown: where did THIS request's latency go — the
queue, the prefill wave, a slow decode chunk, the harvest? Aggregate
Prometheus counters can't answer that; these modules can:

  * `tracing.py`  — `Span`/`Trace`/`Tracer`: a lock-safe in-process span
    pipeline. Trace IDs are minted at HTTP ingress and propagated through
    the batcher worker's admit→prefill→chunk→retire loop, recording
    per-stage wall time plus dispatch metadata (wave size, chunk index,
    compile events via `utils/compile_guard`). Finished traces land in a
    bounded ring buffer and export as Chrome/Perfetto `trace_event` JSON
    (`GET /debug/traces`, `serve.py --trace-dump`). A disabled tracer is
    zero-overhead: every call returns shared null singletons and the
    `spans_created` counter stays at zero (pinned by test).
  * `logging.py`  — `StructuredLog`: one JSON line per completed request
    (trace ID + stage breakdown + outcome) and lifecycle events,
    replacing ad-hoc prints in the serving path.
  * `profiler.py` — `ProfilerCapture`: on-demand `jax.profiler` capture
    behind `POST /debug/profile?seconds=N` (root-gated, single-flight,
    writes a TensorBoard trace dir) so a TPU hotspot can be captured
    from a live server without a restart.
  * `aggregate.py` — fleet trace export: the `x-dalle-trace` context
    codec (header parsed at POST /generate ingress, minted if absent, so
    a bench client's or router's span parents the server's root) and the
    `TraceExporter` background thread shipping finished traces as
    batched JSONL to a collector — bounded buffer, exponential backoff,
    drop-with-counter, NULL_EXPORTER zero-overhead when off, serving
    provably unaffected by collector health.
  * `collector.py` — the stitching `TraceCollector` service
    (`python -m dalle_pytorch_tpu.obs.collector`, embeddable
    in-process): joins spans from N processes on trace_id (out-of-order/
    duplicate/late tolerated via a grace window), assembles ONE merged
    Perfetto trace per request with one track per process identity
    (`GET /traces`), and folds traces into fleet-wide per-stage p50/p95
    + dominant-critical-path attribution (`GET /critical_path`).
  * `vitals.py`   — device telemetry and self-diagnosis: per-program
    `ProgramCostTable` (XLA cost/memory analysis captured at warmup →
    live MFU/bandwidth gauges and `GET /debug/programs`), the
    `EngineVitals` background sampler (`GET /debug/vitals` time-series),
    the `StallWatchdog` (stuck dispatch / stale queue head / frozen
    decode → structured `stall` events with a full `/debug/state` dump
    and worker stacks), and the `SLOTracker` (declarative latency
    targets, rolling-window burn rate, the /healthz `degraded` tier).

Stage timings also feed the `dalle_serving_stage_seconds{stage=}`
histogram family (`training/metrics.py`), so `/metrics` and the traces
agree on where the time went.
"""

from dalle_pytorch_tpu.obs.tracing import (
    NULL_EXPORTER,
    NULL_TRACE,
    Span,
    Trace,
    Tracer,
)
from dalle_pytorch_tpu.obs.aggregate import (
    TRACE_HEADER,
    TraceExporter,
    format_trace_header,
    parse_trace_header,
)
from dalle_pytorch_tpu.obs.collector import CollectorServer, TraceCollector
from dalle_pytorch_tpu.obs.logging import StructuredLog
from dalle_pytorch_tpu.obs.profiler import ProfilerBusy, ProfilerCapture
from dalle_pytorch_tpu.obs.vitals import (
    NULL_VITALS,
    EngineVitals,
    ProgramCostTable,
    SLOTarget,
    SLOTracker,
    StallWatchdog,
)

__all__ = [
    "CollectorServer",
    "EngineVitals",
    "NULL_EXPORTER",
    "NULL_TRACE",
    "NULL_VITALS",
    "ProfilerBusy",
    "ProfilerCapture",
    "ProgramCostTable",
    "SLOTarget",
    "SLOTracker",
    "Span",
    "StallWatchdog",
    "StructuredLog",
    "TRACE_HEADER",
    "Trace",
    "TraceCollector",
    "TraceExporter",
    "Tracer",
    "format_trace_header",
    "parse_trace_header",
]
