"""Request-scoped observability for the serving stack.

The debugging surface production LM serving systems are tuned off
(Orca's iteration-level scheduling, vLLM's continuous batching) is the
per-request stage breakdown: where did THIS request's latency go — the
queue, the prefill wave, a slow decode chunk, the harvest? Aggregate
Prometheus counters can't answer that; these modules can:

  * `tracing.py`  — `Span`/`Trace`/`Tracer`: a lock-safe in-process span
    pipeline. Trace IDs are minted at HTTP ingress and propagated through
    the batcher worker's admit→prefill→chunk→retire loop, recording
    per-stage wall time plus dispatch metadata (wave size, chunk index,
    compile events via `utils/compile_guard`). Finished traces land in a
    bounded ring buffer and export as Chrome/Perfetto `trace_event` JSON
    (`GET /debug/traces`, `serve.py --trace-dump`). A disabled tracer is
    zero-overhead: every call returns shared null singletons and the
    `spans_created` counter stays at zero (pinned by test).
  * `logging.py`  — `StructuredLog`: one JSON line per completed request
    (trace ID + stage breakdown + outcome) and lifecycle events,
    replacing ad-hoc prints in the serving path.
  * `profiler.py` — `ProfilerCapture`: on-demand `jax.profiler` capture
    behind `POST /debug/profile?seconds=N` (root-gated, single-flight,
    writes a TensorBoard trace dir) so a TPU hotspot can be captured
    from a live server without a restart.
  * `vitals.py`   — device telemetry and self-diagnosis: per-program
    `ProgramCostTable` (XLA cost/memory analysis captured at warmup →
    live MFU/bandwidth gauges and `GET /debug/programs`), the
    `EngineVitals` background sampler (`GET /debug/vitals` time-series),
    the `StallWatchdog` (stuck dispatch / stale queue head / frozen
    decode → structured `stall` events with a full `/debug/state` dump
    and worker stacks), and the `SLOTracker` (declarative latency
    targets, rolling-window burn rate, the /healthz `degraded` tier).

Stage timings also feed the `dalle_serving_stage_seconds{stage=}`
histogram family (`training/metrics.py`), so `/metrics` and the traces
agree on where the time went.
"""

from dalle_pytorch_tpu.obs.tracing import NULL_TRACE, Span, Trace, Tracer
from dalle_pytorch_tpu.obs.logging import StructuredLog
from dalle_pytorch_tpu.obs.profiler import ProfilerBusy, ProfilerCapture
from dalle_pytorch_tpu.obs.vitals import (
    NULL_VITALS,
    EngineVitals,
    ProgramCostTable,
    SLOTarget,
    SLOTracker,
    StallWatchdog,
)

__all__ = [
    "EngineVitals",
    "NULL_TRACE",
    "NULL_VITALS",
    "ProfilerBusy",
    "ProfilerCapture",
    "ProgramCostTable",
    "SLOTarget",
    "SLOTracker",
    "Span",
    "StallWatchdog",
    "StructuredLog",
    "Trace",
    "Tracer",
]
