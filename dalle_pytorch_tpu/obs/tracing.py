"""Lock-safe in-process span tracer with Perfetto export.

One `Trace` per served request, minted at HTTP ingress and carried on the
`GenRequest` through the batcher worker, so every stage of a request's
life — queue wait, prefill wave, each decode chunk, harvest, response
encoding — is a `Span` on one tree. Spans record wall time plus dispatch
metadata (wave size, chunk index) and, because `utils/compile_guard`
counts backend compilations process-wide, the number of XLA compiles that
happened while the span was open (`compiles=` arg; attribution is
process-wide, same caveat as `assert_no_recompiles`).

Threading model: a trace is written by exactly two threads — the HTTP
handler (root + respond spans) and the single batcher worker (queue end,
prefill/chunk/harvest) — never concurrently on the same span. The spans
list is guarded by a per-trace lock; the finished-trace ring buffer by the
tracer's lock. Span begin/end themselves are just monotonic-clock reads
and attribute stores.

Zero-overhead-when-off is a hard contract (pinned by test): a disabled
tracer returns the shared `NULL_TRACE` singleton from `start_trace`, whose
`begin`/`end`/`span`/`finish` are no-ops returning the shared `NULL_SPAN`
— no allocation per token, per chunk, or per request. `Tracer.
spans_created` counts every real Span constructed, so the contract is
guarded by a counter, not timing.

Export is Chrome/Perfetto `trace_event` JSON (the "JSON Array Format" /
`traceEvents` object both chrome://tracing and ui.perfetto.dev load):
one complete (`ph: "X"`) event per closed span, one synthetic track per
trace so concurrent requests render as parallel rows.

Fleet hooks (obs/aggregate.py): a tracer may carry a `TraceExporter` that
ships every finished trace to a cross-process collector. The default is
the shared `NULL_EXPORTER` no-op — same counter-gated zero-overhead
contract as NULL_TRACE — so a tracer without `--trace_export` pays one
attribute load per finished trace and allocates nothing. `start_trace`
accepts an externally-minted `trace_id` plus a `parent_uid` (the
`x-dalle-trace` header's parse) so spans from N processes join on one ID
and the remote caller's span parents this process's root.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import uuid
from collections import deque
from pathlib import Path
from typing import Dict, Iterator, List, Optional

from dalle_pytorch_tpu.utils import compile_guard


class Span:
    """One timed stage. `args` carries dispatch metadata into the export."""

    __slots__ = ("name", "span_id", "parent_id", "t0", "t1", "args", "_c0")

    def __init__(self, name: str, span_id: int, parent_id: Optional[int],
                 args: Dict):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = time.monotonic()
        self.t1: Optional[float] = None
        self.args = args
        self._c0 = compile_guard.compile_count()

    @property
    def closed(self) -> bool:
        return self.t1 is not None

    @property
    def duration_s(self) -> float:
        return (self.t1 if self.t1 is not None else time.monotonic()) - self.t0


class _NullSpan:
    """Shared do-nothing span: the disabled-tracer (and error-path) stand-in.
    Also a context manager so `with trace.span(...)` costs nothing off."""

    __slots__ = ()
    name = ""
    closed = True
    duration_s = 0.0
    args: Dict = {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class _NullTrace:
    """Shared do-nothing trace. Falsy, so call sites can gate extra work
    (exemplar lookups, log fields) with a plain `if trace:`."""

    __slots__ = ()
    trace_id = ""
    outcome = None
    parent_uid = None
    spans: List = []

    def __bool__(self) -> bool:
        return False

    def begin(self, name, **args):
        return NULL_SPAN

    def end(self, span, **args) -> None:
        pass

    def span(self, name, **args):
        return NULL_SPAN

    def finish(self, outcome="ok", **args) -> None:
        pass

    def stage_seconds(self) -> Dict[str, float]:
        return {}

    def complete(self) -> bool:
        return True

    @property
    def duration_s(self) -> float:
        return 0.0


class _NullExporter:
    """Shared no-op exporter: the off path of cross-process trace export
    (obs/aggregate.py:TraceExporter). Counter-gated like NULL_TRACE — a
    tracer without an exporter attached serializes zero spans and buffers
    zero traces, whatever traffic flows past it."""

    __slots__ = ()
    enabled = False
    spans_serialized = 0
    dropped = 0

    def __bool__(self) -> bool:
        return False

    def export(self, trace) -> None:
        pass


NULL_SPAN = _NullSpan()
NULL_TRACE = _NullTrace()
NULL_EXPORTER = _NullExporter()


class Trace:
    """A request's span tree. Constructed via `Tracer.start_trace`; the
    root span opens immediately and closes at `finish()`."""

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 args: Dict, parent_uid: Optional[str] = None):
        self._tracer = tracer
        self.trace_id = trace_id
        #: globally-unique span reference of the REMOTE span this trace's
        #: root parents into (parsed off the x-dalle-trace header); the
        #: exporter ships it so the collector stitches the cross-process
        #: tree. None for locally-minted traces.
        self.parent_uid = parent_uid
        self._lock = threading.Lock()
        self._next_id = 0
        self.spans: List[Span] = []
        self.outcome: Optional[str] = None
        self.root = self._new_span(name, None, args)

    def _new_span(self, name: str, parent_id: Optional[int],
                  args: Dict) -> Span:
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            span = Span(name, sid, parent_id, args)
            self.spans.append(span)
        self._tracer._count_span()
        return span

    # ------------------------------------------------------------- spans

    def begin(self, name: str, **args) -> Span:
        """Open a child span of the root. Explicit begin/end (rather than
        only a context manager) because serving stages cross threads: the
        queue span begins in the HTTP handler and ends in the worker."""
        return self._new_span(name, self.root.span_id, args)

    def end(self, span: Span, **args) -> None:
        if span is NULL_SPAN:
            return
        t1 = time.monotonic()
        dc = compile_guard.compile_count() - span._c0
        # close under the trace lock: finish() on the HTTP thread (a
        # timed-out request being abandoned) can race the worker's own
        # end() of the same still-open span — first closer wins, the
        # loser's args are dropped whole. t1 is the publication point:
        # exporters treat a non-None t1 as "this span is frozen", so
        # every args mutation lands before it.
        with self._lock:
            if span.closed:
                return
            if dc > 0:
                # process-wide attribution, like compile_guard itself: a
                # compile on another thread during the span counts too
                span.args["compiles"] = dc
            if args:
                span.args.update(args)
            span.t1 = t1

    @contextlib.contextmanager
    def span(self, name: str, **args) -> Iterator[Span]:
        s = self.begin(name, **args)
        try:
            yield s
        finally:
            self.end(s)

    # ----------------------------------------------------------- finish

    def finish(self, outcome: str = "ok", **args) -> None:
        """Close the trace and push it into the tracer's ring buffer.
        Any span left open (error paths abandon spans mid-stage) is
        closed here so exported traces are always complete."""
        with self._lock:
            if self.outcome is not None:
                return  # finish is one-shot; late double-finishes are no-ops
            self.outcome = outcome
            open_spans = [s for s in self.spans if not s.closed]
        for s in open_spans:
            if s is not self.root:
                self.end(s, abandoned=True)
        self.end(self.root, outcome=outcome, **args)
        self._tracer._record(self)

    # ------------------------------------------------------------ views

    def complete(self) -> bool:
        with self._lock:
            return self.outcome is not None and all(
                s.closed for s in self.spans
            )

    def closed_spans(self) -> List[Span]:
        """Consistent snapshot for exporters: the spans list is copied
        under the trace lock, and only frozen (closed) spans are
        returned — a worker can still be opening/closing late spans on a
        finished trace (e.g. rows of a 504'd request still decoding)."""
        with self._lock:
            return [s for s in self.spans if s.closed]

    def stage_seconds(self) -> Dict[str, float]:
        """Total seconds per stage name (closed non-root spans, summed —
        a request sees one queue span but many chunk spans)."""
        out: Dict[str, float] = {}
        with self._lock:
            spans = list(self.spans)
        for s in spans:
            if s is self.root or not s.closed:
                continue
            out[s.name] = out.get(s.name, 0.0) + s.duration_s
        return out

    @property
    def duration_s(self) -> float:
        return self.root.duration_s


# tracelint: threads
class Tracer:
    """Mints traces, owns the finished-trace ring buffer, exports Perfetto.

    `max_traces` bounds memory: a long-lived server keeps only the most
    recent N request traces, not one per request forever. A trace's size
    scales with its span count — continuous decode opens one chunk span
    per dispatched chunk, so a small-`chunk_tokens` config over long
    image sequences holds hundreds of spans per trace; size `max_traces`
    (and use `/debug/traces?n=`) accordingly.
    """

    def __init__(self, enabled: bool = True, max_traces: int = 256):
        self.enabled = bool(enabled)
        self._ring: deque = deque(maxlen=int(max_traces))
        self._lock = threading.Lock()
        #: real Span objects constructed through this tracer — the
        #: zero-overhead-when-off contract is `spans_created == 0` for a
        #: disabled tracer, whatever traffic flowed past it
        self.spans_created = 0
        #: cross-process export hook (obs/aggregate.py:TraceExporter);
        #: the shared no-op singleton until one attaches itself
        self.exporter = NULL_EXPORTER
        # paired epoch reads: monotonic timestamps convert to unix wall
        # clock for the fleet collector, which must order spans from N
        # processes on one axis (to_unix). Skew between hosts is the
        # usual NTP-grade caveat, stated in the collector docs.
        self._epoch_mono = time.monotonic()
        self._epoch_unix = time.time()
        if self.enabled:
            try:  # per-span compile attribution needs the jax.monitoring
                compile_guard.install_listener()  # listener; optional —
            except Exception:  # without jax, compile counts just stay 0
                pass

    # ------------------------------------------------------------ minting

    def start_trace(self, name: str = "request", trace_id: Optional[str] = None,
                    parent_uid: Optional[str] = None, **args):
        """Mint a trace. `trace_id`/`parent_uid` carry a propagated
        x-dalle-trace context (validated by the caller —
        `aggregate.parse_trace_header` is the gate); both default to a
        locally-minted root context."""
        if not self.enabled:
            return NULL_TRACE
        return Trace(
            self, name, trace_id or uuid.uuid4().hex[:16], args,
            parent_uid=parent_uid,
        )

    def to_unix(self, t_mono: float) -> float:
        """Monotonic span timestamp -> unix seconds (the exporter's wire
        time base; mutually consistent within this process)."""
        return self._epoch_unix + (t_mono - self._epoch_mono)

    def _count_span(self) -> None:
        with self._lock:
            self.spans_created += 1

    def _record(self, trace: Trace) -> None:
        with self._lock:
            self._ring.append(trace)
        # outside the ring lock: export() is a bounded-deque append (or
        # the shared no-op) and must never couple to the tracer lock
        self.exporter.export(trace)

    # ------------------------------------------------------------- views

    def recent(self, n: Optional[int] = None) -> List[Trace]:
        """Most recent finished traces, oldest first."""
        with self._lock:
            traces = list(self._ring)
        return traces if n is None else traces[-n:]

    def find(self, trace_id: str) -> Optional[Trace]:
        """Exact-ID lookup in the retained ring (newest first — a reused
        ID, which uuid4 makes cosmically unlikely, resolves to the most
        recent trace). None once evicted: the ring is bounded, and the
        HTTP layer turns that into a 404 rather than pretending."""
        with self._lock:
            for trace in reversed(self._ring):
                if trace.trace_id == trace_id:
                    return trace
        return None

    def __len__(self) -> int:
        return len(self._ring)

    # ------------------------------------------------------------ export

    def trace_events(self, n: Optional[int] = None,
                     traces: Optional[List[Trace]] = None) -> Dict:
        """Chrome/Perfetto `trace_event` JSON object for the ring buffer.

        One `ph: "X"` (complete) event per closed span; each trace gets
        its own synthetic thread id plus a `thread_name` metadata event,
        so concurrent requests render as parallel tracks with the trace
        ID as the row label. Timestamps are microseconds since the
        tracer's epoch (Perfetto only needs them mutually consistent).
        `traces` overrides the ring selection (the `?trace_id=` exact
        lookup exports a single trace through the same serializer).
        """
        pid = os.getpid()
        events: List[Dict] = []
        selected = self.recent(n) if traces is None else traces
        for tid, trace in enumerate(selected, start=1):
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": f"req {trace.trace_id}"},
            })
            for s in trace.closed_spans():
                events.append({
                    "name": s.name,
                    "cat": "serving",
                    "ph": "X",
                    "ts": round((s.t0 - self._epoch_mono) * 1e6, 1),
                    "dur": round((s.t1 - s.t0) * 1e6, 1),
                    "pid": pid,
                    "tid": tid,
                    "args": {"trace_id": trace.trace_id, **s.args},
                })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def dump(self, path) -> Path:
        """Write the ring buffer as a Perfetto-loadable JSON file."""
        out = Path(path)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(self.trace_events()), encoding="utf-8")
        return out
