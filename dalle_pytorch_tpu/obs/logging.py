"""Structured JSON logging for the serving path.

One JSON object per line (JSONL on a stream, stdout by default): a
`request` line per completed request — trace ID, outcome, HTTP status,
latency, per-stage breakdown — plus free-form lifecycle `event` lines
(warmup, shutdown, profile captures). This replaces ad-hoc prints in the
serving path with lines an aggregator can parse; the one human-first
exception is `serve.py`'s `[serve] listening on ...` readiness line,
which orchestrators (and the e2e tests) pattern-match.

Request-line schema (keys always present):

    {"ts": <unix seconds>, "event": "request", "trace_id": str,
     "site": str, "pid": int, "host": str,
     "outcome": "ok" | "rejected" | "timeout" | "cancelled" | "error"
               | "shutdown",
     "status": <http code>, "latency_ms": float,
     "stages": {"queue": ms, "prefill": ms, "chunk": ms, ...}}

plus whatever extra fields the caller attaches (prompt length, rows,
seed, error text). `stages` is empty when tracing is disabled — the log
line still records outcome and latency.

Every line — request lines AND lifecycle events (so watchdog `stall`
records too) — carries the stable process identity triple `site`/`pid`/
`host` (`serve.py --trace_site`; site defaults to the hostname): fleet
logs from N replicas merge into one stream and join against the
collector's assembled traces by trace_id without guessing which process
wrote what.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time
from typing import Dict, Optional


# tracelint: threads
class StructuredLog:
    """Thread-safe JSONL writer. Failures to write never raise into the
    serving path (a closed pipe must not fail a request).

    File-backed mode (`path=`) adds size-capped rotation: once the file
    exceeds `max_mb`, it is renamed to `<path>.1` (replacing any prior
    one — keep-one policy, so disk use is bounded at ~2x the cap) and a
    fresh file is started. Rotation failures are swallowed like write
    failures: a long-lived replica must not fail a request over its own
    log housekeeping."""

    def __init__(self, stream=None, component: str = "dalle.serving",
                 site: Optional[str] = None, path: Optional[str] = None,
                 max_mb: Optional[float] = None):
        from dalle_pytorch_tpu.obs.aggregate import default_site, sanitize_site

        assert stream is None or path is None, (
            "pass a stream OR a file path, not both"
        )
        self._path = str(path) if path is not None else None
        self._max_bytes = (
            int(float(max_mb) * 1024 * 1024)
            if max_mb is not None and self._path is not None else None
        )
        if self._path is not None:
            stream = open(self._path, "a", encoding="utf-8")
        self._stream = stream if stream is not None else sys.stdout
        self._component = component
        self._lock = threading.Lock()
        # stamped once: identity must be STABLE across every line this
        # process writes, or downstream joins fracture mid-run —
        # sanitized through the SAME clamp as TraceExporter so log lines
        # and exported traces carry one identical site string
        self._identity = {
            "site": sanitize_site(site) if site else default_site(),
            "pid": os.getpid(),
            # host through the same clamp as TraceExporter.host, or log
            # lines and span UIDs would disagree on long/odd hostnames
            "host": sanitize_site(socket.gethostname() or "localhost"),
        }

    def _rotate_locked(self) -> None:
        """Caller holds the lock. Rename the full file to `<path>.1`
        (keep one) and start fresh; any failure leaves the current
        stream writable and is retried implicitly at the next cap
        crossing."""
        try:
            self._stream.close()
        except (ValueError, OSError):
            pass
        try:
            os.replace(self._path, self._path + ".1")
        except OSError:
            pass  # rename failed: reopen appends to the oversized file
        try:
            self._stream = open(self._path, "a", encoding="utf-8")
        except OSError:
            # can't reopen (dir vanished?): swallow writes from now on
            # rather than raise into the request path
            self._stream = None

    def _emit(self, record: Dict) -> None:
        record = {**self._identity, **record}
        line = json.dumps(record, default=str)
        try:
            with self._lock:
                if self._stream is None:
                    return
                self._stream.write(line + "\n")
                self._stream.flush()
                if (
                    self._max_bytes is not None
                    and self._stream.tell() >= self._max_bytes
                ):
                    self._rotate_locked()
        except (ValueError, OSError):
            pass  # stream closed mid-shutdown; the request already succeeded

    def event(self, event: str, **fields) -> None:
        """Free-form lifecycle line (warmup, listening, shutdown, ...)."""
        self._emit({
            "ts": round(time.time(), 3),
            "component": self._component,
            "event": event,
            **fields,
        })

    def request(
        self,
        trace_id: str,
        outcome: str,
        status: int,
        latency_ms: float,
        stages: Optional[Dict[str, float]] = None,
        **fields,
    ) -> None:
        """One line per completed (or failed) request."""
        self._emit({
            "ts": round(time.time(), 3),
            "component": self._component,
            "event": "request",
            "trace_id": trace_id,
            "outcome": outcome,
            "status": int(status),
            "latency_ms": round(float(latency_ms), 2),
            "stages": {
                k: round(v * 1000.0, 2) for k, v in (stages or {}).items()
            },
            **fields,
        })
