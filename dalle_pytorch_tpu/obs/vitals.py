"""Device telemetry and self-diagnosis for the serving engines.

PR 5's span pipeline answers "where did THIS request's wall time go";
this module answers the capacity question underneath it — "is the device
healthy and well-utilized" — with four cooperating pieces:

  * `ProgramCostTable` — per-program XLA cost/memory accounting. During
    engine warmup every program in the compiled ladder (prefill, chunk,
    release, pixel decode, the micro sampler rungs, the paged variants)
    is AOT-lowered and `compiled.cost_analysis()` + `memory_analysis()`
    are captured: FLOPs, bytes accessed, argument/temp/output HBM.
    Combined with measured dispatch wall time (EMA) this yields live
    model-FLOPs-utilization and achieved-bandwidth gauges per program
    (`dalle_serving_mfu{program=}`, `dalle_serving_hbm_gbps{program=}`)
    — the same roofline arithmetic as `scripts/hbm_model.py` /
    `scripts/flash_crossover.py`, which import `extract_cost` and the
    peak constants from here so offline and live accounting cannot
    drift. Capture costs ONE extra backend compile per program at warmup
    (JAX's AOT path does not share the jit dispatch cache — measured),
    which is why it is opt-in via `engine.cost_table`. Mesh-sharded
    engines pass their device labels at capture: where jax exposes
    per-partition analysis the row gains a per-device block
    (`GET /debug/programs?per_shard=1`,
    `dalle_serving_mfu{program=,device=}`); the global row is the
    documented fallback everywhere else.

  * `EngineVitals` — a background sampler thread snapshotting queue
    depth, slots/blocks active, prefix-cache occupancy, the age of the
    dispatch currently in flight, and `device.memory_stats()` (when the
    backend provides it; per-device across the engine's mesh for the
    sharded engine, rolled up into one payload + a
    `dalle_serving_hbm_bytes{device=}` gauge per shard) into a bounded
    ring, exported as
    `GET /debug/vitals` JSON time-series plus `/metrics` gauges. The
    device seam (`_device_memory_stats`) is an overridable hook so tests
    stub it. Zero-overhead-when-off is a counter-gated contract like the
    tracer's: a disabled `EngineVitals` never starts its thread and
    `samples_taken` stays 0; engines talk to `NULL_VITALS` (shared no-op
    singleton) unless a real instance is bound.

  * `StallWatchdog` — runs on the sampler's tick. Three detectors: a
    dispatch whose in-flight age exceeds an EMA-based multiple of that
    program's typical wall time; a queue head older than its budget; and
    zero decode progress (chunk index frozen) with slots active. A
    detection emits one structured `stall` JSONL event carrying the full
    engine-state dump (`/debug/state`: slot table, page tables +
    refcounts, queue summary, in-flight trace IDs) and a worker-thread
    Python stack capture, bumps `dalle_serving_stalls_total{reason=}`,
    and marks /healthz degraded. A cooldown per reason keeps a long
    stall from flooding the log.

  * `SLOTracker` — declarative latency targets (serve.py
    `--slo_ttft_ms` / `--slo_request_ms`) with rolling-window burn rate
    computed from the EXISTING stage/latency histograms: each tick diffs
    cumulative bucket counts, so no per-request bookkeeping is added to
    the hot path. Burn rate = observed violation fraction / allowed
    error budget; > 1 means the budget is burning and /healthz reports
    `"status": "degraded"` (still 200 — a router should shed load, not
    pull the replica).

Everything here reads host-side state only (allocator counts, numpy page
tables, monotonic clocks); nothing in the sampler path can trigger an
XLA compile — pinned, like the tracer, by a serve-cycle-under-
`assert_no_recompiles` test with all of it enabled.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from dalle_pytorch_tpu.utils import compile_guard

# v5e roofline anchors, shared with scripts/hbm_model.py and
# scripts/flash_crossover.py (import from here, don't re-declare)
V5E_PEAK_FLOPS = 197e12
V5E_HBM_BPS = 819e9  # ~819 GB/s


def extract_cost(compiled) -> Dict[str, float]:
    """`compiled.cost_analysis()` as one flat dict, across jax versions
    (older jax returns `[dict]`). The shared extraction helper for this
    module and the offline roofline scripts."""
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost or {})


_MEMORY_FIELDS = (
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "temp_size_in_bytes",
    "alias_size_in_bytes",
    "generated_code_size_in_bytes",
)


def _memory_fields(mem) -> Dict[str, int]:
    out = {}
    for field in _MEMORY_FIELDS:
        v = getattr(mem, field, None)
        if v is not None:
            out[field] = int(v)
    return out


def extract_memory(compiled) -> Dict[str, int]:
    """`compiled.memory_analysis()` HBM footprint fields as a plain dict
    (empty when the backend doesn't implement it). A per-shard list
    (some jax versions report one entry per partition) collapses to its
    first entry here — `extract_memory_per_device` keeps the split."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return {}
    if mem is None:
        return {}
    if isinstance(mem, (list, tuple)):
        mem = mem[0] if mem else None
        if mem is None:
            return {}
    return _memory_fields(mem)


def extract_cost_per_device(compiled) -> Optional[List[Dict[str, float]]]:
    """Per-partition cost dicts when jax exposes them — a
    `cost_analysis()` returning MULTIPLE entries is read as one entry
    per mesh device. The common shape (one global entry for the whole
    partitioned program) returns None and callers fall back to the
    global row; that fallback IS the contract, not an error."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return None
    if (
        isinstance(cost, (list, tuple)) and len(cost) > 1
        and all(isinstance(c, dict) for c in cost)
    ):
        return [dict(c) for c in cost]
    return None


def extract_memory_per_device(compiled) -> Optional[List[Dict[str, int]]]:
    """Per-partition memory dicts where `memory_analysis()` reports one
    entry per device; None (fall back to the global row) otherwise."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return None
    if isinstance(mem, (list, tuple)) and len(mem) > 1:
        return [_memory_fields(m) for m in mem]
    return None


def thread_stacks(name_contains: str = "batcher") -> Dict[str, List[str]]:
    """Python stacks of live threads whose name matches, via
    `sys._current_frames()` — the watchdog's answer to "WHERE is the
    worker stuck". Host-side introspection only; safe on any thread."""
    frames = sys._current_frames()
    out: Dict[str, List[str]] = {}
    for t in threading.enumerate():
        if name_contains not in t.name:
            continue
        frame = frames.get(t.ident)
        if frame is not None:
            out[t.name] = [
                line.rstrip("\n")
                for line in traceback.format_stack(frame)
            ]
    return out


class _ProgramRow:
    """Static compile-time cost of one warmed program plus its measured
    dispatch-wall EMA."""

    __slots__ = (
        "name", "flops", "bytes_accessed", "memory", "wall_ema_s",
        "last_wall_s", "dispatches", "synced", "per_shard",
    )

    def __init__(self, name: str, flops: float, bytes_accessed: float,
                 memory: Dict[str, int]):
        self.name = name
        self.flops = float(flops)
        self.bytes_accessed = float(bytes_accessed)
        self.memory = memory
        #: device label -> {"flops", "bytes_accessed", "memory"} when jax
        #: exposed per-partition analysis at capture; None = global only
        self.per_shard: Optional[Dict[str, Dict]] = None
        self.wall_ema_s: Optional[float] = None
        self.last_wall_s: Optional[float] = None
        self.dispatches = 0
        #: False until a wall measurement that includes a device sync
        #: lands — MFU from an async dispatch's host-side wall would be
        #: fiction, so gauges only export once this is True
        self.synced = False


# tracelint: threads
class ProgramCostTable:
    """Compile-time cost registry + live MFU/bandwidth accounting.

    `capture(name, lower_fn)` AOT-compiles the program (one extra backend
    compile — warmup-time only; engines gate it on `_warmup`) and stores
    FLOPs / bytes-accessed / HBM footprint. `record_wall(name, seconds,
    synced=True)` feeds measured dispatch wall time into an EMA and, when
    a registry is attached, updates `dalle_serving_mfu{program=}` and
    `dalle_serving_hbm_gbps{program=}` — per-dispatch model-FLOPs-
    utilization and achieved bandwidth against the configured roofline.

    Wall times are only trusted for MFU when the measurement brackets a
    device sync (the chunk boundary's fused `device_get`, the micro
    sampler's `np.asarray`, the pixel decode's host copy); a pure
    dispatch wall (async prefill) keeps the row's static cost visible
    without exporting a bogus utilization number.
    """

    def __init__(
        self,
        peak_flops: float = V5E_PEAK_FLOPS,
        hbm_bps: float = V5E_HBM_BPS,
        registry=None,
        ema_alpha: float = 0.2,
    ):
        self.peak_flops = float(peak_flops)
        self.hbm_bps = float(hbm_bps)
        self.ema_alpha = float(ema_alpha)
        self._rows: Dict[str, _ProgramRow] = {}
        self._errors: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._m_mfu = self._m_bw = None
        if registry is not None:
            self._m_mfu = registry.gauge_family(
                "dalle_serving_mfu",
                "model-FLOPs-utilization of the most recent synced "
                "dispatches per compiled program (EMA wall vs roofline "
                "peak)",
                label_name="program",
            )
            self._m_bw = registry.gauge_family(
                "dalle_serving_hbm_gbps",
                "achieved HBM bandwidth (bytes accessed / EMA wall) per "
                "compiled program, GB/s",
                label_name="program",
            )

    # ------------------------------------------------------------ capture

    def has(self, name: str) -> bool:
        with self._lock:
            return name in self._rows

    def add(self, name: str, compiled, devices=None) -> None:
        """Register one already-compiled program's cost analysis.

        `devices` (the engine's mesh device labels, in mesh order) opts
        into per-shard attribution: where jax exposes per-partition
        cost/memory analysis (`extract_cost_per_device`), each device
        gets its own row — `GET /debug/programs?per_shard=1` and
        `dalle_serving_mfu{program=,device=}`. Everywhere else the
        global row stands alone, exactly as before."""
        cost = extract_cost(compiled)
        row = _ProgramRow(
            name,
            flops=float(cost.get("flops", 0.0)),
            bytes_accessed=float(cost.get("bytes accessed", 0.0)),
            memory=extract_memory(compiled),
        )
        if devices:
            per_cost = extract_cost_per_device(compiled)
            if per_cost is not None and len(per_cost) == len(devices):
                per_mem = extract_memory_per_device(compiled)
                if per_mem is None or len(per_mem) != len(devices):
                    per_mem = [{}] * len(devices)
                row.per_shard = {
                    str(dev): {
                        "flops": float(c.get("flops", 0.0)),
                        "bytes_accessed": float(
                            c.get("bytes accessed", 0.0)
                        ),
                        "memory": m,
                    }
                    for dev, c, m in zip(devices, per_cost, per_mem)
                }
                # with per-partition entries the program-level row is
                # their SUM (extract_cost's first entry would understate
                # the collective dispatch by ~1/num_devices)
                row.flops = sum(c["flops"] for c in row.per_shard.values())
                row.bytes_accessed = sum(
                    c["bytes_accessed"] for c in row.per_shard.values()
                )
        with self._lock:
            self._rows[name] = row
            self._errors.pop(name, None)

    def capture(self, name: str, lower_fn: Callable,
                devices=None) -> bool:
        """AOT-lower + compile via `lower_fn() -> jax.stages.Lowered` and
        record the program's cost. Failures are recorded, never raised —
        a backend without cost analysis must not break warmup."""
        if self.has(name):
            return True
        try:
            lowered = lower_fn()
            if lowered is None:  # eager-fallback sampler: nothing to lower
                return False
            self.add(name, lowered.compile(), devices=devices)
            return True
        except Exception as exc:
            with self._lock:
                self._errors[name] = repr(exc)
            return False

    def record_error(self, name: str, exc: BaseException) -> None:
        """Record a capture failure from a caller that did its own
        lower/compile (the engines' shared AOT ladder pre-compiles once
        and feeds both this table and the compile-cache export)."""
        with self._lock:
            self._errors[name] = repr(exc)

    # ---------------------------------------------------------- live wall

    def record_wall(self, name: str, seconds: float,
                    synced: bool = True) -> None:
        with self._lock:
            row = self._rows.get(name)
            if row is None:
                return
            row.dispatches += 1
            row.last_wall_s = float(seconds)
            row.wall_ema_s = (
                float(seconds) if row.wall_ema_s is None
                else (1 - self.ema_alpha) * row.wall_ema_s
                + self.ema_alpha * float(seconds)
            )
            row.synced = row.synced or bool(synced)
            export = row.synced and row.wall_ema_s > 0
            mfu = bw = None
            shard_stats = []
            if export:
                mfu = min(
                    1.0, row.flops / (row.wall_ema_s * self.peak_flops)
                )
                bw = row.bytes_accessed / row.wall_ema_s / 1e9
                if row.per_shard:
                    # the dispatch is collective — every shard shares the
                    # program wall; per-device MFU divides each shard's
                    # OWN flops by it, so a lopsided partition shows up
                    # as one hot device, not a fleet average
                    shard_stats = [
                        (
                            dev,
                            min(1.0, c["flops"]
                                / (row.wall_ema_s * self.peak_flops)),
                            c["bytes_accessed"] / row.wall_ema_s / 1e9,
                        )
                        for dev, c in row.per_shard.items()
                    ]
        if export:
            if self._m_mfu is not None:
                self._m_mfu.labels(name).set(mfu)
                for dev, s_mfu, _ in shard_stats:
                    self._m_mfu.labels_extra(name, device=dev).set(s_mfu)
            if self._m_bw is not None:
                self._m_bw.labels(name).set(bw)
                for dev, _, s_bw in shard_stats:
                    self._m_bw.labels_extra(name, device=dev).set(s_bw)

    def mfu(self, name: str) -> Optional[float]:
        with self._lock:
            row = self._rows.get(name)
        if row is None or not row.synced or not row.wall_ema_s:
            return None
        return min(1.0, row.flops / (row.wall_ema_s * self.peak_flops))

    # ------------------------------------------------------------- export

    def rows(self, per_shard: bool = False) -> List[Dict]:
        """JSON-ready rows for `GET /debug/programs`. `per_shard=True`
        adds a per-mesh-device block to programs whose capture exposed
        per-partition analysis (the `?per_shard=1` query); programs with
        only the global row render unchanged — the documented fallback."""
        with self._lock:
            rows = list(self._rows.values())
            errors = dict(self._errors)
        out = []
        for r in rows:
            ai = r.flops / r.bytes_accessed if r.bytes_accessed else None
            row = {
                "program": r.name,
                "flops": r.flops,
                "bytes_accessed": r.bytes_accessed,
                "arithmetic_intensity": round(ai, 2) if ai else None,
                "memory": r.memory,
                "dispatches": r.dispatches,
            }
            live = r.wall_ema_s is not None
            if live:
                row["wall_ema_ms"] = round(r.wall_ema_s * 1e3, 3)
                row["wall_includes_sync"] = r.synced
                if r.synced and r.wall_ema_s > 0:
                    # significant figures, not decimal places: a toy CPU
                    # engine's honest MFU is ~1e-7 and must not render 0
                    mfu = min(1.0, r.flops / (r.wall_ema_s * self.peak_flops))
                    row["mfu"] = float(f"{mfu:.4g}")
                    row["hbm_gbps"] = float(
                        f"{r.bytes_accessed / r.wall_ema_s / 1e9:.4g}"
                    )
            if per_shard and r.per_shard:
                shards = []
                for dev, c in r.per_shard.items():
                    shard = {
                        "device": dev,
                        "flops": c["flops"],
                        "bytes_accessed": c["bytes_accessed"],
                        "memory": c["memory"],
                    }
                    if live and r.synced and r.wall_ema_s > 0:
                        s_mfu = min(
                            1.0,
                            c["flops"] / (r.wall_ema_s * self.peak_flops),
                        )
                        shard["mfu"] = float(f"{s_mfu:.4g}")
                        shard["hbm_gbps"] = float(
                            f"{c['bytes_accessed'] / r.wall_ema_s / 1e9:.4g}"
                        )
                    shards.append(shard)
                row["per_shard"] = shards
            out.append(row)
        for name, err in errors.items():
            out.append({"program": name, "error": err})
        return out

    def detail(self, per_shard: bool = False) -> Dict:
        return {
            "peak_flops": self.peak_flops,
            "hbm_bps": self.hbm_bps,
            "programs": self.rows(per_shard=per_shard),
        }


class _NullVitals:
    """Shared no-op stand-in engines hold by default: dispatch-clock calls
    in the hot path cost one attribute lookup and nothing else, and no
    object is ever allocated (the tracer's NULL_TRACE pattern)."""

    __slots__ = ()
    enabled = False
    samples_taken = 0

    def __bool__(self) -> bool:
        return False

    def dispatch_begin(self, name: str) -> None:
        pass

    def dispatch_end(self, name: str, seconds: float) -> None:
        pass


NULL_VITALS = _NullVitals()


# tracelint: threads
class StallWatchdog:
    """Stall detectors evaluated on the vitals tick (host state only).

    `check(snapshot)` returns the list of stall records it fired this
    tick (for tests and for the caller to log); state needed across ticks
    (per-reason cooldowns, progress tracking) lives here so the sampler
    stays stateless about stalls.
    """

    #: detector names — the `reason` label on dalle_serving_stalls_total
    DISPATCH_STUCK = "dispatch_stuck"
    QUEUE_HEAD_STALE = "queue_head_stale"
    NO_PROGRESS = "no_progress"

    def __init__(
        self,
        dispatch_mult: float = 8.0,
        dispatch_min_s: float = 1.0,
        queue_age_budget_s: Optional[float] = None,
        no_progress_ticks: int = 3,
        cooldown_s: float = 30.0,
        first_dispatch_budget_s: float = 600.0,
        registry=None,
        log=None,
        state_dump_fn: Optional[Callable[[], Dict]] = None,
    ):
        self.dispatch_mult = float(dispatch_mult)
        self.dispatch_min_s = float(dispatch_min_s)
        self.queue_age_budget_s = queue_age_budget_s
        self.no_progress_ticks = int(no_progress_ticks)
        self.cooldown_s = float(cooldown_s)
        # a program's first dispatch may legitimately be compiling, so
        # it gets this LARGE fixed budget instead of the EMA-based one —
        # large, not unlimited: a deadlocked first dispatch must still
        # eventually fire (nothing else would catch it: no-progress is
        # suppressed while a dispatch is in flight)
        self.first_dispatch_budget_s = float(first_dispatch_budget_s)
        self.log = log
        self.state_dump_fn = state_dump_fn
        # guards recent/_last_fired: _fire runs on the sampler thread
        # while /healthz and /debug/vitals handlers read them (deque/dict
        # iteration during mutation raises RuntimeError)
        self._lock = threading.Lock()
        self._m_stalls = None
        if registry is not None:
            self._m_stalls = registry.counter_family(
                "dalle_serving_stalls_total",
                "watchdog stall detections by reason",
                label_name="reason",
            )
        self._last_fired: Dict[str, float] = {}
        self._progress_mark = None  # (chunk_index, consecutive stuck ticks)
        self.stalls_fired = 0
        #: most recent stall summaries (reason + detail, no dump), newest
        #: last — /debug/vitals and the degraded healthz read these
        self.recent: deque = deque(maxlen=16)

    def last_stall_age_s(self) -> Optional[float]:
        with self._lock:
            if not self._last_fired:
                return None
            return time.monotonic() - max(self._last_fired.values())

    def recent_stalls(self) -> List[Dict]:
        """Snapshot of the recent-stall ring for exporters (the sampler
        thread appends concurrently)."""
        with self._lock:
            return list(self.recent)

    # ------------------------------------------------------------- checks

    def _fire(self, reason: str, now: float, **detail) -> Optional[Dict]:
        record = {"reason": reason, **detail}
        with self._lock:
            last = self._last_fired.get(reason)
            if last is not None and now - last < self.cooldown_s:
                return None
            self._last_fired[reason] = now
            self.stalls_fired += 1
            self.recent.append({"ts": round(time.time(), 3), **record})
        if self._m_stalls is not None:
            self._m_stalls.labels(reason).inc()
        if self.log is not None:
            dump = None
            if self.state_dump_fn is not None:
                try:
                    dump = self.state_dump_fn()
                except Exception as exc:  # the dump must not kill the tick
                    dump = {"error": repr(exc)}
            extra = {}
            if not (isinstance(dump, dict) and "worker_stacks" in dump):
                # the server's state_dump already captures worker stacks;
                # only fall back to our own capture when the dump didn't
                # (standalone watchdogs, custom dump fns) — one
                # sys._current_frames pass per stall, not two, under ONE
                # schema key wherever the stacks land
                extra["worker_stacks"] = thread_stacks("batcher")
            self.log.event("stall", **record, state=dump, **extra)
        return record

    def check(self, snapshot: Dict, wall_ema: Dict[str, float]) -> List[Dict]:
        """Evaluate every detector against one vitals snapshot. `wall_ema`
        maps program name -> typical dispatch wall (the EMA the dispatch
        clock keeps), the baseline for "this dispatch is taking too long".
        """
        now = time.monotonic()
        fired = []

        inflight = snapshot.get("dispatch_inflight")
        if inflight is not None:
            name, age = inflight["program"], inflight["age_s"]
            if inflight.get("first"):
                # may be paying a legitimate XLA compile (--no_warmup
                # cold start): a large fixed budget, not the EMA one
                ema = None
                budget = self.first_dispatch_budget_s
            else:
                ema = wall_ema.get(name)
                budget = max(
                    self.dispatch_min_s,
                    self.dispatch_mult * ema if ema else 0.0,
                )
            if age > budget:
                rec = self._fire(
                    self.DISPATCH_STUCK, now, program=name,
                    age_s=round(age, 3), budget_s=round(budget, 3),
                    wall_ema_s=round(ema, 4) if ema else None,
                )
                if rec:
                    fired.append(rec)

        head_age = snapshot.get("queue_head_age_s")
        if (
            self.queue_age_budget_s is not None
            and head_age is not None
            and head_age > self.queue_age_budget_s
        ):
            rec = self._fire(
                self.QUEUE_HEAD_STALE, now,
                head_age_s=round(head_age, 3),
                budget_s=self.queue_age_budget_s,
                queue_depth_rows=snapshot.get("queue_depth_rows"),
            )
            if rec:
                fired.append(rec)

        # zero decode progress with slots active and NO dispatch in
        # flight: the worker is wedged somewhere host-side (the stuck-
        # dispatch detector owns the in-flight case)
        chunk_index = snapshot.get("chunk_index")
        slots = snapshot.get("slots_active") or 0
        if chunk_index is not None and slots > 0 and inflight is None:
            mark, stuck = self._progress_mark or (None, 0)
            stuck = stuck + 1 if mark == chunk_index else 0
            self._progress_mark = (chunk_index, stuck)
            if stuck >= self.no_progress_ticks:
                rec = self._fire(
                    self.NO_PROGRESS, now, chunk_index=chunk_index,
                    slots_active=slots, ticks=stuck,
                )
                if rec:
                    fired.append(rec)
        else:
            self._progress_mark = (chunk_index, 0)
        return fired


class SLOTarget:
    """One declarative latency objective over an existing histogram."""

    __slots__ = ("name", "threshold_s", "objective", "histogram")

    def __init__(self, name: str, threshold_s: float, histogram: str,
                 objective: float = 0.99):
        assert 0.0 < objective < 1.0
        self.name = name
        self.threshold_s = float(threshold_s)
        self.objective = float(objective)
        self.histogram = histogram  # registry metric name to read

    def describe(self) -> Dict:
        return {
            "slo": self.name,
            "threshold_ms": round(self.threshold_s * 1e3, 1),
            "objective": self.objective,
            "histogram": self.histogram,
        }


# tracelint: threads
class SLOTracker:
    """Rolling-window SLO burn rate from cumulative histogram buckets.

    Each `update()` diffs the target histogram's bucket counts against
    the previous tick and classifies the delta as compliant (buckets
    whose bound <= threshold) or violating — bucket-granular and
    CONSERVATIVE: a threshold that falls between bounds counts its
    straddling bucket as violating, so a misaligned target over-alerts
    rather than silently never alerting (stated in `status()`). It keeps
    a deque of per-tick deltas spanning `window_s`. Burn rate is
    the window's violation fraction over the allowed error budget
    (1 - objective): 1.0 means exactly on budget, above it the budget is
    burning and /healthz degrades.
    """

    def __init__(self, targets: Sequence[SLOTarget], registry,
                 window_s: float = 300.0):
        self.targets = list(targets)
        self.registry = registry
        self.window_s = float(window_s)
        self._m_burn = registry.gauge_family(
            "dalle_slo_burn_rate",
            "rolling-window error-budget burn rate per SLO (>1 = budget "
            "burning; /healthz degrades)",
            label_name="slo",
        )
        self._prev: Dict[str, tuple] = {}  # slo -> (counts, total)
        self._window: Dict[str, deque] = {
            t.name: deque() for t in self.targets
        }
        self._burn: Dict[str, float] = {t.name: 0.0 for t in self.targets}
        # update() runs on the sampler thread; status()/burning() on
        # /healthz handler threads — the window deques need the lock
        # (iteration during append raises RuntimeError)
        self._lock = threading.Lock()

    @staticmethod
    def _split(buckets, counts, threshold_s):
        """(ok, total) of a bucket snapshot: compliant = observations in
        buckets whose bound <= threshold (provably <= threshold). A
        threshold between bounds leaves its straddling bucket ambiguous —
        counted VIOLATING, so off-bucket thresholds fail conservative
        (burn over-reports) instead of silently never alerting; align
        thresholds with bucket bounds for exact accounting."""
        ok = 0
        for bound, n in zip(buckets, counts):
            if bound > threshold_s:
                break
            ok += n
        return ok, sum(counts)

    def update(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        burns = {}
        for t in self.targets:
            hist = self.registry.get(t.histogram)
            if hist is None or not hasattr(hist, "bucket_counts"):
                continue
            buckets, counts, total, _ = hist.bucket_counts()
            ok, _ = self._split(buckets, counts, t.threshold_s)
            with self._lock:
                prev_ok, prev_total = self._prev.get(t.name, (0, 0))
                d_total = total - prev_total
                d_viol = (total - ok) - (prev_total - prev_ok)
                self._prev[t.name] = (ok, total)
                win = self._window[t.name]
                if d_total > 0:
                    win.append((now, max(d_viol, 0), d_total))
                while win and now - win[0][0] > self.window_s:
                    win.popleft()
                viol = sum(v for _, v, _ in win)
                seen = sum(n for _, _, n in win)
                burn = (
                    (viol / seen) / (1.0 - t.objective) if seen else 0.0
                )
                self._burn[t.name] = burn
            burns[t.name] = burn
        for name, burn in burns.items():  # gauges have their own locks
            self._m_burn.labels(name).set(burn)

    def burning(self) -> List[str]:
        with self._lock:
            return [name for name, b in self._burn.items() if b > 1.0]

    def max_burn(self) -> float:
        """Worst burn rate across every tracked SLO — the scalar the
        batcher's preemption-aware shed consults (0.0 with no targets
        or no observations yet)."""
        with self._lock:
            return max(self._burn.values(), default=0.0)

    def status(self) -> List[Dict]:
        out = []
        for t in self.targets:
            with self._lock:
                win_viol = sum(v for _, v, _ in self._window[t.name])
                win_seen = sum(n for _, _, n in self._window[t.name])
                burn = self._burn[t.name]
            out.append({
                **t.describe(),
                "window_s": self.window_s,
                "burn_rate": round(burn, 3),
                "window_violations": win_viol,
                "window_observations": win_seen,
                "granularity": "histogram buckets (off-bound thresholds "
                               "count the straddling bucket as violating)",
            })
        return out


class EngineVitals:
    """Bounded-ring vitals sampler + dispatch clock for one serving stack.

    Construction is cheap and inert; `bind(engine, batcher, ...)` wires
    the host-state sources and `start()` launches the daemon sampler
    thread (no-ops when `enabled=False` — the counter-gated
    zero-allocation path). Engines call `dispatch_begin/dispatch_end`
    around every device dispatch; both are plain attribute stores, and
    `dispatch_end` feeds the per-program wall EMA the watchdog's
    stuck-dispatch budget derives from.
    """

    def __init__(
        self,
        enabled: bool = True,
        interval_s: float = 1.0,
        max_samples: int = 512,
        registry=None,
        log=None,
        watchdog: Optional[StallWatchdog] = None,
        slo: Optional[SLOTracker] = None,
    ):
        self.enabled = bool(enabled)
        self.interval_s = float(interval_s)
        self._ring: deque = deque(maxlen=int(max_samples))
        self._lock = threading.Lock()
        #: vitals snapshots actually allocated — the counter-gated
        #: zero-overhead-when-off contract, like Tracer.spans_created
        self.samples_taken = 0
        self.registry = registry
        self.log = log
        self.watchdog = watchdog
        self.slo = slo
        self._engine = None
        self._batcher = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # dispatch clock: written by the engine thread, read (torn reads
        # tolerated — monotonic floats) by the sampler thread
        self._inflight_name: Optional[str] = None
        self._inflight_t0 = 0.0
        self._inflight_first = False
        self._inflight_c0 = 0
        self._wall_ema: Dict[str, float] = {}
        #: programs that have completed >= 1 dispatch since this sampler
        #: bound: a program's FIRST dispatch may be paying an unbounded,
        #: legitimate XLA compile (--no_warmup, a lazily-built program),
        #: so the stuck detector exempts it; whether its wall seeds the
        #: EMA is decided by whether a compile ACTUALLY landed (the
        #: compile_guard counter delta), so warmed servers get their
        #: baseline from dispatch one
        self._seen_programs: set = set()
        if self.enabled:
            try:  # compile-delta attribution needs the jax.monitoring
                compile_guard.install_listener()  # listener; optional —
            except Exception:  # without jax, deltas just stay 0
                pass
        self._m_inflight_age = self._m_head_age = self._m_mem = None
        self._m_hbm = None
        if self.enabled and registry is not None:
            # per-shard HBM gauge family: a mesh-sharded engine has one
            # device PER SHARD, and "the device is full" is useless until
            # it names which one — label by device so dashboards and the
            # watchdog postmortem identify the sick shard
            self._m_hbm = registry.gauge_family(
                "dalle_serving_hbm_bytes",
                "device memory_stats() bytes_in_use per mesh device "
                "(one series per shard; absent when the backend doesn't "
                "report memory stats)",
                label_name="device",
            )
            self._m_inflight_age = registry.gauge(
                "dalle_serving_dispatch_inflight_age_seconds",
                "age of the engine dispatch currently in flight (0 when "
                "idle)",
            )
            self._m_head_age = registry.gauge(
                "dalle_serving_queue_head_age_seconds",
                "age of the oldest queued request (0 when the queue is "
                "empty)",
            )
            self._m_mem = registry.gauge(
                "dalle_serving_device_bytes_in_use",
                "device.memory_stats() bytes_in_use (0 when the backend "
                "doesn't report it)",
            )

    # ------------------------------------------------------ dispatch clock

    def dispatch_begin(self, name: str) -> None:
        self._inflight_first = name not in self._seen_programs
        self._inflight_c0 = compile_guard.compile_count()
        self._inflight_t0 = time.monotonic()
        self._inflight_name = name

    def dispatch_end(self, name: str, seconds: float) -> None:
        self._inflight_name = None
        self._seen_programs.add(name)
        if compile_guard.compile_count() > self._inflight_c0:
            # a backend compile landed during this dispatch (--no_warmup
            # cold start, lazy program): the wall is compile latency, and
            # folding it in would inflate the watchdog's stuck budget by
            # dispatch_mult * compile_s — blinding it to real stalls.
            # (Attribution is process-wide, like compile_guard itself: a
            # concurrent compile elsewhere costs one skipped sample.)
            return
        # under the lock: the sampler thread snapshots this dict per tick
        # while engine dispatch threads land EMA updates here
        with self._lock:
            ema = self._wall_ema.get(name)
            self._wall_ema[name] = (
                seconds if ema is None else 0.8 * ema + 0.2 * seconds
            )

    def inflight(self) -> Optional[Dict]:
        name = self._inflight_name
        if name is None:
            return None
        return {
            "program": name,
            "age_s": time.monotonic() - self._inflight_t0,
            # True while the program's FIRST dispatch is in flight — it
            # may be compiling, so the stuck detector exempts it
            "first": self._inflight_first,
        }

    # ------------------------------------------------------------ lifecycle

    def bind(self, engine=None, batcher=None, log=None,
             state_dump_fn=None) -> "EngineVitals":
        self._engine = engine
        self._batcher = batcher
        if log is not None:
            self.log = log
        if self.watchdog is not None:
            if log is not None and self.watchdog.log is None:
                self.watchdog.log = log
            if state_dump_fn is not None:
                self.watchdog.state_dump_fn = state_dump_fn
        if engine is not None and getattr(engine, "vitals", None) is not None:
            engine.vitals = self if self.enabled else NULL_VITALS
        return self

    def start(self) -> "EngineVitals":
        if not self.enabled or self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="dalle-vitals", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # a bad source must not kill the sampler
                pass

    # ------------------------------------------------------------ sampling

    def _device_memory_stats(self) -> Optional[Dict]:
        """Overridable device seam (the profiler-hook pattern): returns
        `jax.devices()[0].memory_stats()` or None when the backend (CPU)
        doesn't provide it. Tests stub this — no real device touch."""
        try:
            import jax

            return jax.devices()[0].memory_stats()
        except Exception:
            return None

    def _device_memory_stats_all(self) -> Dict[str, Dict]:
        """Overridable per-shard seam: `memory_stats()` for EVERY device
        of the engine's mesh, keyed 'platform:id'. PR 7's sampler read
        one process-local device; a mesh-sharded engine has one device
        per shard, and a lopsided shard (bad partition rule, leaked
        buffer) is invisible in a single-device read.

        Without a mesh this routes through the legacy single-device seam
        (`_device_memory_stats`) — ONE query per tick, and tests that
        stub the legacy seam keep their no-real-device-touch contract on
        every backend, not just ones whose memory_stats is empty."""
        mesh = getattr(self._engine, "mesh", None)
        if mesh is None:
            stats = self._device_memory_stats()
            return {"device:0": stats} if stats else {}
        out: Dict[str, Dict] = {}
        try:
            for d in mesh.devices.flat:
                stats = d.memory_stats()
                if stats:
                    out[f"{d.platform}:{d.id}"] = stats
        except Exception:
            return out
        return out

    def sample(self) -> Dict:
        """One vitals snapshot from host state (never dispatches)."""
        snap: Dict = {"ts": round(time.time(), 3)}
        batcher = self._batcher
        if batcher is not None:
            snap["queue_depth_rows"] = batcher.queue_depth_rows
            head_age = getattr(batcher, "head_age_s", None)
            if head_age is not None:
                snap["queue_head_age_s"] = head_age()
            class_depths = getattr(batcher, "class_depths", None)
            if class_depths is not None:
                # per-priority-class queue split: under overload the
                # headline depth hides WHICH class is backing up
                snap["queue_depth_by_class"] = class_depths()
            alloc = getattr(batcher, "allocator", None)
            if alloc is not None:
                snap["slots_active"] = alloc.n_active
        engine = self._engine
        if engine is not None:
            chunk_index = getattr(engine, "chunk_index", None)
            if chunk_index is not None:
                snap["chunk_index"] = int(chunk_index)
            kv = getattr(engine, "kv", None)
            if kv is not None:
                snap["blocks_active"] = kv.blocks_active
                snap["blocks_free"] = kv.blocks_free
                snap["prefix_entries"] = len(kv.cache)
        snap["dispatch_inflight"] = self.inflight()
        snap["compile_count"] = compile_guard.compile_count()
        per_dev = self._device_memory_stats_all()
        if per_dev:
            snap["memory_stats_per_device"] = {
                dev: {
                    k: int(v) for k, v in stats.items()
                    if isinstance(v, (int, float))
                }
                for dev, stats in per_dev.items()
            }
            snap["bytes_in_use_total"] = sum(
                s.get("bytes_in_use", 0)
                for s in snap["memory_stats_per_device"].values()
            )
            # the legacy single-device block is the FIRST device's stats
            # — derived, not re-queried (one memory_stats pass per device
            # per tick, not two for device 0)
            snap["memory_stats"] = next(
                iter(snap["memory_stats_per_device"].values())
            )
        return snap

    def tick(self) -> Dict:
        """Sample once, run the watchdog and SLO updates, update gauges.
        Public so tests drive deterministic ticks without the thread."""
        snap = self.sample()
        with self._lock:
            self._ring.append(snap)
            self.samples_taken += 1
            # snapshot the EMA table while no dispatch thread is mid-update
            # (dispatch_end mutates it under this lock)
            wall_ema = dict(self._wall_ema)
        if self._m_inflight_age is not None:
            inflight = snap.get("dispatch_inflight")
            self._m_inflight_age.set(inflight["age_s"] if inflight else 0.0)
        if self._m_head_age is not None:
            self._m_head_age.set(snap.get("queue_head_age_s") or 0.0)
        if self._m_mem is not None:
            self._m_mem.set(
                (snap.get("memory_stats") or {}).get("bytes_in_use", 0)
            )
        if self._m_hbm is not None:
            for dev, stats in (
                snap.get("memory_stats_per_device") or {}
            ).items():
                self._m_hbm.labels(dev).set(stats.get("bytes_in_use", 0))
        if self.watchdog is not None:
            self.watchdog.check(snap, wall_ema)
        if self.slo is not None:
            # tracelint: disable=TL013 -- SLOTracker.update() is a method call, not a dict mutation; the tracker guards its windows with its own lock (review-hardening round, PR 7)
            self.slo.update()
        return snap

    # ------------------------------------------------------------- export

    def recent(self, n: Optional[int] = None) -> List[Dict]:
        with self._lock:
            samples = list(self._ring)
        return samples if n is None else samples[-n:]

    def reset_window(self) -> None:
        """Drop ring contents (bench: measure only the open-loop window)."""
        with self._lock:
            self._ring.clear()

    def window_summary(self) -> Dict:
        """mean/peak aggregates over the current ring — the bench's
        `vitals` block and a quick /debug/vitals headline."""
        samples = self.recent()
        out: Dict = {"samples": len(samples)}
        for key in ("slots_active", "blocks_active", "queue_depth_rows"):
            vals = [s[key] for s in samples if key in s]
            if vals:
                out[key] = {
                    "mean": round(sum(vals) / len(vals), 2),
                    "peak": max(vals),
                }
        return out

    def detail(self, n: Optional[int] = None) -> Dict:
        """JSON payload for `GET /debug/vitals`."""
        with self._lock:  # ticked by the sampler thread under this lock
            samples_taken = self.samples_taken
        out = {
            "enabled": self.enabled,
            "interval_s": self.interval_s,
            "samples_taken": samples_taken,
            "summary": self.window_summary(),
            "samples": self.recent(n),
        }
        mesh_detail = getattr(self._engine, "mesh_detail", None)
        if mesh_detail is not None:
            # sharded engine: one rolled-up payload names every shard —
            # axis geometry + live per-device buffer bytes — next to the
            # per-device memory_stats the samples carry
            out["mesh"] = mesh_detail()
        if self.watchdog is not None:
            out["stalls"] = self.watchdog.recent_stalls()
        if self.slo is not None:
            out["slo"] = self.slo.status()
        return out

    # ------------------------------------------------------------- health

    def degraded_reasons(self, window_s: float = 60.0) -> List[str]:
        """Why /healthz should report `degraded` (empty = fully ok):
        a watchdog stall within `window_s`, or an SLO burning."""
        reasons = []
        if self.watchdog is not None:
            age = self.watchdog.last_stall_age_s()
            if age is not None and age < window_s:
                stalls = self.watchdog.recent_stalls()
                last = stalls[-1] if stalls else {}
                reasons.append(
                    f"stall:{last.get('reason', 'unknown')}"
                )
        if self.slo is not None:
            reasons.extend(f"slo_burn:{name}" for name in self.slo.burning())
        return reasons
