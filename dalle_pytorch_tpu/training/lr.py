"""Host-side learning-rate controllers.

The reference uses torch's ReduceLROnPlateau for DALLE training
(`/root/reference/train_dalle.py:344-353`: factor 0.5, patience 10,
cooldown 10, min_lr 1e-6, stepped once per epoch on the averaged loss) and
ExponentialLR for dVAE training (`train_vae.py:158`). Both are control
decisions on host-visible scalars, so they live outside jit and rewrite
the optimizer's injected `learning_rate` hyperparameter between steps —
no recompilation.
"""

from __future__ import annotations

from dataclasses import dataclass, field, asdict


@dataclass
class ReduceLROnPlateau:
    factor: float = 0.5
    patience: int = 10
    cooldown: int = 10
    min_lr: float = 1e-6
    best: float = float("inf")
    num_bad: int = 0
    cooldown_counter: int = 0

    def step(self, metric: float, lr: float) -> float:
        """Feed the epoch metric; returns the (possibly reduced) lr."""
        if metric < self.best:
            self.best = metric
            self.num_bad = 0
        elif self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad = 0
        else:
            self.num_bad += 1
            if self.num_bad > self.patience:
                lr = max(lr * self.factor, self.min_lr)
                self.cooldown_counter = self.cooldown
                self.num_bad = 0
        return lr

    def state_dict(self) -> dict:
        return asdict(self)

    def load_state_dict(self, state: dict) -> None:
        for k, v in state.items():
            setattr(self, k, v)


@dataclass
class ExponentialDecay:
    gamma: float = 0.98

    def step(self, metric: float, lr: float) -> float:
        return lr * self.gamma

    def state_dict(self) -> dict:
        return asdict(self)

    def load_state_dict(self, state: dict) -> None:
        for k, v in state.items():
            setattr(self, k, v)
