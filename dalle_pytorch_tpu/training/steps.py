"""Jitted train-step factories for the three model families.

Equivalent of the reference hot loops (`/root/reference/train_dalle.py:
494-592`, `train_vae.py:230-303`) — but each whole step (frozen-VAE encode,
forward(s), backward, clip, Adam update) is ONE compiled XLA program, pjit-
shardable over the mesh. Gradient averaging across data-parallel shards is
implicit (XLA inserts the psum); the reference's explicit
`average_all(loss)` (`deepspeed_backend.py:165-171`) becomes a jnp.mean the
compiler lowers to the same collective.

Feature mapping:
  * `--fp16` + apex AMP (`train_dalle.py:326-327,382-388`) -> bf16 compute
    dtype on the model, fp32 params/optimizer (no loss scaling needed);
  * DeepSpeed `ga_steps` (`train_dalle.py:380`) -> lax.scan microbatching
    inside the step (`grad_accum`);
  * `clip_grad_norm_` (`train_dalle.py:526`) -> optax.clip_by_global_norm;
  * the fork's objective modes (`train_dalle.py:513-518`,
    `config/config.yaml:13`): forward_only / forward_forward /
    forward_reverse_partial; reverse_only (named in `config/exp/ro.yaml`
    but unhandled by the reference trainer) is implemented here as the
    inverse objective alone.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state

from dalle_pytorch_tpu.models.dvae import DiscreteVAE

MODES = ("forward_only", "forward_forward", "forward_reverse_partial", "reverse_only")


class TrainState(train_state.TrainState):
    pass


def make_optimizer(
    learning_rate: float, clip_grad_norm: Optional[float] = None
) -> optax.GradientTransformation:
    """Adam with optional global-norm clipping; lr is a mutable hyperparam
    (host-side schedulers rewrite it, see lr.py)."""

    def build(learning_rate):
        steps = []
        if clip_grad_norm is not None:
            steps.append(optax.clip_by_global_norm(clip_grad_norm))
        steps.append(optax.adam(learning_rate))
        return optax.chain(*steps)

    return optax.inject_hyperparams(build)(learning_rate=learning_rate)


def get_learning_rate(state: TrainState) -> float:
    return float(state.opt_state.hyperparams["learning_rate"])


def set_learning_rate(state: TrainState, lr: float) -> TrainState:
    opt_state = state.opt_state
    hyper = dict(opt_state.hyperparams)
    hyper["learning_rate"] = jnp.asarray(lr, jnp.float32)
    return state.replace(opt_state=opt_state._replace(hyperparams=hyper))


def _accumulate(loss_and_metrics_fn, params, batches, rng, accum: int):
    """Scan `accum` microbatches, averaging grads and metrics."""

    def micro(carry, inp):
        g_acc, m_acc = carry
        mb, r = inp
        (_, metrics), grads = jax.value_and_grad(
            loss_and_metrics_fn, has_aux=True
        )(params, mb, r)
        g_acc = jax.tree.map(jnp.add, g_acc, grads)
        m_acc = jax.tree.map(jnp.add, m_acc, metrics)
        return (g_acc, m_acc), None

    rngs = jax.random.split(rng, accum)
    mb0 = jax.tree.map(lambda x: x[0], batches)
    (_, m0), g0 = jax.value_and_grad(loss_and_metrics_fn, has_aux=True)(
        params, mb0, rngs[0]
    )
    if accum == 1:
        return g0, m0
    rest = jax.tree.map(lambda x: x[1:], batches)
    (g, m), _ = jax.lax.scan(micro, (g0, m0), (rest, rngs[1:]))
    scale = 1.0 / accum
    return jax.tree.map(lambda x: x * scale, g), jax.tree.map(lambda x: x * scale, m)


def _microbatch(batch, accum: int):
    """[B, ...] -> [accum, B/accum, ...] for every leaf."""
    if accum == 1:
        return jax.tree.map(lambda x: x[None], batch)
    return jax.tree.map(
        lambda x: x.reshape(accum, x.shape[0] // accum, *x.shape[1:]), batch
    )


def make_vae_train_step(vae: DiscreteVAE, grad_accum: int = 1) -> Callable:
    """step(state, images, rng, temp) -> (state, metrics).

    Mirrors the dVAE hot loop (`train_vae.py:234-248`); `temp` is the
    annealed gumbel temperature (`train_vae.py:278`), traced so annealing
    doesn't recompile.
    """

    def loss_fn(params, images, rng, temp):
        loss = vae.apply(
            {"params": params}, images, return_loss=True, temp=temp,
            rngs={"gumbel": rng},
        )
        return loss, {"loss": loss}

    def step(state: TrainState, images, rng, temp):
        fn = lambda p, mb, r: loss_fn(p, mb, r, temp)
        grads, metrics = _accumulate(
            fn, state.params, _microbatch(images, grad_accum), rng, grad_accum
        )
        return state.apply_gradients(grads=grads), metrics

    return step


def make_dalle_train_step(
    model,
    vae: Optional[DiscreteVAE] = None,
    mode: str = "forward_only",
    grad_accum: int = 1,
    null_cond_prob: float = 0.0,
    pp_trunk: Optional[Callable] = None,
) -> Callable:
    """step(state, batch, rng[, vae_params]) -> (state, metrics).

    batch: {"text": [B, T] ids, "images": [B, H, W, C]} when a trainable-
    frozen `vae` is supplied (in-step encode, reference
    `dalle_pytorch.py:619-627`), else {"text", "image_tokens": [B, N]}
    (the better TPU pattern: tokens precomputed offline).

    Loss composition per the fork trainer (`train_dalle.py:509-518`):
    forward loss always except reverse_only; inverse loss added for
    forward_forward (same layer order) / forward_reverse_partial
    (reversed layer order).

    `pp_trunk` (optional): the `run(tparams, x)` closure from
    `make_pipeline_trunk` — the transformer trunk executes pipeline-
    parallel over the mesh 'pp' axis instead of on-module. The pp trunk
    is deterministic by design (no dropout; models/dalle.py asserts) and
    owns the layer order, so reversed-layer modes are rejected.
    """
    assert mode in MODES, f"mode must be one of {MODES}"
    if pp_trunk is not None:
        assert mode != "forward_reverse_partial", (
            "pipeline parallelism cannot run reversed layer order "
            "(trunk_fn owns the layer order); use forward_only / "
            "forward_forward / reverse_only"
        )

    def encode(vae_params, batch):
        if vae is not None and "image_tokens" not in batch:
            return jax.lax.stop_gradient(
                vae.apply(
                    {"params": vae_params},
                    batch["images"],
                    method=DiscreteVAE.get_codebook_indices,
                )
            )
        return batch["image_tokens"]

    def loss_fn(params, batch, rng, vae_params):
        text = batch["text"]
        tokens = encode(vae_params, batch)
        drop_rng, null_rng = jax.random.split(rng)
        rngs = {"dropout": drop_rng, "null_cond": null_rng}
        shared = dict(
            return_loss=True, null_cond_prob=null_cond_prob,
            deterministic=False, rngs=rngs,
        )
        if pp_trunk is not None:
            # deterministic by design: dropout layers are hard-disabled
            # under the pp trunk (config validation requires zero dropout
            # rates); null-cond CFG randomness still applies — it acts on
            # the embeddings before the trunk
            shared.update(
                deterministic=True, rngs={"null_cond": null_rng},
                trunk_fn=lambda h: pp_trunk(params["transformer"], h),
            )
        apply = lambda **kw: model.apply(
            {"params": params}, text, tokens, **shared, **kw
        )

        metrics = {}
        if mode == "reverse_only":
            loss, acc = apply(inverse_mapping=True)
            metrics.update(inverse_loss=loss, accuracy=acc, forward_loss=0.0)
        else:
            loss, _ = apply()
            metrics["forward_loss"] = loss
            if mode in ("forward_forward", "forward_reverse_partial"):
                inv_loss, acc = apply(
                    inverse_mapping=True,
                    reverse_model=(mode == "forward_reverse_partial"),
                )
                loss = loss + inv_loss
                metrics.update(inverse_loss=inv_loss, accuracy=acc)
        metrics["loss"] = loss
        return loss, metrics

    def step(state: TrainState, batch, rng, vae_params=None):
        fn = lambda p, mb, r: loss_fn(p, mb, r, vae_params)
        grads, metrics = _accumulate(
            fn, state.params, _microbatch(batch, grad_accum), rng, grad_accum
        )
        return state.apply_gradients(grads=grads), metrics

    return step


def make_multi_step(step_fn: Callable, n_steps: int) -> Callable:
    """Wrap a train step so `n_steps` optimizer steps run in ONE dispatch.

    multi(state, batches, rngs, *extras) -> (state, mean_metrics)

    `batches` is the per-step batch pytree with a leading [n_steps, ...]
    axis on every leaf; `rngs` is an [n_steps] stack of PRNG keys (callers
    that fold per-global-step — `train_dalle.py`'s
    `fold_in(rng, global_step)` — pass the same folded keys stacked, so
    the key stream is bit-identical to n_steps separate dispatches and
    mid-run resume replays exactly). `*extras` (frozen VAE params, gumbel
    temp) are per-dispatch constants, closed over the whole scan — with
    multi-stepping, schedules that anneal such extras move at dispatch
    granularity instead of step granularity.

    Why this exists: the host loop pays one dispatch round trip per jitted
    call, and on synchronous-dispatch backends (the tunneled axon TPU; any
    profiling setup that forces readbacks) that round trip bounds
    throughput no matter how fast the compiled step is. Scanning the step
    body amortizes one round trip over `n_steps` real optimizer steps —
    the same host-loop-elimination trick production TPU trainers (t5x et
    al.) use. Compiled size stays ~one step (scan compiles the body once).

    The reference has no analogue: its hot loop is host-driven per step
    (`/root/reference/train_dalle.py:494-592`), which CUDA hides via async
    launch queues; XLA's equivalent is putting the loop on device.

    Returned metrics are the mean over the inner steps (the per-step
    stream is still observable by lowering n_steps).
    """
    assert n_steps >= 1

    def multi(state: TrainState, batches, rngs, *extras):
        def body(st, inp):
            b, r = inp
            st, metrics = step_fn(st, b, r, *extras)
            return st, metrics

        state, metrics = jax.lax.scan(body, state, (batches, rngs))
        return state, jax.tree.map(lambda x: jnp.mean(x, axis=0), metrics)

    return multi


def window_keys(rng, start_step: int, n: int):
    """[n]-stacked `fold_in(rng, start_step + i)` keys — the per-global-step
    stream `make_multi_step` prescribes. One shared helper so every
    windowed trainer derives the identical stream: a pure function of the
    step index, invariant to steps_per_dispatch, epoch tails, and resume."""
    return jnp.stack(
        [jax.random.fold_in(rng, start_step + i) for i in range(n)]
    )


def stack_batches(batches: list):
    """Stack a list of per-step batch pytrees into the [n_steps, ...]
    layout `make_multi_step` consumes (one host->device transfer for the
    whole window instead of one per step)."""
    import numpy as np

    return jax.tree.map(lambda *xs: np.stack(xs), *batches)


def window_iter(it, n: int):
    """Group an iterator into lists of `n` (the final group may be
    shorter — trainers replay such epoch tails through their single-step
    program). Shared by every steps_per_dispatch trainer loop."""
    buf = []
    for b in it:
        buf.append(b)
        if len(buf) == n:
            yield buf
            buf = []
    if buf:
        yield buf


def make_clip_train_step(clip_model, grad_accum: int = 1) -> Callable:
    """step(state, batch{text,images}, rng) -> (state, metrics)."""

    def loss_fn(params, batch, rng):
        loss = clip_model.apply(
            {"params": params}, batch["text"], batch["images"],
            text_mask=batch.get("text_mask"), return_loss=True,
            deterministic=False, rngs={"dropout": rng},
        )
        return loss, {"loss": loss}

    def step(state: TrainState, batch, rng):
        grads, metrics = _accumulate(
            loss_fn, state.params, _microbatch(batch, grad_accum), rng, grad_accum
        )
        return state.apply_gradients(grads=grads), metrics

    return step
