"""Orbax-backed checkpointing with rotation and resume.

Replaces the reference's torch.save single-file checkpoints and DeepSpeed
engine directories (`/root/reference/train_dalle.py:432-479`,
`train_vae.py:203-223`) with one format that works identically on a laptop
CPU and a multi-host pod: Orbax sharded array checkpoints for the
TrainState plus a JSON metadata blob carrying the same logical payload the
reference stores ({hparams, vae_params, epoch, version, vae_class_name}).

Rotation mirrors `keep_n_checkpoints` (`train_dalle.py:444-447`); resume
mirrors `--dalle_path` reload of weights+opt+scheduler
(`train_dalle.py:139-161,330-338,354-355`).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Optional

import jax


class CheckpointManager:
    def __init__(self, directory: str, keep_n: Optional[int] = None):
        import orbax.checkpoint as ocp

        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=keep_n, create=True, enable_async_checkpointing=True
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    def save(self, step: int, state: Any, metadata: Optional[dict] = None) -> None:
        import orbax.checkpoint as ocp

        args = {"state": ocp.args.StandardSave(state)}
        if metadata is not None:
            args["metadata"] = ocp.args.JsonSave(metadata)
        self._mgr.save(step, args=ocp.args.Composite(**args))

    def restore(self, state_template: Any, step: Optional[int] = None):
        """Returns (state, metadata, step) or (None, None, None) if empty."""
        import orbax.checkpoint as ocp

        step = self.latest_step() if step is None else step
        if step is None:
            return None, None, None
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(state_template),
                metadata=ocp.args.JsonRestore(),
            ),
        )
        return restored["state"], restored.get("metadata"), step

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()


def save_params_npz(path: str, params: Any, metadata: Optional[dict] = None) -> None:
    """Single-file portable export (the moral torch.save equivalent) for
    small models / generate.py interchange."""
    import numpy as np

    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    arrays = {
        "/".join(str(getattr(k, "key", k)) for k in path): np.asarray(v)
        for path, v in flat
    }
    Path(path).parent.mkdir(parents=True, exist_ok=True)
    np.savez(path, __metadata__=json.dumps(metadata or {}), **arrays)


def load_params_npz(path: str):
    """Returns (nested params dict, metadata dict)."""
    import numpy as np

    data = np.load(path, allow_pickle=False)
    metadata = json.loads(str(data["__metadata__"]))
    params: dict = {}
    for key in data.files:
        if key == "__metadata__":
            continue
        node = params
        parts = key.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = data[key]
    return params, metadata
