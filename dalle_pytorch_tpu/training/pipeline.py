"""Shared trainer plumbing: building tokenizers/datasets/models from config,
sharding states over the mesh, and checkpoint payload assembly.

This is the glue the reference keeps inline in its entry scripts
(`/root/reference/train_dalle.py:119-330`, `generate.py:70-107`),
factored so the CLIs stay thin and the pieces are testable.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from dalle_pytorch_tpu.data.tokenizer import get_tokenizer
from dalle_pytorch_tpu.models.dvae import DiscreteVAE
from dalle_pytorch_tpu.models.dalle import DALLE
from dalle_pytorch_tpu.training.config import TrainConfig, VaeConfig, config_to_dict
from dalle_pytorch_tpu.training.checkpoint import save_params_npz, load_params_npz
from dalle_pytorch_tpu.version import __version__


def build_tokenizer(cfg: TrainConfig):
    return get_tokenizer(
        bpe_path=cfg.bpe_path, hug=cfg.hug, chinese=cfg.chinese, yttm=cfg.yttm,
        native=getattr(cfg, "native", False)
    )


def build_dataset(cfg: TrainConfig, tokenizer, image_size: int):
    """folder | 'rainbow[:N]' builtin | wds tar shards."""
    if cfg.wds:
        from dalle_pytorch_tpu.data.webdataset import TarImageTextDataset

        cols = [c.strip() for c in cfg.wds.split(",")]
        img_key, txt_key = (cols + ["jpg", "txt"])[:2]
        assert cfg.image_text_folder, "--image_text_folder must point at shards"
        return TarImageTextDataset(
            cfg.image_text_folder,
            image_key=img_key,
            text_key=txt_key,
            text_len=cfg.model.text_seq_len,
            image_size=image_size,
            truncate_captions=cfg.truncate_captions,
            resize_ratio=cfg.resize_ratio,
            tokenizer=tokenizer,
        )
    folder = cfg.image_text_folder or "rainbow"
    if folder.startswith("rainbow"):
        from dalle_pytorch_tpu.data.rainbow import RainbowDataset

        n = int(folder.split(":")[1]) if ":" in folder else 1024

        class _RainbowAdapter:
            def __init__(self):
                self.ds = RainbowDataset(num_samples=n, image_size=image_size)

            def __len__(self):
                return len(self.ds)

            def batches(self, batch_size, shuffle_seed=None, shard=(0, 1), **kw):
                return self.ds.batches(
                    batch_size,
                    tokenizer,
                    cfg.model.text_seq_len,
                    shuffle_seed=shuffle_seed,
                    shard=shard,
                    **kw,
                )

        return _RainbowAdapter()
    from dalle_pytorch_tpu.data.loader import TextImageDataset

    return TextImageDataset(
        folder,
        text_len=cfg.model.text_seq_len,
        image_size=image_size,
        truncate_captions=cfg.truncate_captions,
        resize_ratio=cfg.resize_ratio,
        tokenizer=tokenizer,
        class_name_json=cfg.class_name_json,
    )


def vae_from_config(vcfg: VaeConfig, dtype=jnp.float32) -> DiscreteVAE:
    return DiscreteVAE(
        image_size=vcfg.image_size,
        num_tokens=vcfg.num_tokens,
        codebook_dim=vcfg.codebook_dim,
        num_layers=vcfg.num_layers,
        num_resnet_blocks=vcfg.num_resnet_blocks,
        hidden_dim=vcfg.hidden_dim,
        channels=vcfg.channels,
        smooth_l1_loss=vcfg.smooth_l1_loss,
        temperature=vcfg.temperature,
        straight_through=vcfg.straight_through,
        reinmax=vcfg.reinmax,
        kl_div_loss_weight=vcfg.kl_loss_weight,
        dtype=dtype,
    )


def dvae_hparams(vae: DiscreteVAE) -> dict:
    return {
        "image_size": vae.image_size,
        "num_tokens": vae.num_tokens,
        "codebook_dim": vae.codebook_dim,
        "num_layers": vae.num_layers,
        "num_resnet_blocks": vae.num_resnet_blocks,
        "hidden_dim": vae.hidden_dim,
        "channels": vae.channels,
        "smooth_l1_loss": vae.smooth_l1_loss,
        "temperature": vae.temperature,
        "straight_through": vae.straight_through,
        "reinmax": vae.reinmax,
        "kl_div_loss_weight": vae.kl_div_loss_weight,
    }


def dvae_from_hparams(h: dict, dtype=jnp.float32) -> DiscreteVAE:
    return DiscreteVAE(
        image_size=h["image_size"],
        num_tokens=h["num_tokens"],
        codebook_dim=h["codebook_dim"],
        num_layers=h["num_layers"],
        num_resnet_blocks=h.get("num_resnet_blocks", 0),
        hidden_dim=h["hidden_dim"],
        channels=h.get("channels", 3),
        smooth_l1_loss=h.get("smooth_l1_loss", False),
        temperature=h.get("temperature", 0.9),
        straight_through=h.get("straight_through", False),
        reinmax=h.get("reinmax", False),
        kl_div_loss_weight=h.get("kl_div_loss_weight", 0.0),
        dtype=dtype,
    )


def save_vae_checkpoint(path: str, vae: DiscreteVAE, params, epoch: int = 0):
    """Single-file dVAE ckpt ({hparams, weights}, `train_vae.py:203-223`)."""
    hparams = dvae_hparams(vae)
    save_params_npz(
        path,
        params,
        metadata={
            "type": "DiscreteVAE",
            "version": __version__,
            "epoch": epoch,
            "hparams": hparams,
        },
    )


def load_vae_checkpoint(path: str, dtype=jnp.float32) -> Tuple[DiscreteVAE, Any]:
    params, meta = load_params_npz(path)
    assert meta.get("type") == "DiscreteVAE", f"{path} is not a dVAE checkpoint"
    vae = dvae_from_hparams(meta["hparams"], dtype=dtype)
    params = jax.tree.map(jnp.asarray, params)
    return vae, params


def build_vae(cfg: TrainConfig, dtype=jnp.float32):
    """VAE reconstitution precedence (`train_dalle.py:139-186`):
    --vae_path (trained dVAE) | --taming (VQGAN) | OpenAI pretrained."""
    if cfg.vae_path:
        return load_vae_checkpoint(cfg.vae_path, dtype=dtype)
    if cfg.taming:
        from dalle_pytorch_tpu.models.vae_io import VQGanVAE

        assert cfg.vqgan_model_path and cfg.vqgan_config_path
        return VQGanVAE(cfg.vqgan_model_path, cfg.vqgan_config_path), None
    from dalle_pytorch_tpu.models.vae_io import OpenAIDiscreteVAE

    return OpenAIDiscreteVAE(), None


# ready-to-use jax.checkpoint_policies predicates (the module's other
# attributes are factories that require arguments)
REMAT_POLICIES = frozenset(
    {
        "everything_saveable",
        "nothing_saveable",
        "dots_saveable",
        "dots_with_no_batch_dims_saveable",
        "checkpoint_dots",
        "checkpoint_dots_with_no_batch_dims",
    }
    & set(dir(jax.checkpoint_policies))
)


def dalle_from_config(
    cfg: TrainConfig,
    num_image_tokens: int,
    image_fmap_size: int,
    vocab_size: int,
    sp_mesh=None,
) -> DALLE:
    """`sp_mesh`: pass the trainer's mesh when cfg.mesh.sp > 1 — the model
    then runs ring attention (sequence-parallel over the "sp" axis) for
    long-context training; with sp == 1 the mesh axis is inert and the
    configured attn_impl ("auto"/"dense"/"flash") applies."""
    m = cfg.model
    remat_policy = getattr(m, "remat_policy", None)
    if remat_policy is not None and remat_policy not in REMAT_POLICIES:
        # jax.checkpoint_policies also contains policy FACTORIES
        # (save_only_these_names, ...) that need arguments — passing one
        # directly as a policy silently disables remat, so only the
        # ready-to-use predicates are accepted here
        raise ValueError(
            f"unknown model.remat_policy {remat_policy!r}; valid names: "
            f"{sorted(REMAT_POLICIES)}"
        )
    attn_impl = m.attn_impl
    executor = getattr(m, "executor", "unrolled")
    if executor not in ("unrolled", "scan"):
        raise ValueError(
            f"unknown model.executor {executor!r}; valid: unrolled, scan"
        )
    if executor == "scan" and sp_mesh is not None and sp_mesh.shape.get("sp", 1) > 1:
        raise ValueError(
            'model.executor="scan" has not been validated with ring '
            "attention (mesh.sp>1); use the unrolled executor for "
            "sequence-parallel training"
        )
    if sp_mesh is not None and sp_mesh.shape.get("sp", 1) > 1:
        if attn_impl in ("auto", "ring"):
            attn_impl = "ring"
        else:
            raise ValueError(
                f'mesh.sp={sp_mesh.shape["sp"]} requires ring attention, but '
                f"model.attn_impl={attn_impl!r} was set explicitly; use "
                '"ring" or "auto" (or set mesh.sp=1)'
            )
        if m.stable_softmax:
            raise ValueError(
                "ring attention (mesh.sp > 1) is incompatible with "
                "model.stable_softmax; its streaming accumulator is already "
                "max-subtracted"
            )
        sp = sp_mesh.shape["sp"]
        # transformer sequence = bos-padded text truncated back to
        # text_seq_len, plus the image grid (models/dalle.py __call__)
        total_seq = m.text_seq_len + image_fmap_size**2
        if total_seq % sp:
            raise ValueError(
                f"sequence length {total_seq} (text_seq_len {m.text_seq_len} "
                f"+ {image_fmap_size}^2 image tokens) must be divisible by "
                f"mesh.sp={sp} for ring attention; adjust text_seq_len"
            )
    else:
        if attn_impl == "ring":
            raise ValueError(
                'model.attn_impl="ring" needs a sequence-parallel mesh: set '
                "mesh.sp>1 in the trainer (generate/decode paths never use "
                "ring attention — KV-cached decode serves long-context "
                "models there)"
            )
        sp_mesh = None  # inert axis: don't thread a mesh the model won't use
    return DALLE(
        dim=m.dim,
        depth=m.depth,
        heads=m.heads,
        dim_head=m.dim_head,
        num_image_tokens=num_image_tokens,
        image_fmap_size=image_fmap_size,
        num_text_tokens=vocab_size,
        text_seq_len=m.text_seq_len,
        reversible=m.reversible,
        reversible_impl=getattr(m, "reversible_impl", "remat"),
        remat_policy=remat_policy,
        attn_dropout=m.attn_dropout,
        ff_dropout=m.ff_dropout,
        attn_types=m.attn_types_tuple(),
        loss_img_weight=m.loss_img_weight,
        stable=m.stable_softmax,
        sandwich_norm=m.sandwich_norm,
        shift_tokens=m.shift_tokens,
        rotary_emb=m.rotary_emb,
        shared_attn_ids=m.shared_attn_ids_tuple(),
        shared_ff_ids=m.shared_ff_ids_tuple(),
        share_input_output_emb=m.share_input_output_emb,
        text_loss_coeff=cfg.text_loss_coeff,
        img_loss_coeff=cfg.img_loss_coeff,
        text_loss_coeff_inv=cfg.text_loss_coeff_inv,
        img_loss_coeff_inv=cfg.img_loss_coeff_inv,
        attn_impl=attn_impl,
        sp_mesh=sp_mesh,
        executor=executor,
        fused_ce=getattr(m, "fused_ce", False),
        dtype=jnp.bfloat16 if cfg.bf16 else jnp.float32,
    )


def save_dalle_checkpoint(
    path: str,
    cfg: TrainConfig,
    dalle_params,
    vae_params,
    epoch: int,
    vae_class_name: str,
    vae_hparams: Optional[dict] = None,
    opt_state: Any = None,
    train_meta: Optional[dict] = None,
):
    """Portable single-file DALLE ckpt carrying the reference's payload
    ({hparams, vae_params, epoch, version, vae_class_name, weights,
    opt_state, scheduler_state}, `train_dalle.py:432-439,472-479`).
    `vae_hparams` records the ACTUAL frozen VAE geometry (not cfg.vae,
    which may be stale when the VAE came from --vae_path). `opt_state`
    is stored as leaves in tree-flatten order — restorable into any
    optimizer with the same structure (i.e. the same config).
    `train_meta` carries scheduler/global-step state for exact resume."""
    trees = {"dalle": dalle_params}
    if vae_params is not None:
        trees["vae"] = vae_params
    if opt_state is not None:
        leaves = jax.tree_util.tree_leaves(opt_state)
        trees["opt"] = {f"{i:04d}": np.asarray(leaf) for i, leaf in enumerate(leaves)}
    save_params_npz(
        path,
        trees,
        metadata={
            "type": "DALLE",
            "version": __version__,
            "epoch": epoch,
            "vae_class_name": vae_class_name,
            "vae_hparams": vae_hparams,
            "config": config_to_dict(cfg),
            "train": train_meta or {},
        },
    )


def load_dalle_checkpoint(path: str):
    """Returns (cfg, dalle_params, vae_params_or_None, metadata,
    opt_leaves_or_None). Restore the optimizer with
    `restore_opt_state(fresh_opt_state, opt_leaves)`."""
    params, meta = load_params_npz(path)
    assert meta.get("type") == "DALLE", f"{path} is not a DALLE checkpoint"
    cfg = TrainConfig()
    from dalle_pytorch_tpu.training.config import _merge_dict

    _merge_dict(cfg, meta["config"])
    dalle_params = jax.tree.map(jnp.asarray, params["dalle"])
    vae_params = (
        jax.tree.map(jnp.asarray, params["vae"]) if "vae" in params else None
    )
    opt_leaves = None
    if "opt" in params:
        # numeric sort: lexicographic would scramble order past 9999 leaves
        opt_leaves = [params["opt"][k] for k in sorted(params["opt"], key=int)]
    return cfg, dalle_params, vae_params, meta, opt_leaves


def restore_opt_state(fresh_opt_state: Any, opt_leaves):
    """Rebuild a saved optimizer state into `fresh_opt_state`'s structure
    (the resume half of the reference's `opt.load_state_dict`,
    `/root/reference/train_dalle.py:330-338`). Returns the restored state,
    or `fresh_opt_state` unchanged (with a warning) on mismatch — e.g.
    when resuming with a changed optimizer config."""
    if opt_leaves is None:
        return fresh_opt_state
    treedef = jax.tree_util.tree_structure(fresh_opt_state)
    fresh_leaves = jax.tree_util.tree_leaves(fresh_opt_state)
    if len(fresh_leaves) != len(opt_leaves) or any(
        jnp.shape(a) != jnp.shape(b) for a, b in zip(fresh_leaves, opt_leaves)
    ):
        print(
            "WARNING: checkpoint optimizer state does not match the current "
            "optimizer (config changed?) — starting with a fresh optimizer"
        )
        return fresh_opt_state
    return jax.tree_util.tree_unflatten(
        treedef, [jnp.asarray(leaf) for leaf in opt_leaves]
    )


def clip_hparams(clip) -> dict:
    return {
        "dim_text": clip.dim_text,
        "dim_image": clip.dim_image,
        "dim_latent": clip.dim_latent,
        "num_text_tokens": clip.num_text_tokens,
        "text_enc_depth": clip.text_enc_depth,
        "text_seq_len": clip.text_seq_len,
        "text_heads": clip.text_heads,
        "num_visual_tokens": clip.num_visual_tokens,
        "visual_enc_depth": clip.visual_enc_depth,
        "visual_heads": clip.visual_heads,
        "visual_image_size": clip.visual_image_size,
        "visual_patch_size": clip.visual_patch_size,
        "channels": clip.channels,
        # param-layout-affecting: a scan-trained CLIP must reload as scan
        "executor": clip.executor,
    }


def save_clip_checkpoint(path: str, clip, params) -> None:
    """Single-file CLIP checkpoint (hparams + weights), the same logical
    payload shape as the reference's `.pt` saves (`train_dalle.py:432-479`)."""
    save_params_npz(path, params, metadata={"clip_hparams": clip_hparams(clip)})


def load_clip_checkpoint(path: str, dtype=jnp.float32):
    from dalle_pytorch_tpu.models.clip import CLIP

    params, metadata = load_params_npz(path)
    clip = CLIP(dtype=dtype, **metadata["clip_hparams"])
    return clip, params
