"""Metrics, throughput, MFU, and profiler hooks.

Covers the reference's observability surface (SURVEY.md §5.1, §5.5):
  * wandb scalar/image logging, root-gated, with `mode=disabled` in debug
    (`train_dalle.py:367-373,543-587`) — degrades to stdout + PNG files
    when wandb isn't installed;
  * samples/sec probe every 10 steps (`train_dalle.py:578-581`);
  * the DeepSpeed flops-profiler equivalent (`train_dalle.py:389-396,
    583-584`): a `jax.profiler` trace captured around a chosen step, plus
    an analytic FLOPs/MFU estimate every log interval.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Optional

import jax


class MetricsLogger:
    def __init__(
        self,
        project: str,
        config: Optional[dict] = None,
        enabled: bool = True,
        debug: bool = False,
        run_name: Optional[str] = None,
        out_dir: str = "logs",
        entity: Optional[str] = None,
    ):
        self.enabled = enabled
        self.out_dir = Path(out_dir)
        self.run = None
        self._jsonl = None
        if not enabled:
            return
        try:
            import wandb

            self.run = wandb.init(
                project=project,
                name=run_name,
                entity=entity,  # --wandb_entity (`train_dalle.py:119-124`)
                config=config or {},
                mode="disabled" if debug else "online",
            )
        except Exception:
            self.out_dir.mkdir(parents=True, exist_ok=True)
            self._jsonl = open(self.out_dir / "metrics.jsonl", "a")

    @property
    def run_name(self) -> str:
        if self.run is not None and getattr(self.run, "name", None):
            return str(self.run.name)
        return "local"

    def log(self, data: dict, step: Optional[int] = None) -> None:
        if not self.enabled:
            return
        scalars = {
            k: (float(v) if hasattr(v, "item") or isinstance(v, (int, float)) else v)
            for k, v in data.items()
        }
        if self.run is not None:
            self.run.log(scalars, step=step)
        elif self._jsonl is not None:
            self._jsonl.write(json.dumps({"step": step, **scalars}) + "\n")
            self._jsonl.flush()

    def log_images(self, images, caption: str, name: str, step: int) -> None:
        if not self.enabled:
            return
        if self.run is not None:
            import wandb

            self.run.log({name: wandb.Image(images, caption=caption)}, step=step)
        else:
            from dalle_pytorch_tpu.utils.images import save_image_grid

            import numpy as np

            imgs = np.asarray(images)
            if imgs.ndim == 3:
                imgs = imgs[None]
            save_image_grid(imgs, self.out_dir / f"{name}_{step}.png")

    def log_model_artifact(self, path, name: str = "trained-dalle") -> None:
        """Upload a checkpoint as a run artifact (the reference's per-epoch
        wandb.save / Artifact upload, `/root/reference/train_dalle.py:
        481-484`, `train_vae.py:305-310`). No-op without a live wandb run
        (the file already sits on disk in that case)."""
        if not self.enabled or self.run is None:
            return
        try:
            import wandb

            art = wandb.Artifact(name, type="model")
            art.add_file(str(path))
            self.run.log_artifact(art)
        except Exception as e:  # artifact upload must never kill training
            print(f"[metrics] artifact upload failed: {e}")

    def finish(self) -> None:
        if self.run is not None:
            self.run.finish()
        if self._jsonl is not None:
            self._jsonl.close()


class ThroughputMeter:
    """samples/sec every `interval` steps (`train_dalle.py:501-502,578-581`)."""

    def __init__(self, interval: int = 10):
        self.interval = interval
        self._t0 = None
        self._step0 = None

    def update(self, step: int, batch_size: int) -> Optional[float]:
        """Fires on interval crossings and scales by the true step delta,
        so it stays correct when the trainer advances multiple steps per
        call (steps_per_dispatch windows)."""
        if self._t0 is None:
            # initialize on the FIRST call, whatever the step: stride>1
            # step sequences may never land on an exact interval multiple
            self._t0 = time.time()
            self._step0 = step
            return None
        if step // self.interval > self._step0 // self.interval:
            now = time.time()
            rate = batch_size * (step - self._step0) / (now - self._t0)
            self._t0 = now
            self._step0 = step
            return rate
        return None


class ProfilerHook:
    """jax.profiler trace around one step (flops-profiler parity: profile
    step 200, stop training at 201, `train_dalle.py:389-396,583-584`)."""

    def __init__(self, enabled: bool, profile_step: int = 200, out_dir: str = "profiles"):
        self.enabled = enabled
        self.profile_step = profile_step
        self.out_dir = out_dir
        self._active = False
        self._done = False

    def before_step(self, step: int) -> None:
        # >= (not ==): a steps_per_dispatch>1 trainer may never land on the
        # exact step index; profile the first dispatch at/after it instead
        # of stopping later without ever having traced
        if self.enabled and not self._done and step >= self.profile_step:
            Path(self.out_dir).mkdir(parents=True, exist_ok=True)
            jax.profiler.start_trace(self.out_dir)
            self._active = True

    def after_step(self, step: int) -> bool:
        """Returns True when training should stop (profiler finished)."""
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self._done = True
            print(f"[profiler] trace for step {step} written to {self.out_dir}")
        return self.enabled and self._done
