"""Metrics, throughput, MFU, and profiler hooks.

Covers the reference's observability surface (SURVEY.md §5.1, §5.5):
  * wandb scalar/image logging, root-gated, with `mode=disabled` in debug
    (`train_dalle.py:367-373,543-587`) — degrades to stdout + PNG files
    when wandb isn't installed;
  * samples/sec probe every 10 steps (`train_dalle.py:578-581`);
  * the DeepSpeed flops-profiler equivalent (`train_dalle.py:389-396,
    583-584`): a `jax.profiler` trace captured around a chosen step, plus
    an analytic FLOPs/MFU estimate every log interval.
"""

from __future__ import annotations

import bisect
import json
import re
import threading
import time
from collections import deque
from pathlib import Path
from typing import Dict, Iterable, List, NamedTuple, Optional, Sequence, Tuple

import jax


# ------------------------------------------------- Prometheus-style registry
#
# Shared counter/gauge/histogram instruments for the serving layer
# (`dalle_pytorch_tpu/serving/`) and anything else that wants scrapeable
# process metrics. Deliberately tiny and stdlib-only: the serving HTTP
# server renders `registry.render()` at GET /metrics in the Prometheus
# text exposition format. All instruments are thread-safe — the serving
# path observes from request handler threads and the batcher worker.


def _fmt(v: float) -> str:
    """Prometheus number formatting: integers without a trailing .0."""
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class Counter:
    """Monotonically increasing counter."""

    def __init__(self, name: str, help: str = ""):
        self.name, self.help = name, help
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        assert amount >= 0, "counters only go up"
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def render(self, exemplars: bool = False) -> List[str]:
        # OpenMetrics (the exemplars exposition) reserves the _total
        # suffix: the counter FAMILY drops it and only the sample keeps
        # it, else the OpenMetrics parser rejects the whole scrape.
        # Classic text keeps the flat name everywhere.
        fam = (
            self.name[: -len("_total")]
            if exemplars and self.name.endswith("_total")
            else self.name
        )
        return [
            f"# HELP {fam} {self.help}",
            f"# TYPE {fam} counter",
            f"{self.name} {_fmt(self._value)}",
        ]


class Gauge:
    """Instantaneous value (queue depth, in-flight requests, ...)."""

    def __init__(self, name: str, help: str = ""):
        self.name, self.help = name, help
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value

    def render(self, exemplars: bool = False) -> List[str]:
        return [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} gauge",
            f"{self.name} {_fmt(self._value)}",
        ]


# default buckets suit request latencies in seconds AND small occupancy
# counts; instruments that care pass explicit buckets.
_DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class Histogram:
    """Cumulative-bucket histogram plus a bounded reservoir for quantiles.

    Prometheus proper computes quantiles server-side from the buckets; the
    reservoir (last `reservoir_size` observations) lets /metrics also expose
    ready-made p50/p95 gauges so a bare `curl` shows latency percentiles
    without a Prometheus deployment.
    """

    def __init__(
        self,
        name: str,
        help: str = "",
        buckets: Sequence[float] = _DEFAULT_BUCKETS,
        reservoir_size: int = 1024,
    ):
        self.name, self.help = name, help
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +inf bucket last
        self._sum = 0.0
        self._count = 0
        self._recent: deque = deque(maxlen=reservoir_size)
        # most recent exemplar-carrying observation: (value, trace_id, unix
        # time). Exposed via `render(exemplars=True)` in OpenMetrics
        # exemplar syntax so a scrape can jump from a latency bucket to
        # the exact trace that landed there.
        self._exemplar = None
        self._lock = threading.Lock()

    def observe(self, value: float, exemplar: Optional[str] = None) -> None:
        v = float(value)
        with self._lock:
            self._counts[bisect.bisect_left(self.buckets, v)] += 1
            self._sum += v
            self._count += 1
            self._recent.append(v)
            if exemplar:
                self._exemplar = (v, str(exemplar), time.time())

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def bucket_counts(self):
        """Consistent snapshot for rolling-window readers (the SLO burn-rate
        tracker diffs these between ticks): (bucket bounds, per-bucket
        counts with +Inf last, total count, sum) under the lock."""
        with self._lock:
            return self.buckets, tuple(self._counts), self._count, self._sum

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile over the recent-observation reservoir
        (0.0 when nothing has been observed yet)."""
        with self._lock:
            if not self._recent:
                return 0.0
            ordered = sorted(self._recent)
            idx = min(len(ordered) - 1, max(0, int(q * len(ordered))))
            return ordered[idx]

    def mean(self) -> float:
        with self._lock:
            return self._sum / self._count if self._count else 0.0

    def render(self, exemplars: bool = False) -> List[str]:
        with self._lock:
            lines = [
                f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} histogram",
            ]
            # OpenMetrics exemplar: appended to the ONE bucket line whose
            # range the exemplar value falls in (cumulative buckets, so
            # that's the first le >= value)
            ex_idx, ex_suffix = None, ""
            if exemplars and self._exemplar is not None:
                ev, etid, ets = self._exemplar
                ex_idx = bisect.bisect_left(self.buckets, ev)
                ex_suffix = (
                    f' # {{trace_id="{etid}"}} {_fmt(ev)} {round(ets, 3)}'
                )
            cum = 0
            for i, (bound, n) in enumerate(zip(self.buckets, self._counts)):
                cum += n
                suffix = ex_suffix if i == ex_idx else ""
                lines.append(
                    f'{self.name}_bucket{{le="{_fmt(bound)}"}} {cum}{suffix}'
                )
            suffix = ex_suffix if ex_idx == len(self.buckets) else ""
            lines.append(
                f'{self.name}_bucket{{le="+Inf"}} {self._count}{suffix}'
            )
            lines.append(f"{self.name}_sum {_fmt(self._sum)}")
            lines.append(f"{self.name}_count {self._count}")
        # convenience percentile gauges from the reservoir (outside the
        # lock: percentile() re-acquires it)
        for q, suffix in ((0.5, "p50"), (0.95, "p95")):
            qn = f"{self.name}_{suffix}"
            lines.append(f"# TYPE {qn} gauge")
            lines.append(f"{qn} {_fmt(self.percentile(q))}")
        return lines


class Family:
    """Labeled instrument family: one metric name, one label, N children.

    Minimal Prometheus label support for the serving layer (per-compiled-
    shape occupancy/batch-seconds series): `labels(value)` get-or-creates a
    child instrument, and `render()` emits ONE HELP/TYPE header followed by
    every child's samples tagged `{label_name="value"}` — the exposition
    shape scrapers expect for labeled series. Children are full instruments
    (Counter/Gauge/Histogram), so observation is lock-protected as usual;
    labeled histograms skip the convenience p50/p95 gauges (Prometheus
    computes quantiles from the buckets server-side).
    """

    def __init__(self, cls, name: str, help: str, label_name: str, **kw):
        self.cls, self.name, self.help = cls, name, help
        self.label_name = label_name
        self._kw = kw
        self._children: Dict[str, object] = {}
        self._lock = threading.Lock()

    def labels(self, value) -> object:
        key = str(value)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self.cls(self.name, self.help, **self._kw)
                child._label_suffix = f'{self.label_name}="{key}"'
                self._children[key] = child
            return child

    def labels_extra(self, value, **extra) -> object:
        """Child carrying the family label PLUS extra label dimensions —
        the per-shard series (`dalle_serving_mfu{program=,device=}`)
        without registering a second family per dimension. Children are
        keyed by the full rendered label set, so plain `labels(value)`
        children and extra-labeled ones coexist under one HELP/TYPE
        header."""
        pairs = [f'{self.label_name}="{value}"'] + [
            f'{k}="{v}"' for k, v in sorted(extra.items())
        ]
        suffix = ",".join(pairs)
        with self._lock:
            child = self._children.get(suffix)
            if child is None:
                child = self.cls(self.name, self.help, **self._kw)
                child._label_suffix = suffix
                self._children[suffix] = child
            return child

    def items(self) -> List:
        """Snapshot of (label value, child instrument) pairs — the public
        read surface for per-label reporting (bench_serving's per-stage
        breakdown reads the stage family through this)."""
        with self._lock:
            return sorted(self._children.items())

    def render(self, exemplars: bool = False) -> List[str]:
        children = self.items()
        type_name = {Counter: "counter", Gauge: "gauge", Histogram: "histogram"}[
            self.cls
        ]
        fam = (
            self.name[: -len("_total")]
            if exemplars and self.cls is Counter
            and self.name.endswith("_total")
            else self.name
        )
        lines = [
            f"# HELP {fam} {self.help}",
            f"# TYPE {fam} {type_name}",
        ]
        for _, child in children:
            lines.extend(_render_samples(child, exemplars=exemplars))
        return lines


def _render_samples(inst, exemplars: bool = False) -> List[str]:
    """Sample lines of an instrument with its family label spliced in."""
    label = getattr(inst, "_label_suffix", "")
    out = []
    for line in inst.render(exemplars=exemplars):
        if line.startswith("#"):
            continue  # family emits HELP/TYPE once
        name, value = line.split(" ", 1)
        if "_p50" in name or "_p95" in name:
            continue  # reservoir quantiles stay on unlabeled instruments
        if "{" in name:  # histogram bucket: merge labels
            base, rest = name.split("{", 1)
            name = f"{base}{{{label},{rest}" if label else name
        elif label:
            name = f"{name}{{{label}}}"
        out.append(f"{name} {value}")
    return out


class MetricsRegistry:
    """Named instrument registry rendering Prometheus text exposition.

    `counter/gauge/histogram` are get-or-create (idempotent by name), so
    independently constructed components can share instruments.
    """

    def __init__(self):
        self._instruments: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name: str, help: str, **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, help, **kw)
                self._instruments[name] = inst
            assert isinstance(inst, cls), (
                f"metric {name!r} already registered as {type(inst).__name__}"
            )
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(
        self, name: str, help: str = "",
        buckets: Sequence[float] = _DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def _family(self, cls, name: str, help: str, label_name: str, **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = Family(cls, name, help, label_name, **kw)
                self._instruments[name] = inst
            assert isinstance(inst, Family) and inst.cls is cls, (
                f"metric {name!r} already registered as {type(inst).__name__}"
            )
            return inst

    def histogram_family(
        self, name: str, help: str = "", label_name: str = "shape",
        buckets: Sequence[float] = _DEFAULT_BUCKETS,
    ) -> Family:
        return self._family(
            Histogram, name, help, label_name, buckets=buckets
        )

    def gauge_family(
        self, name: str, help: str = "", label_name: str = "name"
    ) -> Family:
        """Labeled gauge series (per-program MFU, per-SLO burn rate)."""
        return self._family(Gauge, name, help, label_name)

    def counter_family(
        self, name: str, help: str = "", label_name: str = "name"
    ) -> Family:
        """Labeled counter series (stall events by reason)."""
        return self._family(Counter, name, help, label_name)

    def get(self, name: str):
        return self._instruments.get(name)

    def render(self, exemplars: bool = False) -> str:
        """Prometheus text exposition. `exemplars=True` switches to the
        OpenMetrics flavor: exemplar annotations (`# {trace_id="..."}`)
        on histogram buckets that recorded one, plus the mandatory
        `# EOF` terminator — serve it with the
        `application/openmetrics-text` content type (the HTTP layer
        does); classic Prometheus text parsers reject the syntax."""
        with self._lock:
            instruments = sorted(self._instruments.items())
        lines: List[str] = []
        for _, inst in instruments:
            lines.extend(inst.render(exemplars=exemplars))
        if exemplars:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"


# ----------------------------------------------- exposition text parsing
#
# The inverse of `MetricsRegistry.render()`, for the fleet scraper
# (obs/fleetmetrics.py): a router-side poller pulls each replica's
# GET /metrics body and needs the samples back as typed values to
# federate, delta, and roll up. Tolerates both exposition flavors this
# registry emits — classic text and the OpenMetrics exemplar variant
# (`_total`-stripped counter family names, `# {...}` bucket exemplars,
# trailing `# EOF`) — and the convenience `_p50`/`_p95` gauge lines that
# carry a TYPE header but no HELP.


class ParsedSample(NamedTuple):
    """One exposition sample line: full rendered name (`foo_total`,
    `foo_bucket`, ...), label dict, numeric value."""

    name: str
    labels: Dict[str, str]
    value: float

    def key(self) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
        """Hashable series identity (name + sorted labels) — the join
        key for cross-scrape deltas and cross-replica rollups."""
        return self.name, tuple(sorted(self.labels.items()))


class ParsedFamily:
    """All samples of one metric family plus its TYPE/HELP metadata."""

    __slots__ = ("name", "type", "help", "samples")

    def __init__(self, name: str, type: str = "untyped", help: str = ""):
        self.name, self.type, self.help = name, type, help
        self.samples: List[ParsedSample] = []

    def histogram_series(self) -> Dict[Tuple[Tuple[str, str], ...], Dict]:
        """Reassemble `_bucket`/`_sum`/`_count` samples into per-series
        histogram points keyed by the non-`le` label set: each value is
        `{"bounds": [...], "cum": [...], "count": int, "sum": float}`
        with cumulative bucket counts and `+Inf` folded into `count`."""
        out: Dict[Tuple[Tuple[str, str], ...], Dict] = {}

        def point(labels: Dict[str, str]) -> Dict:
            k = tuple(sorted(
                (n, v) for n, v in labels.items() if n != "le"
            ))
            return out.setdefault(
                k, {"bounds": [], "cum": [], "count": 0, "sum": 0.0}
            )

        for s in self.samples:
            if s.name == f"{self.name}_bucket":
                le = s.labels.get("le", "+Inf")
                if le == "+Inf":
                    point(s.labels)["count"] = int(s.value)
                else:
                    p = point(s.labels)
                    p["bounds"].append(float(le))
                    p["cum"].append(int(s.value))
            elif s.name == f"{self.name}_sum":
                point(s.labels)["sum"] = float(s.value)
            elif s.name == f"{self.name}_count":
                point(s.labels)["count"] = int(s.value)
        for p in out.values():
            order = sorted(range(len(p["bounds"])), key=p["bounds"].__getitem__)
            p["bounds"] = [p["bounds"][i] for i in order]
            p["cum"] = [p["cum"][i] for i in order]
        return out


_SAMPLE_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
#: sample-name suffixes that attach a line to a declared family; classic
#: counters match their family name exactly, OpenMetrics counters add
#: `_total`, histograms fan out into bucket/sum/count
_FAMILY_SUFFIXES = ("", "_total", "_bucket", "_sum", "_count")


def _unescape_label(v: str) -> str:
    return v.replace('\\"', '"').replace("\\n", "\n").replace("\\\\", "\\")


def _parse_sample_line(line: str) -> ParsedSample:
    """`name[{labels}] value[ # exemplar...]` → ParsedSample. Raises
    ValueError on anything malformed (the scraper treats that as a
    failed scrape, not a partial one)."""
    name, labels_part, rest = line, "", ""
    brace = line.find("{")
    if brace >= 0:
        close = line.find("}", brace)
        if close < 0:
            raise ValueError(f"unterminated label block: {line!r}")
        name = line[:brace]
        labels_part = line[brace + 1:close]
        rest = line[close + 1:].strip()
    else:
        try:
            name, rest = line.split(None, 1)
        except ValueError:
            raise ValueError(f"sample line without a value: {line!r}")
    if not _SAMPLE_NAME_RE.match(name):
        raise ValueError(f"bad sample name in line: {line!r}")
    labels: Dict[str, str] = {}
    if labels_part:
        matched = _LABEL_RE.findall(labels_part)
        stripped = _LABEL_RE.sub("", labels_part).replace(",", "").strip()
        if stripped:
            raise ValueError(f"bad label block: {labels_part!r}")
        labels = {k: _unescape_label(v) for k, v in matched}
    # an OpenMetrics exemplar trails the value as ` # {...} v ts`
    value_token = rest.split(" # ", 1)[0].strip().split()
    if len(value_token) != 1:
        raise ValueError(f"bad sample value in line: {line!r}")
    tok = value_token[0]
    try:
        value = float("inf") if tok == "+Inf" else float(tok)
    except ValueError:
        raise ValueError(f"non-numeric sample value {tok!r} in {line!r}")
    return ParsedSample(name, labels, value)


def parse_exposition(text: str) -> Dict[str, ParsedFamily]:
    """Parse Prometheus text exposition (as `MetricsRegistry.render`
    emits it, either flavor) back into `{family name: ParsedFamily}`.

    Strict on sample lines — a truncated or garbage body raises
    ValueError rather than returning half a scrape — but permissive on
    metadata: unknown comment lines are skipped, TYPE without HELP is
    fine (the `_p50`/`_p95` convenience gauges), and samples with no
    declared family land in an `untyped` one.
    """
    families: Dict[str, ParsedFamily] = {}

    def family_for(sample_name: str) -> ParsedFamily:
        for suffix in _FAMILY_SUFFIXES:
            if suffix and not sample_name.endswith(suffix):
                continue
            base = sample_name[: len(sample_name) - len(suffix)] if suffix \
                else sample_name
            fam = families.get(base)
            if fam is not None:
                return fam
        fam = families.setdefault(sample_name, ParsedFamily(sample_name))
        return fam

    for raw_line in text.splitlines():
        line = raw_line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                fam = families.setdefault(parts[2], ParsedFamily(parts[2]))
                fam.type = parts[3] if len(parts) > 3 else "untyped"
            elif len(parts) >= 3 and parts[1] == "HELP":
                fam = families.setdefault(parts[2], ParsedFamily(parts[2]))
                fam.help = parts[3] if len(parts) > 3 else ""
            # anything else (# EOF, stray comments) is skippable metadata
            continue
        families_sample = _parse_sample_line(line)
        family_for(families_sample.name).samples.append(families_sample)
    return families


def counter_delta(prev: Optional[float], cur: float) -> float:
    """Reset-aware counter delta: a monotonic counter that went DOWN
    means the replica restarted (a supervised crash/recovery) — clamp
    the delta to 0 rather than going negative; the post-restart
    increments land in the following scrapes once the new baseline is
    recorded. `prev=None` (first sight of the series) also reads as 0:
    a scraper joining mid-life must not claim the replica's whole
    counter history as one interval's work."""
    if prev is None or cur < prev:
        return 0.0
    return float(cur - prev)


def merge_histogram_points(points: Iterable[Dict]) -> Dict:
    """Merge per-replica histogram points (the `histogram_series()`
    shape) into one fleet histogram. Identical bucket bounds — the
    common case, every replica runs the same instrument definitions —
    merge exactly (cumulative counts sum). Mismatched bounds merge on
    the union grid, flooring each histogram's cumulative count at
    unknown bounds to its nearest LOWER known bound (an undercount
    bias, never an overcount)."""
    points = [p for p in points if p is not None]
    if not points:
        return {"bounds": [], "cum": [], "count": 0, "sum": 0.0}
    bounds: List[float] = sorted({b for p in points for b in p["bounds"]})

    def cum_at(p: Dict, bound: float) -> int:
        idx = bisect.bisect_right(p["bounds"], bound) - 1
        return int(p["cum"][idx]) if idx >= 0 else 0

    return {
        "bounds": bounds,
        "cum": [sum(cum_at(p, b) for p in points) for b in bounds],
        "count": int(sum(p["count"] for p in points)),
        "sum": float(sum(p["sum"] for p in points)),
    }


def render_histogram_point(name: str, point: Dict,
                           labels: str = "") -> List[str]:
    """Exposition bucket/sum/count lines for one merged histogram point
    (no HELP/TYPE header — the caller owns family metadata). `labels`
    is a pre-rendered `k="v"` list spliced before `le`."""
    prefix = f"{labels}," if labels else ""
    lines = [
        f'{name}_bucket{{{prefix}le="{_fmt(b)}"}} {int(c)}'
        for b, c in zip(point["bounds"], point["cum"])
    ]
    lines.append(f'{name}_bucket{{{prefix}le="+Inf"}} {int(point["count"])}')
    suffix = f"{{{labels}}}" if labels else ""
    lines.append(f'{name}_sum{suffix} {_fmt(point["sum"])}')
    lines.append(f'{name}_count{suffix} {int(point["count"])}')
    return lines


class MetricsLogger:
    def __init__(
        self,
        project: str,
        config: Optional[dict] = None,
        enabled: bool = True,
        debug: bool = False,
        run_name: Optional[str] = None,
        out_dir: str = "logs",
        entity: Optional[str] = None,
    ):
        self.enabled = enabled
        self.out_dir = Path(out_dir)
        self.run = None
        self._jsonl = None
        if not enabled:
            return
        try:
            import wandb

            self.run = wandb.init(
                project=project,
                name=run_name,
                entity=entity,  # --wandb_entity (`train_dalle.py:119-124`)
                config=config or {},
                mode="disabled" if debug else "online",
            )
        except Exception:
            self.out_dir.mkdir(parents=True, exist_ok=True)
            self._jsonl = open(self.out_dir / "metrics.jsonl", "a")

    @property
    def run_name(self) -> str:
        if self.run is not None and getattr(self.run, "name", None):
            return str(self.run.name)
        return "local"

    def log(self, data: dict, step: Optional[int] = None) -> None:
        if not self.enabled:
            return
        scalars = {
            k: (float(v) if hasattr(v, "item") or isinstance(v, (int, float)) else v)
            for k, v in data.items()
        }
        if self.run is not None:
            self.run.log(scalars, step=step)
        elif self._jsonl is not None:
            self._jsonl.write(json.dumps({"step": step, **scalars}) + "\n")
            self._jsonl.flush()

    def log_images(self, images, caption: str, name: str, step: int) -> None:
        if not self.enabled:
            return
        if self.run is not None:
            import wandb

            self.run.log({name: wandb.Image(images, caption=caption)}, step=step)
        else:
            from dalle_pytorch_tpu.utils.images import save_image_grid

            import numpy as np

            imgs = np.asarray(images)
            if imgs.ndim == 3:
                imgs = imgs[None]
            save_image_grid(imgs, self.out_dir / f"{name}_{step}.png")

    def log_model_artifact(self, path, name: str = "trained-dalle") -> None:
        """Upload a checkpoint as a run artifact (the reference's per-epoch
        wandb.save / Artifact upload, `/root/reference/train_dalle.py:
        481-484`, `train_vae.py:305-310`). No-op without a live wandb run
        (the file already sits on disk in that case)."""
        if not self.enabled or self.run is None:
            return
        try:
            import wandb

            art = wandb.Artifact(name, type="model")
            art.add_file(str(path))
            self.run.log_artifact(art)
        except Exception as e:  # artifact upload must never kill training
            print(f"[metrics] artifact upload failed: {e}")

    def finish(self) -> None:
        if self.run is not None:
            self.run.finish()
        if self._jsonl is not None:
            self._jsonl.close()


class ThroughputMeter:
    """samples/sec every `interval` steps (`train_dalle.py:501-502,578-581`)."""

    def __init__(self, interval: int = 10):
        self.interval = interval
        self._t0 = None
        self._step0 = None

    def update(self, step: int, batch_size: int) -> Optional[float]:
        """Fires on interval crossings and scales by the true step delta,
        so it stays correct when the trainer advances multiple steps per
        call (steps_per_dispatch windows)."""
        if self._t0 is None:
            # initialize on the FIRST call, whatever the step: stride>1
            # step sequences may never land on an exact interval multiple
            self._t0 = time.time()
            self._step0 = step
            return None
        if step // self.interval > self._step0 // self.interval:
            now = time.time()
            rate = batch_size * (step - self._step0) / (now - self._t0)
            self._t0 = now
            self._step0 = step
            return rate
        return None


class ProfilerHook:
    """jax.profiler trace around one step (flops-profiler parity: profile
    step 200, stop training at 201, `train_dalle.py:389-396,583-584`)."""

    def __init__(self, enabled: bool, profile_step: int = 200, out_dir: str = "profiles"):
        self.enabled = enabled
        self.profile_step = profile_step
        self.out_dir = out_dir
        self._active = False
        self._done = False

    def before_step(self, step: int) -> None:
        # >= (not ==): a steps_per_dispatch>1 trainer may never land on the
        # exact step index; profile the first dispatch at/after it instead
        # of stopping later without ever having traced
        if self.enabled and not self._done and step >= self.profile_step:
            Path(self.out_dir).mkdir(parents=True, exist_ok=True)
            jax.profiler.start_trace(self.out_dir)
            self._active = True

    def after_step(self, step: int) -> bool:
        """Returns True when training should stop (profiler finished)."""
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self._done = True
            print(f"[profiler] trace for step {step} written to {self.out_dir}")
        return self.enabled and self._done
