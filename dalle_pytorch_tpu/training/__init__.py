from dalle_pytorch_tpu.training.steps import (
    TrainState,
    make_optimizer,
    make_vae_train_step,
    make_dalle_train_step,
    make_clip_train_step,
    make_multi_step,
    stack_batches,
    window_iter,
    window_keys,
    set_learning_rate,
    get_learning_rate,
)
from dalle_pytorch_tpu.training.lr import ReduceLROnPlateau, ExponentialDecay
