"""Single config system with composable presets.

The reference has THREE coexisting flag systems (SURVEY.md §5.6): a Hydra
YAML tree for train_dalle (`/root/reference/config/config.yaml`), argparse
for train_vae/generate, and the legacy full argparse surface
(`tmp_main.py:34-144`). Here there is exactly one: a dataclass tree,
loadable from YAML, overridable with dotted `key=value` strings (hydra-
style), with named experiment presets replacing the `config/exp/*.yaml`
group (f/ff/r/ro -> objective mode).

Every reference flag has a field here (same names where sensible), plus
the TPU-mesh fields the reference delegates to DeepSpeed/Horovod.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, List, Optional, Sequence, Tuple

# exp presets (`config/exp/{f,ff,r,ro}.yaml`)
EXP_PRESETS = {
    "f": "forward_only",
    "ff": "forward_forward",
    "r": "forward_reverse_partial",
    "ro": "reverse_only",
}


@dataclass
class MeshConfig:
    dp: int = -1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    # pipeline parallelism (parallel/gpipe.py GPipe schedule over the
    # transformer trunk). pp > 1 requires executor="scan", zero dropout
    # (the pp trunk is deterministic by design — models/dalle.py), a mode
    # without reversed layer order, and dp/fsdp/tp/sp all 1 (pure-pp
    # mesh; compose dp x pp via parallel/gpipe.pipeline_layers directly)
    pp: int = 1
    pp_micro: int = 4  # GPipe microbatches per step (batch % pp_micro == 0)


@dataclass
class VaeConfig:
    image_size: int = 128
    num_tokens: int = 8192
    codebook_dim: int = 512
    num_layers: int = 3
    num_resnet_blocks: int = 0
    hidden_dim: int = 64
    channels: int = 3
    smooth_l1_loss: bool = False
    temperature: float = 0.9
    straight_through: bool = False
    reinmax: bool = False
    kl_loss_weight: float = 0.0
    # gumbel temperature annealing (`train_vae.py:278`)
    anneal_rate: float = 1e-6
    temp_min: float = 0.5


@dataclass
class DalleConfig:
    dim: int = 512
    text_seq_len: int = 256
    depth: int = 2
    heads: int = 8
    dim_head: int = 64
    ff_dropout: float = 0.0
    attn_dropout: float = 0.0
    reversible: bool = False
    reversible_impl: str = "remat"  # remat | revnet
    # jax.checkpoint policy for the remat executor (e.g.
    # "dots_with_no_batch_dims_saveable"); None = full recompute
    remat_policy: "Optional[str]" = None
    loss_img_weight: float = 7.0
    attn_types: str = "full"  # comma separated
    shift_tokens: bool = False
    rotary_emb: bool = False
    shared_attn_ids: Optional[str] = None  # comma separated
    shared_ff_ids: Optional[str] = None
    share_input_output_emb: bool = False
    stable_softmax: bool = False
    sandwich_norm: bool = False
    num_text_tokens: int = 10000  # overridden by tokenizer vocab size
    # vocab-chunked cross-entropy (ops/losses.py): forward objective
    # without materializing [B, N, vocab] logits
    fused_ce: bool = False
    # attention kernel selection: "dense" | "flash" (in-repo Pallas) |
    # "lib_flash" (jax library TPU kernel, plain causal/full only) |
    # "ring" (sequence-parallel over the mesh sp axis) | "auto" (dense
    # below AUTO_FLASH_MIN_SEQ, flash above; ring when mesh.sp > 1)
    attn_impl: str = "auto"
    # layer executor: "unrolled" | "scan" (nn.scan over depth-stacked
    # params — ~depth× smaller program/compile; masked attn_types run as
    # dense + scanned pattern masks, no shared ids; cached decode is
    # native, pattern masks included)
    executor: str = "unrolled"

    def attn_types_tuple(self) -> Tuple[str, ...]:
        return tuple(s.strip() for s in self.attn_types.split(",") if s.strip())

    @staticmethod
    def _ids(spec: Optional[str]) -> Optional[Tuple[int, ...]]:
        if not spec:
            return None
        return tuple(int(s) for s in str(spec).split(","))

    def shared_attn_ids_tuple(self):
        return self._ids(self.shared_attn_ids)

    def shared_ff_ids_tuple(self):
        return self._ids(self.shared_ff_ids)


@dataclass
class TrainConfig:
    # run / logging (`config/config.yaml`)
    debug: bool = False
    project: str = "dalle_pytorch_tpu"
    mode: str = "forward_only"
    exp: Optional[str] = None  # preset key overriding mode
    wandb_name: str = "dalle_train_transformer"
    wandb_entity: Optional[str] = None
    # accepted for reference-CLI parity (`config/config.yaml`); the
    # trainer, like the reference's, generates one sample per log step
    wandb_num_images: int = 4
    log_images_freq: int = 1000

    # paths
    vae_path: Optional[str] = None
    dalle_path: Optional[str] = None
    vqgan_model_path: Optional[str] = None
    vqgan_config_path: Optional[str] = None
    image_text_folder: Optional[str] = None
    tokens_path: Optional[str] = None  # precompute_tokens.py artifact
    wds: str = ""
    output_dir: str = "checkpoints"
    dalle_output_file_name: str = "dalle"

    # tokenizer flags (`train_dalle.py:131-135`)
    chinese: bool = False
    taming: bool = False
    hug: bool = False
    yttm: bool = False
    native: bool = False  # framework-native C++ BPE (native/bpe.cpp)
    bpe_path: Optional[str] = None
    truncate_captions: bool = False

    # data
    resize_ratio: float = 0.75
    class_name_json: Optional[str] = None

    # optimization
    epochs: int = 20
    save_every_n_steps: int = 1000
    keep_n_checkpoints: Optional[int] = None
    batch_size: int = 4
    ga_steps: int = 1
    # optimizer steps scanned into ONE device dispatch (make_multi_step):
    # eliminates the host-loop round trip per step — the dominant cost on
    # synchronous-dispatch backends. Logging/checkpoint cadences fire on
    # interval crossings, so their effective granularity becomes this many
    # steps. 1 = classic per-step host loop.
    steps_per_dispatch: int = 1
    # batches assembled ahead of the step by the prefetch thread
    # (DataLoader-workers equivalent, `train_dalle.py:309-316`); 0 would
    # mean no lookahead but still off-thread assembly
    prefetch_depth: int = 2
    learning_rate: float = 3e-4
    clip_grad_norm: float = 0.5
    lr_decay: bool = False
    null_cond_prob: float = 0.0
    seed: int = 42

    # precision / profiling
    bf16: bool = True  # replaces --fp16/--amp (`train_dalle.py:326,385-388`)
    flops_profiler: bool = False

    # inverse-objective coefficients (`config/config.yaml:21-24`)
    text_loss_coeff: float = 1.0
    text_loss_coeff_inv: float = 7.0
    img_loss_coeff: float = 7.0
    img_loss_coeff_inv: float = 1.0

    model: DalleConfig = field(default_factory=DalleConfig)
    vae: VaeConfig = field(default_factory=VaeConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)

    def resolve(self) -> "TrainConfig":
        if self.exp:
            assert self.exp in EXP_PRESETS, f"unknown exp preset {self.exp}"
            self.mode = EXP_PRESETS[self.exp]
        return self


def _set_dotted(obj: Any, key: str, value: Any) -> None:
    parts = key.split(".")
    for p in parts[:-1]:
        obj = getattr(obj, p)
    leaf = parts[-1]
    if not hasattr(obj, leaf):
        raise KeyError(f"unknown config key: {key}")
    current = getattr(obj, leaf)
    if isinstance(current, bool):
        value = str(value).lower() in ("1", "true", "yes", "on")
    elif isinstance(current, int) and not isinstance(current, bool):
        value = int(value)
    elif isinstance(current, float):
        value = float(value)
    elif value in ("null", "None", ""):
        value = None
    elif current is None and isinstance(value, str):
        # Optional[int/float] fields (e.g. keep_n_checkpoints): infer type
        for cast in (int, float):
            try:
                value = cast(value)
                break
            except ValueError:
                continue
    setattr(obj, leaf, value)


def _merge_dict(cfg: Any, data: dict, prefix: str = "") -> None:
    for k, v in data.items():
        if isinstance(v, dict) and dataclasses.is_dataclass(getattr(cfg, k, None)):
            _merge_dict(getattr(cfg, k), v)
        else:
            _set_dotted(cfg, k, v) if not isinstance(v, (dict, list)) else setattr(cfg, k, v)


def load_config(
    yaml_path: Optional[str] = None, overrides: Sequence[str] = ()
) -> TrainConfig:
    """YAML file (optional) + `key=value` / `section.key=value` overrides."""
    cfg = TrainConfig()
    if yaml_path:
        import yaml

        with open(yaml_path) as f:
            data = yaml.safe_load(f) or {}
        _merge_dict(cfg, data)
    for ov in overrides:
        assert "=" in ov, f"override must be key=value, got {ov!r}"
        key, value = ov.split("=", 1)
        _set_dotted(cfg, key.strip(), value.strip())
    return cfg.resolve()


def config_to_dict(cfg) -> dict:
    return dataclasses.asdict(cfg)
