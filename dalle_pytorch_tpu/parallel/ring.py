"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

The reference has NO sequence/context parallelism (SURVEY.md §5.7) — it
scales sequence length with attention *sparsity* instead. This module goes
beyond parity: the sequence is sharded over the `sp` mesh axis, each device
holds one block of queries, and key/value blocks rotate around the ring via
`ppermute` over ICI while a streaming (flash-style) log-sum-exp
accumulator builds the exact softmax — O(n/P) memory per device, compute
overlapped with neighbor communication by XLA's async collective
scheduling.

Use `ring_attention` inside `shard_map` (axis name "sp"), or the
`ring_attention_sharded` convenience wrapper for a full [B, H, N, D] array
sharded along N.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dalle_pytorch_tpu.parallel.mesh import axis_size, shard_map

_NEG = -1e30


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Per-shard blocks q,k,v: [B, H, n_local, D]; returns [B, H, n_local, D].

    Shard i owns global positions [i*n_local, (i+1)*n_local). Must run
    inside shard_map over `axis_name`.
    """
    n_shards = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, h, n_local, d = q.shape
    scale = d**-0.5 if scale is None else scale

    q = q * scale
    q_pos = idx * n_local + jnp.arange(n_local)

    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    def step(carry, s):
        k_blk, v_blk, m, l, acc = carry
        kv_idx = (idx - s) % n_shards
        k_pos = kv_idx * n_local + jnp.arange(n_local)

        scores = jnp.einsum(
            "bhid,bhjd->bhij", q, k_blk, preferred_element_type=jnp.float32
        )
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, _NEG)

        m_new = jnp.maximum(m, scores.max(axis=-1, keepdims=True))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new)
        l = l * corr + p.sum(axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum(
            "bhij,bhjd->bhid", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32,
        )
        # rotate kv blocks one hop around the ring (ICI neighbor exchange)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, m_new, l, acc), None

    # derive the accumulators from q so they carry q's varying manual axes
    # (shard_map's vma typing rejects invariant carries updated with
    # varying values)
    m0 = jnp.full_like(q[..., :1], _NEG, dtype=jnp.float32)
    l0 = jnp.zeros_like(q[..., :1], dtype=jnp.float32)
    acc0 = jnp.zeros_like(q, dtype=jnp.float32)

    (_, _, _, l, acc), _ = lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n_shards)
    )
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention_sharded(
    mesh: Mesh,
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    batch_axes=("dp", "fsdp"),
    seq_axis: str = "sp",
) -> jnp.ndarray:
    """Wrapper: q,k,v [B, H, N, D] with N sharded over `seq_axis`.

    The batch axis is sharded over `batch_axes` when its size divides their
    product, else replicated — so abstract traces with unsharded batches
    (model.init with batch 1, small eval forwards) still compile; training
    batches (sized by the data loader to dp*fsdp) get the real sharding.
    """
    dp_extent = 1
    for a in batch_axes:
        dp_extent *= mesh.shape.get(a, 1)
    b_axes = batch_axes if q.shape[0] % dp_extent == 0 else None
    spec = P(b_axes, None, seq_axis, None)
    fn = shard_map(
        partial(ring_attention, axis_name=seq_axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
