"""GPipe-style pipeline parallelism over a `pp` mesh axis.

The reference has no pipeline parallelism at all (SURVEY.md §2.2 row PP:
"none") — its depth scaling is reversibility + DeepSpeed ZeRO. On TPU the
idiomatic construction is SPMD: shard the depth-stacked layer parameters
over a `pp` mesh axis and move ACTIVATIONS between stages with
`lax.ppermute` inside `shard_map`, exactly like ring attention moves K/V
blocks (`parallel/ring.py`). XLA lowers the permute onto ICI
neighbor links; the schedule below is classic GPipe: M microbatches flow
through P stages in M + P - 1 ticks, each stage running its local slice
of layers per tick (bubble fraction (P-1)/(M+P-1)).

Everything is a pure jittable function — `jax.grad` differentiates
straight through the schedule (ppermute's transpose is the reverse
permutation; the backward pipeline runs automatically in reverse), so a
training step needs no hand-written backward schedule.

Scope: a generic engine over any `layer_fn(layer_params, x) -> x` whose
parameters are depth-stacked pytrees ([depth, ...] leaves — the same
layout the scan executor trains and checkpoints,
`models/transformer.py` `executor="scan"`). Numerical parity with
sequential execution (fwd AND grads) is pinned by
`tests/test_gpipe.py` on a virtual 8-device CPU mesh.
"""

from __future__ import annotations

from typing import Callable

import jax

from dalle_pytorch_tpu.parallel.mesh import axis_size, shard_map
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_pp_mesh(pp: int, devices=None) -> Mesh:
    """1-axis ('pp',) mesh over the first `pp` devices."""
    devices = list(devices if devices is not None else jax.devices())
    assert pp <= len(devices), f"pp={pp} > {len(devices)} devices"
    return Mesh(np.asarray(devices[:pp]), ("pp",))


def stage_params_sharding(mesh: Mesh, params):
    """Shardings placing depth-stacked [P*L, ...] leaves over the pp axis
    (leading axis split across stages). Routed through the same
    divisibility fallback as every other placement (tracelint TL020): a
    leaf whose leading dim does not divide by pp replicates instead of
    sharding unevenly — unreachable for the [P, L, ...] stacks
    `gpipe_apply` reshapes, but callers can hand arbitrary pytrees."""
    from dalle_pytorch_tpu.parallel.partition import _divisible

    return jax.tree.map(
        lambda leaf: NamedSharding(
            mesh, _divisible(P("pp"), leaf.shape, mesh)
        ),
        params,
    )


def pipeline_layers(
    layer_fn: Callable,
    stage_params,
    microbatches: jax.Array,
    *,
    axis_name: str,
    n_micro: int,
    aux=None,
):
    """The inside-shard_map GPipe stage program (ring.py pattern: a pure
    per-device function parameterized by `axis_name`, so it composes with
    ANY caller mesh that carries a pipeline axis — alongside dp/fsdp/tp
    axes in a pjit train step, not only the standalone mesh
    `gpipe_apply` builds).

    stage_params: THIS stage's [L, ...] layer slice
    microbatches: [n_micro, mb, ...] (replicated; only stage 0 reads them)
    aux:          optional pytree of per-microbatch side inputs with
                  [n_micro, ...] leaves, replicated on every stage (e.g.
                  a key-padding mask). Each stage indexes the slot it is
                  CURRENTLY processing (microbatch t - stage), so aux
                  rides the schedule without any extra permute; when
                  given, layers are called layer_fn(lp, x, aux_slot).
    returns       [n_micro, mb, ...] outputs — valid on the LAST stage
                  (other stages return zeros; callers either slice the
                  stage axis outside or mask-psum).

    Memory note: the [n_micro, mb, ...] input stack, the aux pytree, and
    the output buffer are replicated on EVERY stage (in_specs P()), and
    dead schedule slots still execute full layer compute on zeros — so
    per-stage activation memory scales with the whole global batch,
    O(n_micro). This favors throughput at the current scale; if pp is
    ever used for *memory* scaling, move injection/collection to
    stage-local slices instead.
    """
    n_stages = axis_size(axis_name)
    p = lax.axis_index(axis_name)
    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]
    ticks = n_micro + n_stages - 1

    def run_stage(h, aux_slot):
        def body(h, lp):
            if aux is None:
                return layer_fn(lp, h), None
            return layer_fn(lp, h, aux_slot), None

        h, _ = lax.scan(body, h, stage_params)
        return h

    zeros_mb = jnp.zeros_like(microbatches[0])
    outs0 = jnp.zeros_like(microbatches)

    def tick(carry, t):
        recv, outs = carry
        # stage 0 injects microbatch t (clipped; the tail ticks feed
        # zeros through dead slots), later stages process what the
        # previous stage sent last tick
        feed = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, n_micro - 1), keepdims=False
        )
        feed = jnp.where(t < n_micro, feed, zeros_mb)
        h = jnp.where(p == 0, feed, recv)
        # the microbatch THIS stage processes this tick
        mb_idx = jnp.clip(t - p, 0, n_micro - 1)
        aux_slot = (
            None if aux is None else jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, mb_idx, keepdims=False),
                aux,
            )
        )
        y = run_stage(h, aux_slot)
        recv_next = lax.ppermute(y, axis_name, fwd_perm)
        # last stage emits microbatch t-(P-1) at tick t
        out_idx = t - (n_stages - 1)
        valid = jnp.logical_and(out_idx >= 0, p == n_stages - 1)
        upd = lax.dynamic_update_index_in_dim(
            outs, y, jnp.clip(out_idx, 0, n_micro - 1), axis=0
        )
        outs = jnp.where(valid, upd, outs)
        return (recv_next, outs), None

    (_, outs), _ = lax.scan(tick, (zeros_mb, outs0), jnp.arange(ticks))
    return outs


def gpipe_apply(
    mesh: Mesh,
    params,
    layer_fn: Callable,
    x: jax.Array,
    n_micro: int,
    aux=None,
):
    """Run `depth` layers of `layer_fn` over `x`, pipelined over mesh
    axis 'pp' (standalone-mesh convenience wrapper around
    `pipeline_layers`).

    params: pytree with [depth, ...] leaves, depth = P * layers_per_stage
    x:      [batch, ...] activations, batch % n_micro == 0
    aux:    optional pytree of batch-leading side inputs ([batch, ...]
            leaves, e.g. a key mask), microbatched alongside x and fed to
            layer_fn(lp, x, aux_slot)
    returns [batch, ...] output, numerically equal to the sequential
            lax.scan over all `depth` layers.
    """
    pp = mesh.shape["pp"]
    depth = jax.tree.leaves(params)[0].shape[0]
    assert depth % pp == 0, f"depth {depth} not divisible by pp={pp}"
    batch = x.shape[0]
    assert batch % n_micro == 0, f"batch {batch} % n_micro {n_micro} != 0"

    def micro(a):
        return a.reshape(n_micro, batch // n_micro, *a.shape[1:])

    if pp == 1:
        def body(h, lp):
            if aux is None:
                return layer_fn(lp, h), None
            return layer_fn(lp, h, aux), None

        out, _ = lax.scan(body, x, params)
        return out

    # [depth, ...] -> [P, L, ...] so shard_map splits the stage axis
    staged = jax.tree.map(
        lambda a: a.reshape(pp, depth // pp, *a.shape[1:]), params
    )
    mb = micro(x)
    mb_aux = None if aux is None else jax.tree.map(micro, aux)

    def stage_fn(params_local, mb_local, aux_local):
        # shard_map hands each device its [1, L, ...] slice
        my_layers = jax.tree.map(lambda a: a[0], params_local)
        outs = pipeline_layers(
            layer_fn, my_layers, mb_local, axis_name="pp",
            n_micro=n_micro, aux=aux_local,
        )
        # leading stage axis for the out_spec; caller takes the last stage
        return outs[None]

    if mb_aux is None:
        sharded = shard_map(
            lambda p_, m_: stage_fn(p_, m_, None),
            mesh=mesh,
            in_specs=(P("pp"), P()),
            out_specs=P("pp"),
            check_vma=False,
        )
        outs = sharded(staged, mb)
    else:
        sharded = shard_map(
            stage_fn,
            mesh=mesh,
            in_specs=(P("pp"), P(), P()),
            out_specs=P("pp"),
            check_vma=False,
        )
        outs = sharded(staged, mb, mb_aux)
    return outs[-1].reshape(batch, *x.shape[1:])
