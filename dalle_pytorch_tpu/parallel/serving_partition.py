"""PartitionSpec rules for the SERVING decode-state pytree.

`parallel/partition.py` answers "how do the *parameters* shard" for
training (and the sharded serving engine reuses it verbatim — params are
params). This module answers the serving-only half of the question: how
the continuous engine's persistent decode state — the slot (or paged) KV
cache, the pending-logits rows, and the per-row host-side control
scalars — spreads over a `make_mesh` device mesh so a model or a batch
too big for one chip's HBM still serves from ONE engine.

Sharding scheme (the natural splits of the decode data path):

  KV cache k/v        [..., B|P, H, L, D]  -> heads over `tp`
      Attention is head-independent, so a head split needs no collective
      inside the attention read/write itself — the same cut SNIPPETS.md
      [1] makes for its shard_map-wrapped flash/paged kernels, and the
      one `ops/pallas_decode.py:sharded_flash_decode_attention` uses.
      Works for both layouts: slotted lanes [B, H, max_len, dh] and the
      paged pool [P, H, page_size, dh] (scan executor adds a leading
      depth axis, which stays unsharded so one scan step touches exactly
      one layer's shards).
  KV scales k/v_scale [..., B|P, H, L]    -> heads over `tp`
      int8-cache per-(position, head) fp32 scales ride with the heads
      they scale; the page axis (paged pool) stays whole, like k/v.
  pending logits      [S, V]              -> vocab over `tp`
      Matches the logits head's (fsdp, tp) column split, so the head's
      output lands already distributed.
  shift rings         [.., B, fmap, dim]  -> replicated (tiny)
  per-row scalars     [S]                 -> replicated
      img_pos / active / seeds / temps / keep_k / cache index are bytes
      per row and feed host-side retirement decisions — replicating them
      keeps `harvest`/`step_chunk`'s chunk-boundary `device_get` a local
      read on every process.

Every assignment passes through the same divisibility fallback as
`partition.py:_divisible`: an axis that does not divide the dimension
drops to replicated rather than erroring, so a 2-head toy model on an
8-way mesh still runs (just without the head split).
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dalle_pytorch_tpu.parallel.partition import _divisible, partition_params

#: mesh axis the KV heads / vocab columns shard over (the "model" axis of
#: the 4-axis `make_mesh` vocabulary); batch/slot rows would shard over
#: "dp" but the slot ops index slots host-side, so state rows replicate
SERVING_MODEL_AXIS = "tp"

#: decode-state leaves that are per-row control state — replicated so the
#: chunk-boundary host snapshot stays a local read
_ROW_SCALAR_KEYS = frozenset(
    {"img_pos", "active", "seeds", "temps", "keep_k", "img_tokens", "index"}
)
#: token-shift ring leaves — [B, fmap, dim]-ish, too small to shard
_RING_KEYS = frozenset({"shift_attn", "shift_ff"})


def _leaf_key(path) -> str:
    """Last mapping key of a tree path ('k', 'img_pos', ...)."""
    for p in reversed(path):
        key = getattr(p, "key", None)
        if key is not None:
            return str(key)
    return ""


def decode_state_spec(path, leaf, model_axis: str = SERVING_MODEL_AXIS) -> P:
    """PartitionSpec for ONE decode-state leaf, before the divisibility
    fallback. Covers both the slotted (`init_slot_state`) and paged
    (`init_paged_slot_state`) layouts — the tree keys are shared."""
    key = _leaf_key(path)
    rank = getattr(leaf, "ndim", 0)
    if key in ("k", "v"):
        # [B|P, H, L, dh] (unrolled) or [depth, B|P, H, L, dh] (scan):
        # heads sit at rank-3 in both layouts
        assert rank in (4, 5), f"unexpected cache leaf {key} rank {rank}"
        return P(*([None] * (rank - 3)), model_axis)
    if key in ("k_scale", "v_scale"):
        # int8-cache per-(position, head) fp32 scales: [B, H, L] slotted /
        # [P, H, page_size] paged (scan adds depth) — heads at rank-2, so
        # the scales split WITH the heads they scale and the head-split
        # shard_map kernel reads its shard's scales locally
        assert rank in (3, 4), f"unexpected scale leaf {key} rank {rank}"
        return P(*([None] * (rank - 2)), model_axis)
    if key in _RING_KEYS or key in _ROW_SCALAR_KEYS:
        return P()
    if key == "row":
        # pending next-token logits [S, total_tokens]: vocab columns over
        # the model axis, matching the logits head's (fsdp, tp) split
        return P(None, model_axis)
    return P()  # anything unrecognized replicates (safe, never wrong)


def decode_state_shardings(
    state: Any, mesh: Mesh, model_axis: str = SERVING_MODEL_AXIS
) -> Any:
    """Decode-state pytree -> NamedSharding pytree (same structure), with
    non-dividing axis assignments dropped to replicated."""

    def one(path, leaf):
        spec = decode_state_spec(path, leaf, model_axis)
        spec = _divisible(spec, leaf.shape, mesh)
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(one, state)


def serving_variables_shardings(variables: Any, mesh: Mesh) -> Any:
    """Shardings for the engine's `variables` dict ({"params": ...}):
    params tensor-shard per `partition.py`'s training rules (to_qkv /
    ff-up column-parallel over tp, to_out / ff-down row-parallel,
    embeddings vocab-parallel); any non-"params" collections replicate."""
    out = {}
    for name, tree in variables.items():
        if name == "params":
            out[name] = partition_params(tree, mesh)
        else:
            out[name] = jax.tree_util.tree_map(
                lambda _leaf: NamedSharding(mesh, P()), tree
            )
    return out


def replicated_shardings(tree: Any, mesh: Mesh) -> Any:
    """Fully-replicated shardings for host-ish pytrees (VAE params: the
    pixel decode is tiny next to the trunk, and replicating it keeps the
    fused decode collective-free)."""
    return jax.tree_util.tree_map(
        lambda _leaf: NamedSharding(mesh, P()), tree
    )
