from dalle_pytorch_tpu.parallel.mesh import (
    MESH_AXES,
    make_mesh,
    initialize_distributed,
    is_root,
    is_local_root,
    host_barrier,
    batch_spec,
    batch_sharding,
    put_host_batch,
    gather_to_host,
)
from dalle_pytorch_tpu.parallel.partition import (
    param_partition_spec,
    partition_params,
    state_shardings,
)
from dalle_pytorch_tpu.parallel.ring import ring_attention
from dalle_pytorch_tpu.parallel.gpipe import (
    gpipe_apply,
    make_pp_mesh,
    pipeline_layers,
    stage_params_sharding,
)
