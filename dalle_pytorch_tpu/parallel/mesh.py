"""Device-mesh management: the TPU-native replacement for the reference's
entire distributed-backend layer.

The reference abstracts NCCL/MPI process groups behind a pluggable backend
registry (`/root/reference/dalle_pytorch/distributed_utils.py`,
`distributed_backends/*.py`: DeepSpeed, Horovod, Dummy). On TPU the whole
layer collapses into a `jax.sharding.Mesh` + pjit: XLA emits the
collectives (psum over ICI within a slice, DCN across slices), gradient
averaging is implicit in sharded autodiff, and the "backend" selection
becomes mesh-axis sizing.

Axis vocabulary (mesh is always 4-D; unused axes have size 1):

  dp    pure data parallelism (params replicated)       — DeepSpeed/Horovod DP
  fsdp  data parallelism with sharded params/opt state   — ZeRO-1/2/3
  tp    tensor (megatron-style) parallelism              — (reference: none)
  sp    sequence/context parallelism (ring attention)    — (reference: none)

Process-level helpers mirror the reference ABC's surface
(`distributed_backend.py:12-178`): `is_root` ≈ rank 0 gating for logging,
`is_local_root` ≈ per-host download coordination, `host_barrier` ≈
`local_barrier` (used by pretrained-VAE loading, `vae.py:69-95`).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax promoted shard_map out of jax.experimental (and later added
# lax.axis_size) at different versions; resolve once here so ring.py /
# gpipe.py run on whichever jax the image bakes in (same compat class as
# pallas_attention.CompilerParams)
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map_experimental

    def shard_map(f, **kwargs):
        # the replication-check kwarg was renamed check_rep -> check_vma;
        # callers use the new name, translate for the old API
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_experimental(f, **kwargs)

if hasattr(jax.lax, "axis_size"):
    def axis_size(axis_name) -> int:
        """STATIC size of a mapped mesh axis (usable in Python loop
        bounds inside shard_map bodies)."""
        return jax.lax.axis_size(axis_name)
else:  # pragma: no cover - depends on installed jax
    def axis_size(axis_name) -> int:
        """Pre-`lax.axis_size` jax: the axis env carries the static size."""
        from jax._src import core as _core

        return _core.get_axis_env().axis_size(axis_name)

MESH_AXES = ("dp", "fsdp", "tp", "sp")


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host init (once per host, before any jax call).

    Replaces `deepspeed.init_distributed()` / `hvd.init()`
    (`deepspeed_backend.py:36-39`, `horovod_backend.py`). Rendezvous info
    comes from (in precedence order) explicit arguments, the
    DALLE_TPU_COORDINATOR / DALLE_TPU_NUM_PROCS / DALLE_TPU_PROC_ID env
    vars set by `launch.py`, or — when DALLE_TPU_DIST=1 — TPU-pod
    auto-detection. With none of those present this is a no-op, so the
    trainers can call it unconditionally.
    """
    import os

    env = os.environ
    if coordinator_address is None:
        coordinator_address = env.get("DALLE_TPU_COORDINATOR")
    if num_processes is None and "DALLE_TPU_NUM_PROCS" in env:
        num_processes = int(env["DALLE_TPU_NUM_PROCS"])
    if process_id is None and "DALLE_TPU_PROC_ID" in env:
        process_id = int(env["DALLE_TPU_PROC_ID"])

    if num_processes is not None and num_processes <= 1:
        return
    if coordinator_address is None and num_processes is None:
        if env.get("DALLE_TPU_DIST") == "1":
            # TPU pod: everything auto-detected from the metadata service
            jax.distributed.initialize()
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def is_root() -> bool:
    """Global rank-0 check (reference `is_root_worker`)."""
    return jax.process_index() == 0


def is_local_root() -> bool:
    """First process on this host (reference `is_local_root_worker`).

    JAX is one process per host on TPU, so every process is its host's
    root; kept for API parity with multi-process-per-host setups.
    """
    return int(os.environ.get("LOCAL_PROCESS_ID", "0")) == 0


def host_barrier(name: str = "barrier") -> None:
    """Cross-host sync (reference `local_barrier`, `vae.py:69-95`)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def make_mesh(
    dp: int = -1,
    fsdp: int = 1,
    tp: int = 1,
    sp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the 4-axis device mesh. dp=-1 absorbs the remaining devices.

    Axis order (dp, fsdp, tp, sp) places tp/sp innermost so their
    collectives ride the fastest ICI links; dp outermost so cross-slice
    (DCN) traffic is limited to gradient all-reduce.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    fixed = fsdp * tp * sp
    if dp == -1:
        assert n % fixed == 0, f"{n} devices not divisible by fsdp*tp*sp={fixed}"
        dp = n // fixed
    assert dp * fixed == n, f"mesh {dp}x{fsdp}x{tp}x{sp} != {n} devices"
    dev_array = np.asarray(devices).reshape(dp, fsdp, tp, sp)
    return Mesh(dev_array, MESH_AXES)


def batch_spec(extra_dims: int = 1) -> P:
    """PartitionSpec for a batch tensor: batch over (dp, fsdp), rest replicated.

    Sharding the batch over fsdp too is what turns parameter sharding into
    ZeRO-style data parallelism rather than pure model parallelism.
    """
    return P(("dp", "fsdp"), *([None] * extra_dims))


def batch_sharding(mesh: Mesh, extra_dims: int = 1) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(extra_dims))


def gather_to_host(tree):
    """Fetch a (possibly cross-host-sharded) pytree to host numpy arrays.

    Single-process: device_get. Multi-host: leaves that span
    non-addressable devices (fsdp/tp across hosts) are allgathered first —
    a COLLECTIVE, so every process must call this (root-gate the
    subsequent save, not the gather). Returns the full global value on
    every host.
    """
    if jax.process_count() == 1:
        return jax.device_get(tree)
    from jax.experimental import multihost_utils

    def one(x):
        if getattr(x, "is_fully_replicated", False):
            return jax.device_get(x)  # local replica is the global value
        if hasattr(x, "is_fully_addressable") and not x.is_fully_addressable:
            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return jax.device_get(x)

    return jax.tree_util.tree_map(one, tree)


def put_host_batch(x, sharding: NamedSharding):
    """Device-put a HOST-LOCAL batch shard under a global batch sharding.

    Single-process: plain device_put. Multi-host: each process holds only
    its own data shard (`host_shard_order`), and `jax.device_put` requires
    the same global value everywhere — the correct assembly is
    `make_array_from_process_local_data`, which treats `x` as this
    process's addressable rows of the [global_batch, ...] array.
    """
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    return jax.make_array_from_process_local_data(sharding, np.asarray(x))
