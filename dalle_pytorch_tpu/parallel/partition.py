"""Parameter partitioning rules: path-pattern -> PartitionSpec.

This is the TPU equivalent of the reference's ZeRO configuration
passthrough (`/root/reference/train_dalle.py:378-404`) plus the tensor
parallelism the reference never had. Instead of annotating every module
with logical axes, a small rule table maps flax parameter paths to
PartitionSpecs — decoupled from model code, easy to audit, and the
default is fully sharded over `fsdp` wherever a dimension divides.

Sharding scheme (megatron-style for tp, ZeRO-3-style for fsdp):

  to_qkv/ff-up kernels   [D, H]  -> (fsdp, tp)   column parallel
  to_out/ff-down kernels [H, D]  -> (tp, fsdp)   row parallel
  embeddings             [V, D]  -> (tp, fsdp)   vocab parallel
  logits head            [D, V]  -> (fsdp, tp)
  conv kernels        [kh,kw,I,O] -> O over fsdp when divisible
  1-D params (norms, biases, scales) -> replicated

Optimizer state (adam mu/nu) inherits the same specs by tree structure —
that is the ZeRO-1/2 equivalent; sharded params themselves are ZeRO-3.
"""

from __future__ import annotations

import re
from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (path regex, rank of param it applies to, spec)
_RULES: tuple[tuple[str, int, P], ...] = (
    (r"to_qkv/kernel$", 2, P("fsdp", "tp")),
    (r"to_out/kernel$", 2, P("tp", "fsdp")),
    (r"ff_\d+/Dense_0/kernel$", 2, P("fsdp", "tp")),
    (r"ff_\d+/Dense_1/kernel$", 2, P("tp", "fsdp")),
    # scan executor: same kernels with a leading stacked-depth axis
    # (transformer/scan_stack/layers/...); depth stays unsharded so one
    # scan step touches exactly one layer's shards
    (r"to_qkv/kernel$", 3, P(None, "fsdp", "tp")),
    (r"to_out/kernel$", 3, P(None, "tp", "fsdp")),
    (r"layers/ff/Dense_0/kernel$", 3, P(None, "fsdp", "tp")),
    (r"layers/ff/Dense_1/kernel$", 3, P(None, "tp", "fsdp")),
    (r"logits_dense/kernel$", 2, P("fsdp", "tp")),
    (r"embedding$", 2, P("tp", "fsdp")),
    (r"(text_pos_emb|visual_pos_emb)/embedding$", 2, P(None, "fsdp")),
    (r"kernel$", 2, P("fsdp", None)),  # generic dense fallback
    (r"kernel$", 4, P(None, None, None, "fsdp")),  # convs: shard out-chans
)


def _path_str(path) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)


def param_partition_spec(path, leaf) -> P:
    """Resolve the PartitionSpec for one parameter."""
    p = _path_str(path)
    rank = getattr(leaf, "ndim", 0)
    for pattern, r, spec in _RULES:
        if r == rank and re.search(pattern, p):
            return spec
    return P()  # replicate


def _divisible(spec: P, shape, mesh: Mesh) -> P:
    """Drop axis assignments that don't divide the dimension evenly."""
    fixed = []
    for dim, axes in zip(shape, tuple(spec) + (None,) * (len(shape) - len(spec))):
        if axes is None:
            fixed.append(None)
            continue
        ax_tuple = axes if isinstance(axes, tuple) else (axes,)
        size = 1
        for a in ax_tuple:
            size *= mesh.shape[a]
        fixed.append(axes if dim % size == 0 else None)
    while fixed and fixed[-1] is None:
        fixed.pop()
    return P(*fixed)


def partition_params(params: Any, mesh: Mesh) -> Any:
    """params pytree -> NamedSharding pytree (same structure)."""

    def one(path, leaf):
        spec = param_partition_spec(path, leaf)
        spec = _divisible(spec, leaf.shape, mesh)
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(one, params)


def state_shardings(state: Any, mesh: Mesh, params_field: str = "params") -> Any:
    """Shardings for a flax TrainState: params + matching opt state.

    Optimizer-state leaves that mirror a parameter (same shape pytree in
    adam's mu/nu) get the parameter's sharding; scalars replicate. This is
    the ZeRO-1/2 equivalent of DeepSpeed's optimizer partitioning.
    """

    def one(path, leaf):
        if not hasattr(leaf, "ndim") or leaf.ndim == 0:
            return NamedSharding(mesh, P())
        spec = param_partition_spec(path, leaf)
        spec = _divisible(spec, leaf.shape, mesh)
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(one, state)
