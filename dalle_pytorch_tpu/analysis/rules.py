"""tracelint rule pack: the JAX failure modes this codebase actually has.

Each rule targets one hazard class the serving/training stack depends on
keeping out (see ISSUE/ROADMAP and the fixed-shape compilation discipline
of pjit-style stacks, arXiv:2204.06514):

TL001  Python `if`/`while`/`assert` on a traced parameter of a jit/pjit/
       scan-wrapped function. Branching on a tracer either raises a
       ConcretizationTypeError or — with static_argnums misapplied —
       silently recompiles per value, destroying the compiled-shape ladder.
       Covers `_*_impl` helpers whose only call sites are traced functions
       (one-hop cross-procedural inheritance, jaxctx.JaxIndex).
TL002  device->host syncs (`.item()`, `float()/int()/bool()` on arrays,
       `np.asarray`, `jax.device_get`, `.block_until_ready()`) inside
       traced functions (error tier — always a bug), or on engine state
       inside functions marked `# tracelint: hotloop` (the serving
       admit/chunk/retire loops; warning tier with its own exit-code bit
       — a sync there needs a reasoned suppression, not deletion):
       every unplanned sync stalls the dispatch pipeline.
TL003  a donated argument read after the donating dispatch: donation
       invalidates the buffer, so the read returns garbage or raises —
       the exact bug class the slot-state donation of PR 2 made possible.
TL004  one PRNG key consumed by two `jax.random.*` draws with no
       `split`/`fold_in` between: correlated randomness, silently.
TL005  dtype-less `jnp.array`/`jnp.zeros`/`jnp.ones` in `models/` and
       `ops/`: default-dtype drift (x64 flags, platform defaults) breaks
       checkpoint compatibility and the bit-exactness contracts the
       decode-composition tests pin.
TL006  debugger artifacts (`import ipdb`, `breakpoint()`, `st()`,
       `.set_trace()`): the reference codebase shipped an import-time
       breakpoint (SURVEY.md §0); any import became a hung process.
TL007  `jnp.asarray`/`jnp.array` of a LARGE host constant inside a
       `lax.scan` body: the constant is captured into the trace, re-staged
       (device upload + program bloat) on every retrace instead of living
       once outside the loop. Size heuristic (estimated element count from
       the numpy constructor expression or a module-level constant) keeps
       small iotas/eye-size constants out of the findings.
TL008  `shard_map` in_specs/out_specs (or a `NamedSharding` spec) naming
       a mesh axis the enclosing mesh does not define: jax rejects the
       spec at trace time on the real mesh — or, when specs drift after
       an axis rename, silently stops sharding what the author thinks is
       sharded. The typo class the mesh-sharded serving stack
       (`serving/sharded.py`, `parallel/serving_partition.py`) makes
       easy to write. Resolves meshes bound from literal
       `Mesh(..., ("a", "b"))` constructors and the repo's known
       factories (`make_mesh`, `build_serving_mesh`, `make_pp_mesh`);
       anything else stays silent (false-negative bias, like the rest of
       the pack). Also flags a `shard_map` wrapping a paged decode
       kernel (`paged_flash_decode_attention` / `paged_decode_attention`,
       directly or via `functools.partial`) whose pool specs
       (in_specs positions 1/2) lead with a mesh axis — that splits the
       PAGE axis, the host allocator's addressing unit; only the head
       axis (position 1 of the pool shape) may shard.
TL010  retry-hygiene in `serving/` loops: (a) a bare `except` /
       `except BaseException` inside a `while` loop that does not
       re-`raise` swallows KeyboardInterrupt and shutdown sentinels —
       the drain/Ctrl-C path wedges inside the retry loop; (b) a broad
       `except Exception` that keeps the loop running with NO backoff or
       budget call anywhere in the loop (heuristic call-name match:
       sleep/wait/backoff/budget/withdraw/retry_after/recover/deposit)
       is a hot failure loop — exactly the retry amplification the
       router's success-fraction retry budget exists to prevent.
       Handlers that `break`/`return`/`raise` are safe (the loop ends);
       anything outside `serving/` is out of scope.
TL011  warmup-coverage drift: a `jax.jit`/`pjit` program constructed in
       `serving/` that is never registered with the warmup/AOT-export
       ladder — it cold-compiles mid-traffic, so a warm-cache boot's
       zero-compile contract (and the compile cache's artifact
       inventory) silently drifts. Covered shapes: construction inside
       a ladder-named function (warmup/capture/register/export/
       sharded_program), as an argument to a ladder-named call, or
       assigned to a handle some ladder function references (the
       lazily-built `_decode_pixels_jit` idiom). `serving/` only.
TL012  mid-chunk decode-state snapshot: a host snapshot/serialization
       call (`snapshot_rows`, `harvest`-as-snapshot, checkpoint
       `encode_checkpoint`) inside a `serving/` `while` loop with NO
       chunk-boundary guard around it. The migration/beacon machinery
       (serving/migrate.py) must only leave the device at chunk
       boundaries, and at a bounded cadence — an unguarded snapshot in
       the worker loop adds a device sync to EVERY iteration, the exact
       stall class TL002's hotloop tier polices. Guards recognized: an
       enclosing `if` whose test names a boundary condition (chunk /
       boundary / beacon / migrat / spool / due / pending) or carries a
       `%`-cadence expression. `serving/` only; calls inside helper
       methods (not loops) stay silent — false-negative bias like the
       rest of the pack.
TL013  unguarded shared state: a `self.*` attribute compound-written
       (augassign / container mutation / check-then-act rebind) on one
       thread root and accessed on another with no common lock between
       the two sides — the bug class every review-hardening round since
       PR 7 has caught by hand. Thread roots, lock binding and the
       compound-write currency come from the threadctx.py index; plain
       write-only flag rebinds (GIL-atomic) stay exempt.
TL014  iterate-while-mutated: iterating a shared list/deque/dict
       attribute (for / comprehension / list()-style snapshot call)
       while another thread root mutates it and no common lock covers
       the two sides — the exact PR 7 sampler-vs-/healthz and PR 9
       collector-read RuntimeError shape. The fix is the shipped
       snapshot-under-lock idiom: `with self._lock: snap = list(...)`.
TL015  lock-order inversion: two attribute-bound locks acquired in
       opposite nesting orders anywhere in the package (package-scope
       rule — the acquisition graph spans modules). Direct `with`
       nesting and one hop through a same-class method call are seen;
       each cycle is reported once, at its earliest edge site.
TL016  blocking call under a lock in `serving/` or `obs/`:
       `time.sleep`, thread `.join()`, event `.wait()` (a condition's
       own `wait` releases the lock and is exempt), socket/HTTP reads,
       or an engine dispatch inside a `with <lock>:` body — the
       head-of-line-blocking shape the batcher's dispatch-lock timing
       deliberately avoids (it releases the lock around dispatch).
TL017  mesh-aware jit program without pinned `out_shardings`: a ladder
       program registered through the serving engines' `_sharded_program`
       cache, or a donating jit that declares `in_shardings`, must pin
       its output shardings — unpinned, GSPMD picks the output layout
       per dispatch, so the donated state's sharding drifts and re-keys
       the jit cache (the silent warm-path recompile PR 8 eliminated by
       hand; shardctx.py summaries make it machine-checked).
TL018  donated jit argument whose declared input sharding matches NO
       declared output sharding: XLA only reuses a donated buffer for an
       output with the identical layout, so the donation silently
       becomes an allocate+copy every dispatch.
TL019  implicit hot-path reshard: a value placed under one sharding is
       passed, inside a `# tracelint: hotloop`-reachable function, to a
       jit program or shard_map whose declared in sharding for that
       position differs — GSPMD inserts a resharding collective in
       front of EVERY dispatch. Package-scope (the program may be
       summarized in another file; summaries propagate one hop through
       positional-identity wrappers, mirroring the jaxctx frontier).
TL020  divisibility assumed: a `NamedSharding` built from a literal
       axis-naming PartitionSpec with no `partition.py:_divisible`
       fallback (or explicit `%` check) in the enclosing scope — a
       non-dividing axis must drop to replicated (the 2-head toy model
       on an 8-way mesh), not assume it divides.
TL021  hot-loop sharded gather: a host read (`jax.device_get`,
       `np.asarray`/`np.array`, float/int/bool) of a value placed under
       a mesh-splitting sharding inside a hotloop-reachable function
       gathers the FULL array across the mesh every chunk — host-read
       leaves belong replicated (serving_partition's row-scalar rule).
TL022  request-scoped data as a metric label in `serving/` or `obs/`:
       a `.labels(...)` / `.labels_extra(...)` argument whose value
       flows from a per-request identifier (trace IDs, prompts, raw
       tenant/user strings, request keys) — every distinct value mints
       a new child series, so an open endpoint can grow the registry
       (and every scrape body) without bound. Values routed through a
       bounding call (`*bounded*`, `*clamp*`, `*bucket*`, `*intern*`,
       `*canonical*`, `*cap*` — the UsageLedger `__other__` pattern)
       are trusted; opaque locals stay silent (false-negative bias).
TL009  a `Trace.begin(...)` span whose matching `end()` is unreachable
       on the exception path: begin and end in the SAME function, every
       `end` in straight-line code — an exception between them leaks the
       span open until `finish()` stamps it `abandoned`, so the exported
       stage duration is the request's whole remaining life, which
       poisons the fleet collector's critical-path attribution. Safe
       shapes: `with trace.span(...)`, an `end` in a `finally` or
       `except` block, or the batcher's cross-thread/cross-function
       begin (no same-function `end` — silent by design). Receiver must
       name a trace (`trace.begin`, `req.trace.begin`); begins bound to
       attributes or inside comprehensions stay silent (false-negative
       bias).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from dalle_pytorch_tpu.analysis.core import FileContext, Finding, Rule
from dalle_pytorch_tpu.analysis.jaxctx import (
    FunctionNode,
    JaxIndex,
    dotted_name,
    mentions_traced,
    propagate_traced,
    terminal_name,
    _assign_targets,
    _int_elements,
)

_ALL_FUNCS = FunctionNode + (ast.Lambda,)


def _jax_index(ctx: FileContext) -> JaxIndex:
    """One traced-function index per file, shared by every rule that
    needs it (memoized on the FileContext)."""
    idx = getattr(ctx, "_jax_index", None)
    if idx is None:
        idx = JaxIndex(ctx.tree)
        ctx._jax_index = idx
    return idx


def _functions(tree: ast.Module) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, _ALL_FUNCS):
            yield node


def _walk_shallow(func: ast.AST) -> Iterator[ast.AST]:
    """Pre-order, source-ordered walk of a function body WITHOUT descending
    into nested function defs (they get their own analysis pass)."""

    def rec(node: ast.AST) -> Iterator[ast.AST]:
        for child in ast.iter_child_nodes(node):
            yield child
            if not isinstance(child, _ALL_FUNCS):
                yield from rec(child)

    return rec(func)


class TracerBranchRule(Rule):
    code = "TL001"
    name = "tracer-branch"
    description = (
        "Python if/while/assert on a traced parameter of a jit/pjit/scan-"
        "wrapped function (recompilation / ConcretizationTypeError hazard)"
    )

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        index = _jax_index(ctx)
        for func, info in index.traced.items():
            traced = propagate_traced(func, info.traced_params())
            if not traced:
                continue
            for node in _walk_shallow(func):
                if isinstance(node, (ast.If, ast.While)):
                    test = node.test
                    kind = "if" if isinstance(node, ast.If) else "while"
                elif isinstance(node, ast.Assert):
                    test = node.test
                    kind = "assert"
                else:
                    continue
                if mentions_traced(test, traced):
                    names = sorted(
                        n.id
                        for n in ast.walk(test)
                        if isinstance(n, ast.Name) and n.id in traced
                    )
                    yield ctx.finding(
                        self.code,
                        node,
                        f"`{kind}` on traced value(s) {', '.join(names)} "
                        f"inside a {info.kind}-traced function — use "
                        "jnp.where/lax.cond, or mark the argument static",
                    )


#: call names that ALWAYS force a device->host sync
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_CAST_BUILTINS = {"float", "int", "bool"}


def _is_np_call(call: ast.Call, names: Tuple[str, ...]) -> bool:
    dotted = dotted_name(call.func) or ""
    return any(
        dotted == f"{mod}.{n}"
        for mod in ("np", "numpy")
        for n in names
    )


def _mentions_self_state(node: ast.AST, derived: Set[str]) -> bool:
    """Does `node` reach engine/device state: an attribute rooted at
    `self`, or a local name derived from one?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute):
            root = sub
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id == "self":
                return True
        if isinstance(sub, ast.Name) and sub.id in derived:
            return True
    return False


class HostSyncRule(Rule):
    code = "TL002"
    name = "host-sync"
    description = (
        "device->host synchronization inside a traced function or a "
        "`# tracelint: hotloop`-marked serving loop"
    )

    # Severity tiers: a sync UNDER TRACING is always a bug (error tier —
    # it concretizes or stalls on every call, there is no legitimate
    # unannotated form); a sync in a hotloop-marked host function is a
    # hazard needing justification (warning tier, its own exit-code bit)
    # — the designed chunk-boundary syncs live there behind reasoned
    # suppressions, and a new one may be a deliberate boundary the author
    # hasn't annotated yet.

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        index = _jax_index(ctx)
        for func, info in index.traced.items():
            traced = propagate_traced(func, info.traced_params())
            yield from self._check_traced(ctx, func, traced)
        for func in _functions(ctx.tree):
            if not isinstance(func, ast.Lambda) and ctx.is_hotloop(func):
                yield from self._check_hotloop(ctx, func)

    def _check_traced(self, ctx, func, traced) -> Iterator[Finding]:
        for node in _walk_shallow(func):
            if not isinstance(node, ast.Call):
                continue
            fname = terminal_name(node.func)
            if fname in _SYNC_METHODS and isinstance(node.func, ast.Attribute):
                yield ctx.finding(
                    self.code, node,
                    f"`.{fname}()` forces a host sync under tracing",
                )
            elif _is_np_call(node, ("asarray", "array")) or (
                dotted_name(node.func) or ""
            ).endswith("jax.device_get"):
                yield ctx.finding(
                    self.code, node,
                    "host-side numpy/device_get inside a traced function "
                    "— the value is pulled off-device at every call",
                )
            elif (
                fname in _CAST_BUILTINS
                and isinstance(node.func, ast.Name)
                and node.args
                and mentions_traced(node.args[0], traced)
            ):
                yield ctx.finding(
                    self.code, node,
                    f"`{fname}()` on a traced value concretizes it "
                    "(host sync / ConcretizationTypeError)",
                )

    def _check_hotloop(self, ctx, func) -> Iterator[Finding]:
        # arg-flow: names assigned from self-rooted expressions count as
        # engine state too (`state = self._state` then `np.asarray(state)`)
        derived: Set[str] = set()
        for node in _walk_shallow(func):
            if isinstance(node, ast.Assign) and _mentions_self_state(
                node.value, derived
            ):
                for t in node.targets:
                    derived.update(n.id for n in _assign_targets(t))
        for node in _walk_shallow(func):
            if not isinstance(node, ast.Call):
                continue
            fname = terminal_name(node.func)
            dotted = dotted_name(node.func) or ""
            if fname in _SYNC_METHODS and isinstance(node.func, ast.Attribute):
                yield ctx.finding(
                    self.code, node,
                    f"`.{fname}()` in a hot loop stalls the dispatch "
                    "pipeline — move the sync to a chunk boundary or "
                    "justify it with a suppression",
                    severity="warning",
                )
            elif dotted.endswith("jax.device_get") or dotted.endswith(
                "jax.block_until_ready"
            ):
                yield ctx.finding(
                    self.code, node,
                    f"`{dotted}` in a hot loop — every call is a "
                    "device round trip; batch transfers at the boundary "
                    "or justify with a suppression",
                    severity="warning",
                )
            elif _is_np_call(node, ("asarray", "array")) and node.args and (
                _mentions_self_state(node.args[0], derived)
            ):
                yield ctx.finding(
                    self.code, node,
                    "np.asarray on engine state in a hot loop is an "
                    "implicit device->host sync — make it explicit "
                    "(jax.device_get at the designed boundary) or justify "
                    "with a suppression",
                    severity="warning",
                )


class DonatedReuseRule(Rule):
    code = "TL003"
    name = "donated-reuse"
    description = (
        "read of a donated argument after the donating dispatch — donation "
        "invalidates the buffer (one cache copy alive, not two)"
    )

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        if package is None:
            return
        for func in _functions(ctx.tree):
            if isinstance(func, ast.Lambda):
                continue
            yield from self._check_function(ctx, func, package)

    def _check_function(self, ctx, func, package) -> Iterator[Finding]:
        # poisoned name -> (donating callable, line of the dispatch)
        poisoned: Dict[str, Tuple[str, int]] = {}

        def shallow_nodes(node) -> Iterator[ast.AST]:
            yield node
            if isinstance(node, _ALL_FUNCS):
                return
            for child in ast.iter_child_nodes(node):
                yield from shallow_nodes(child)

        def scan_exprs(exprs: List[ast.AST], stmt) -> Iterator[Finding]:
            """Per-statement ordering: reads flagged first (exempting the
            donated args themselves), then donations poison, then
            assignment targets clear — so `state = f(state)` ends clean
            while `new = f(state); state[...]` flags the later read."""
            nodes: List[ast.AST] = []
            for e in exprs:
                nodes.extend(shallow_nodes(e))
            exempt = set()
            donations: List[Tuple[str, str, int]] = []
            for node in nodes:
                if isinstance(node, ast.Call):
                    for i in package.call_donated_indices(node):
                        if i < len(node.args) and isinstance(
                            node.args[i], ast.Name
                        ):
                            exempt.add(id(node.args[i]))
                            donations.append((
                                node.args[i].id,
                                terminal_name(node.func) or "<dispatch>",
                                node.lineno,
                            ))
            for node in nodes:
                if (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in poisoned
                    and id(node) not in exempt
                ):
                    donor, line = poisoned[node.id]
                    yield ctx.finding(
                        "TL003", node,
                        f"`{node.id}` was donated to `{donor}` on line "
                        f"{line}; its buffer is invalid — use the "
                        "dispatch's return value instead",
                    )
            for name, donor, line in donations:
                poisoned[name] = (donor, line)
            for node in nodes:
                if isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Store, ast.Del)
                ):
                    poisoned.pop(node.id, None)

        def walk_block(body: List[ast.AST]) -> Iterator[Finding]:
            # linear approximation of control flow: branches analyzed in
            # order with shared state (conservative for reads, forgiving
            # for rebinds — fixtures pin both directions)
            for stmt in body:
                if isinstance(stmt, _ALL_FUNCS):
                    continue
                exprs, blocks = [], []
                for _field, value in ast.iter_fields(stmt):
                    if isinstance(value, list) and value and isinstance(
                        value[0], ast.stmt
                    ):
                        blocks.append(value)
                    elif isinstance(value, list):
                        exprs.extend(
                            v for v in value if isinstance(v, ast.AST)
                        )
                    elif isinstance(value, ast.AST):
                        exprs.append(value)
                yield from scan_exprs(exprs, stmt)
                for block in blocks:
                    yield from walk_block(block)

        yield from walk_block(func.body)


#: jax.random callables that DERIVE keys rather than consuming them
_KEY_DERIVERS = {
    "PRNGKey", "split", "fold_in", "key", "key_data", "wrap_key_data",
    "clone",
}


class KeyReuseRule(Rule):
    code = "TL004"
    name = "rng-key-reuse"
    description = (
        "one PRNG key consumed by two jax.random draws with no split/"
        "fold_in between — correlated randomness"
    )

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        roots, aliases = self._jax_random_bindings(ctx.tree)
        for func in _functions(ctx.tree):
            yield from self._check_function(ctx, func, roots, aliases)

    @staticmethod
    def _jax_random_bindings(tree: ast.Module):
        """(names bound to the jax module, names bound to jax.random) —
        so `np.random.normal` / stdlib `random.choice` never register as
        key draws (they take no key; flagging them is pure noise)."""
        roots = set()
        aliases = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax":
                        roots.add(a.asname or "jax")
                    elif a.name == "jax.random":
                        if a.asname:  # import jax.random as jr
                            aliases.add(a.asname)
                        else:  # bare `import jax.random` binds the name jax
                            roots.add("jax")
            elif isinstance(node, ast.ImportFrom) and node.module == "jax":
                for a in node.names:
                    if a.name == "random":
                        aliases.add(a.asname or "random")
        return roots, aliases

    @staticmethod
    def _is_random_call(call: ast.Call, roots, aliases) -> Optional[str]:
        dotted = dotted_name(call.func) or ""
        parts = dotted.split(".")
        if len(parts) >= 3 and parts[-2] == "random" and parts[0] in roots:
            return parts[-1]  # jax.random.X
        if len(parts) == 2 and parts[0] in aliases:
            return parts[-1]  # from jax import random; random.X
        return None

    def _check_function(self, ctx, func, roots, aliases) -> Iterator[Finding]:
        consumed: Dict[str, int] = {}  # key name -> line first consumed

        def refresh(target) -> None:
            for n in _assign_targets(target):
                consumed.pop(n.id, None)

        for node in _walk_shallow(func):
            # any rebinding refreshes the name (split/fold_in results are
            # fresh keys; so is a brand-new PRNGKey) — including loop and
            # with-as targets: `for key in keys:` binds a fresh key each
            # iteration, the standard iterate-over-split-keys idiom
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    refresh(t)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                refresh(node.target)
            elif isinstance(node, ast.comprehension):
                refresh(node.target)
            elif isinstance(node, ast.withitem) and node.optional_vars:
                refresh(node.optional_vars)
            if not isinstance(node, ast.Call):
                continue
            fname = self._is_random_call(node, roots, aliases)
            if fname is None or fname in _KEY_DERIVERS:
                continue
            if not node.args or not isinstance(node.args[0], ast.Name):
                continue
            key = node.args[0].id
            if key in consumed:
                yield ctx.finding(
                    self.code, node,
                    f"key `{key}` already consumed by a jax.random "
                    f"draw on line {consumed[key]} — split or fold_in "
                    "before drawing again",
                )
            else:
                consumed[key] = node.lineno


class DtypeDriftRule(Rule):
    code = "TL005"
    name = "dtype-drift"
    description = (
        "dtype-less jnp.array/jnp.zeros/jnp.ones in models/ or ops/ — "
        "default-dtype drift breaks checkpoint and bit-exactness contracts"
    )

    #: path fragments this rule applies to (precision-discipline dirs)
    SCOPED_DIRS = ("models", "ops")

    def _in_scope(self, ctx: FileContext) -> bool:
        parts = ctx.path.parts
        return any(d in parts for d in self.SCOPED_DIRS)

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        if not self._in_scope(ctx):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func) or ""
            if dotted not in ("jnp.array", "jnp.zeros", "jnp.ones"):
                continue
            has_dtype = any(kw.arg == "dtype" for kw in node.keywords) or (
                len(node.args) >= 2  # positional dtype: jnp.zeros(shape, jnp.f32)
            )
            if not has_dtype:
                yield ctx.finding(
                    self.code, node,
                    f"`{dotted}` without an explicit dtype — the default "
                    "drifts with x64 flags and platform; pin it",
                )


class DebuggerArtifactRule(Rule):
    code = "TL006"
    name = "debugger-artifact"
    description = (
        "debugger artifact in shipped code — the reference repo's import-"
        "time-breakpoint regression (SURVEY.md §0)"
    )
    # the regex scan this rule replaced had no opt-out; neither does this —
    # a suppression comment must not let a breakpoint ship
    suppressible = False

    _MSG = (
        "debugger artifact in shipped code (the reference repo's "
        "import-time-breakpoint regression, SURVEY.md §0)"
    )

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.split(".")[0] == "ipdb":
                        yield ctx.finding(
                            self.code, node, f"`import ipdb`: {self._MSG}"
                        )
            elif isinstance(node, ast.ImportFrom):
                if (node.module or "").split(".")[0] == "ipdb":
                    yield ctx.finding(
                        self.code, node, f"`from ipdb import`: {self._MSG}"
                    )
            elif isinstance(node, ast.Call):
                if isinstance(node.func, ast.Name):
                    if node.func.id == "breakpoint":
                        yield ctx.finding(
                            self.code, node, f"`breakpoint()`: {self._MSG}"
                        )
                    elif node.func.id == "st" and not node.args and not node.keywords:
                        yield ctx.finding(
                            self.code, node,
                            f"`st()` debugger alias: {self._MSG}",
                        )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "set_trace"
                ):
                    yield ctx.finding(
                        self.code, node, f"`.set_trace()`: {self._MSG}"
                    )


#: numpy constructors whose element count is the product of their shape arg
_NP_SHAPE_CTORS = {"zeros", "ones", "empty", "full"}
#: numpy wrappers that preserve their (first) argument's element count
_NP_SIZE_PRESERVING = {"asarray", "ascontiguousarray", "tril", "triu", "copy"}


class ScanConstUploadRule(Rule):
    code = "TL007"
    name = "scan-const-upload"
    description = (
        "jnp.asarray/jnp.array of a large host constant inside a lax.scan "
        "body — captured into the trace and re-staged on every retrace; "
        "hoist it out of the body"
    )

    #: estimated element count at or above which the capture is flagged
    #: (~8 KB of fp32 — below that the program-constant cost is noise)
    MIN_ELEMENTS = 2048

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        index = _jax_index(ctx)
        consts = self._module_const_sizes(ctx.tree)
        for func, info in index.traced.items():
            if info.kind != "scan":
                continue
            for node in _walk_shallow(func):
                if not isinstance(node, ast.Call):
                    continue
                dotted = dotted_name(node.func) or ""
                if dotted not in ("jnp.asarray", "jnp.array"):
                    continue
                if not node.args:
                    continue
                size = self._const_size(node.args[0], consts)
                if size is not None and size >= self.MIN_ELEMENTS:
                    yield ctx.finding(
                        self.code,
                        node,
                        f"`{dotted}` of a host constant (~{size} elements) "
                        "inside a lax.scan body — it is re-staged into the "
                        "program on every trace; build it once outside the "
                        "body and close over the device array",
                    )

    @staticmethod
    def _module_const_sizes(tree: ast.Module) -> Dict[str, int]:
        """Module-level `NAME = <numpy constructor expr>` bindings whose
        element count is estimable (the only cross-scope lookup: a scan
        body wrapping a module constant is exactly the hazard)."""
        sizes: Dict[str, int] = {}
        for stmt in tree.body:
            if not isinstance(stmt, ast.Assign):
                continue
            size = ScanConstUploadRule._const_size(stmt.value, {})
            if size is None:
                continue
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    sizes[t.id] = size
        return sizes

    @staticmethod
    def _const_size(node: ast.AST, consts: Dict[str, int]) -> Optional[int]:
        """Estimated element count of a host-constant expression, or None
        when the expression is not recognizably a sized numpy constant
        (false-negative bias: unknown means silent, like the rest of the
        rule pack)."""
        rec = ScanConstUploadRule._const_size
        if isinstance(node, ast.Name):
            return consts.get(node.id)
        if isinstance(node, (ast.Compare, ast.BinOp)):
            # broadcasting lower bound: the result is at least as large as
            # its largest sized operand (`np.arange(V) < k`)
            parts = (
                [node.left] + list(node.comparators)
                if isinstance(node, ast.Compare)
                else [node.left, node.right]
            )
            sizes = [s for s in (rec(p, consts) for p in parts) if s is not None]
            return max(sizes) if sizes else None
        if not isinstance(node, ast.Call):
            return None
        dotted = dotted_name(node.func) or ""
        parts = dotted.split(".")
        if len(parts) != 2 or parts[0] not in ("np", "numpy"):
            return None
        ctor = parts[1]
        if ctor == "arange":
            if len(node.args) == 1:
                vals = _int_elements(node.args[0])
                return vals[0] if len(vals) == 1 else None
            if len(node.args) >= 2:
                lo = _int_elements(node.args[0])
                hi = _int_elements(node.args[1])
                if len(lo) != 1 or len(hi) != 1:
                    return None
                span = max(hi[0] - lo[0], 0)
                if len(node.args) < 3:
                    return span
                # strided arange: hi-lo alone would overcount by the step
                # factor and flag small constants (false-positive — the
                # pack's bias is the other way)
                step = _int_elements(node.args[2])
                if len(step) == 1 and step[0] > 0:
                    return -(-span // step[0])
            return None
        if ctor in _NP_SHAPE_CTORS and node.args:
            dims = _int_elements(node.args[0])
            if dims:
                size = 1
                for d in dims:
                    size *= d
                return size
            return None
        if ctor in _NP_SIZE_PRESERVING and node.args:
            return rec(node.args[0], consts)
        return None


# the mesh-axis vocabulary tables and resolution helpers moved to
# shardctx.py (the sharding-dataflow engine TL017-TL021 run on) so TL008
# and the sharding summaries can never drift apart; re-exported here
# because tests/test_analysis.py pins the vocabulary through this module
from dalle_pytorch_tpu.analysis.shardctx import (  # noqa: E402
    _MAKE_MESH_AXES,
    _MESH_FACTORY_AXES,
    iter_hot_calls,
    literal_mesh_axes,
    mesh_axis_bindings,
    package_summaries,
    shard_index,
    specs_differ,
)

#: paged decode kernels whose operand order is (q, k_pages, v_pages, ...):
#: when `shard_map` wraps one (directly or through `functools.partial`),
#: in_specs positions 1 and 2 describe the physical PAGE POOLS
#: [n_pages, heads, page_size, dh] — the leading (page) axis is the host
#: allocator's addressing unit and must NEVER shard (a split pool puts
#: half of every page's tokens on the wrong device while the host page
#: table keeps addressing pages globally); shard the HEAD axis instead
_PAGED_POOL_KERNELS = frozenset(
    {"paged_flash_decode_attention", "paged_decode_attention"}
)


class MeshAxisRule(Rule):
    code = "TL008"
    name = "mesh-axis-unknown"
    description = (
        "shard_map/NamedSharding partition spec naming an axis the "
        "enclosing mesh does not define — trace-time rejection on the "
        "real mesh, or a silent no-op shard after an axis rename; also "
        "flags a shard_map wrapping a paged decode kernel whose pool "
        "specs (in_specs positions 1/2) split the PAGE axis — pages are "
        "the host allocator's unit, only the head axis may shard"
    )

    @staticmethod
    def _wrapped_name(node: ast.Call) -> Optional[str]:
        """Terminal name of the callable a `shard_map(...)` wraps —
        unwrapping one `functools.partial(fn, ...)` layer."""
        target = node.args[0] if node.args else next(
            (kw.value for kw in node.keywords if kw.arg == "f"), None
        )
        if isinstance(target, ast.Call) and terminal_name(
            target.func
        ) == "partial" and target.args:
            target = target.args[0]
        if target is None:
            return None
        return terminal_name(target)

    def _paged_pool_findings(self, ctx, node: ast.Call) -> Iterator[Finding]:
        """shard_map over a paged decode kernel: the pool operands'
        leading (page) axis must stay whole. Structural — needs no mesh
        resolution, any string axis leading in_specs[1]/[2] is wrong."""
        if self._wrapped_name(node) not in _PAGED_POOL_KERNELS:
            return
        in_expr = next(
            (kw.value for kw in node.keywords if kw.arg == "in_specs"), None
        )
        if not isinstance(in_expr, (ast.Tuple, ast.List)):
            return
        for pos in (1, 2):
            if pos >= len(in_expr.elts):
                continue
            spec = in_expr.elts[pos]
            if not (
                isinstance(spec, ast.Call)
                and terminal_name(spec.func) in ("P", "PartitionSpec")
                and spec.args
            ):
                continue
            first = spec.args[0]
            leads = (
                isinstance(first, ast.Constant)
                and isinstance(first.value, str)
            ) or (
                isinstance(first, (ast.Tuple, ast.List))
                and any(
                    isinstance(e, ast.Constant) and isinstance(e.value, str)
                    for e in first.elts
                )
            )
            if leads:
                operand = "k_pages" if pos == 1 else "v_pages"
                yield ctx.finding(
                    self.code, spec,
                    f"shard_map over a paged decode kernel splits the "
                    f"PAGE axis of {operand} (in_specs[{pos}] leads with "
                    f"a mesh axis) — pages are the host allocator's "
                    f"unit; shard the head axis (position 1) instead",
                )

    # mesh resolution lives in shardctx.py (shared with TL017-TL021's
    # sharding summaries); these wrappers keep the rule's seam names
    @staticmethod
    def _literal_axes(call: ast.Call) -> Optional[Set[str]]:
        return literal_mesh_axes(call)

    def _mesh_bindings(self, tree: ast.Module) -> Dict[str, Set[str]]:
        return mesh_axis_bindings(tree)

    def _resolve_mesh(self, expr, axes_of) -> Optional[Set[str]]:
        if isinstance(expr, ast.Name):
            return axes_of.get(expr.id)
        if isinstance(expr, ast.Call):
            return self._literal_axes(expr)
        return None  # attribute/param meshes: silent

    @staticmethod
    def _spec_calls(expr: ast.AST) -> Iterator[ast.Call]:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) and terminal_name(node.func) in (
                "P", "PartitionSpec",
            ):
                yield node

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        axes_of = self._mesh_bindings(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = terminal_name(node.func)
            if fname == "shard_map":
                yield from self._paged_pool_findings(ctx, node)
                mesh_expr = next(
                    (kw.value for kw in node.keywords if kw.arg == "mesh"),
                    None,
                )
                spec_exprs = [
                    kw.value for kw in node.keywords
                    if kw.arg in ("in_specs", "out_specs")
                ]
            elif fname == "NamedSharding":
                mesh_expr = node.args[0] if node.args else next(
                    (kw.value for kw in node.keywords if kw.arg == "mesh"),
                    None,
                )
                spec_exprs = list(node.args[1:]) + [
                    kw.value for kw in node.keywords if kw.arg == "spec"
                ]
            else:
                continue
            if mesh_expr is None:
                continue
            axes = self._resolve_mesh(mesh_expr, axes_of)
            if not axes:
                continue
            for spec_call in (
                c for e in spec_exprs for c in self._spec_calls(e)
            ):
                names = {
                    n.value
                    for arg in spec_call.args
                    for n in ast.walk(arg)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, str)
                }
                unknown = sorted(names - axes)
                if unknown:
                    yield ctx.finding(
                        self.code, spec_call,
                        f"partition spec names axis(es) "
                        f"{', '.join(repr(u) for u in unknown)} not "
                        f"defined by the enclosing mesh "
                        f"(axes: {sorted(axes)})",
                    )


class SpanLeakRule(Rule):
    code = "TL009"
    name = "span-leak"
    description = (
        "Trace.begin(...) whose matching end() is not reachable on the "
        "exception path (no enclosing try/finally or except) — a raise "
        "between them leaks the span open until finish() marks it "
        "abandoned, corrupting exported stage durations"
    )

    @staticmethod
    def _trace_method_call(node: ast.AST, attr: str) -> bool:
        """`<receiver>.{attr}(...)` where the receiver's dotted name
        mentions a trace (`trace.begin`, `req.trace.end`, ...). Bare
        receivers (`t.begin`) and unresolvable ones stay silent —
        false-negative bias, and it keeps unrelated `.begin()` APIs
        (db cursors, matchers) out of the findings."""
        if not isinstance(node, ast.Call):
            return False
        if not isinstance(node.func, ast.Attribute) or node.func.attr != attr:
            return False
        dotted = dotted_name(node.func) or ""
        receiver = dotted.rsplit(".", 1)[0] if "." in dotted else ""
        return "trace" in receiver.lower()

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        for func in _functions(ctx.tree):
            if isinstance(func, ast.Lambda):
                continue
            yield from self._check_function(ctx, func)

    def _check_function(self, ctx, func) -> Iterator[Finding]:
        begins: Dict[str, ast.AST] = {}  # span name -> its begin call
        ends: Dict[str, Dict[str, bool]] = {}  # span name -> seen/protected

        # the walk tracks whether the current block is exception-reachable
        # cleanup (a `finally` or an `except` handler): an `end(span)`
        # there closes the span on the error path too — the contract

        def scan_exprs(exprs: List[ast.AST], protected: bool) -> None:
            for expr in exprs:
                for node in ast.walk(expr):
                    if isinstance(node, _ALL_FUNCS):
                        break  # nested defs get their own pass
                    if self._trace_method_call(node, "end") and node.args:
                        target = node.args[0]
                        if isinstance(target, ast.Name):
                            info = ends.setdefault(
                                target.id, {"seen": False, "protected": False}
                            )
                            info["seen"] = True
                            info["protected"] = info["protected"] or protected

        def visit_stmt(stmt: ast.AST, protected: bool) -> None:
            if isinstance(stmt, _ALL_FUNCS):
                return
            if isinstance(stmt, ast.Try):
                walk(stmt.body, protected)
                for handler in stmt.handlers:
                    walk(handler.body, True)
                walk(stmt.orelse, protected)
                walk(stmt.finalbody, True)
                return
            if isinstance(stmt, ast.Assign) and isinstance(
                stmt.value, ast.Call
            ) and self._trace_method_call(stmt.value, "begin"):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        begins.setdefault(t.id, stmt.value)
            exprs, blocks = [], []
            for _field, value in ast.iter_fields(stmt):
                if isinstance(value, list) and value and isinstance(
                    value[0], ast.stmt
                ):
                    blocks.append(value)
                elif isinstance(value, list):
                    exprs.extend(v for v in value if isinstance(v, ast.AST))
                elif isinstance(value, ast.AST):
                    exprs.append(value)
            scan_exprs(exprs, protected)
            for block in blocks:
                walk(block, protected)

        def walk(stmts: List[ast.AST], protected: bool) -> None:
            for stmt in stmts:
                visit_stmt(stmt, protected)

        walk(func.body, False)
        for span_name, begin_node in begins.items():
            info = ends.get(span_name)
            if info is None or not info["seen"]:
                continue  # cross-thread/cross-function end: silent
            if not info["protected"]:
                yield ctx.finding(
                    self.code, begin_node,
                    f"span `{span_name}` begun here has no end() reachable "
                    "on the exception path — wrap the work in try/finally "
                    "(or use `with trace.span(...)`) so an error can't "
                    "leak the span open until finish()",
                )


class RetryHygieneRule(Rule):
    code = "TL010"
    name = "retry-hygiene"
    description = (
        "serving retry/failover loop with a broad exception handler that "
        "either swallows KeyboardInterrupt/shutdown sentinels (bare "
        "except / except BaseException without re-raise) or keeps "
        "retrying with no backoff or budget call — the hot failure loop "
        "that amplifies an outage"
    )

    #: retry discipline is a serving-stack contract; training scripts and
    #: analysis tooling loop differently and stay out of scope
    SCOPED_DIRS = ("serving",)

    #: call-name fragments that count as backoff/budget discipline. The
    #: list is a heuristic by design (false-negative bias, like the rest
    #: of the pack): `cond.wait`, `time.sleep`, `budget.withdraw`,
    #: `self._recover`, `stop.wait(backoff)` all match.
    BACKOFF_HINTS = (
        "sleep", "wait", "backoff", "budget", "withdraw", "retry_after",
        "recover", "deposit",
    )

    def _in_scope(self, ctx: FileContext) -> bool:
        return any(d in ctx.path.parts for d in self.SCOPED_DIRS)

    @staticmethod
    def _shallow(stmts) -> Iterator[ast.AST]:
        """Every node under `stmts` without descending into nested
        function defs (they get their own pass)."""
        stack = list(stmts)
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if not isinstance(child, _ALL_FUNCS):
                    stack.append(child)

    @classmethod
    def _handler_kind(cls, handler: ast.ExceptHandler) -> Optional[str]:
        """'base' for bare/except BaseException, 'broad' for Exception
        (tuples count if any element matches), None for narrow."""
        t = handler.type
        if t is None:
            return "base"
        elts = t.elts if isinstance(t, ast.Tuple) else [t]
        names = {terminal_name(e) for e in elts}
        if "BaseException" in names:
            return "base"
        if "Exception" in names:
            return "broad"
        return None

    @classmethod
    def _has_backoff(cls, nodes) -> bool:
        for node in nodes:
            if isinstance(node, ast.Call):
                dotted = (dotted_name(node.func) or "").lower()
                if any(h in dotted for h in cls.BACKOFF_HINTS):
                    return True
        return False

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        if not self._in_scope(ctx):
            return
        for func in _functions(ctx.tree):
            if isinstance(func, ast.Lambda):
                continue
            for loop in _walk_shallow(func):
                if isinstance(loop, ast.While):
                    yield from self._check_loop(ctx, loop)

    def _check_loop(self, ctx: FileContext, loop: ast.While
                    ) -> Iterator[Finding]:
        loop_nodes = list(self._shallow(loop.body))
        loop_has_backoff = self._has_backoff(loop_nodes)
        for node in loop_nodes:
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                kind = self._handler_kind(handler)
                if kind is None:
                    continue
                body = list(self._shallow(handler.body))
                # bare `raise` or `raise <caught-name>` both re-raise the
                # caught exception, interrupts included
                reraises = any(
                    isinstance(n, ast.Raise) and (
                        n.exc is None
                        or (
                            handler.name is not None
                            and isinstance(n.exc, ast.Name)
                            and n.exc.id == handler.name
                        )
                    )
                    for n in body
                )
                if kind == "base" and not reraises:
                    yield ctx.finding(
                        self.code, handler,
                        "bare `except`/`except BaseException` inside a "
                        "serving retry loop swallows KeyboardInterrupt "
                        "and shutdown sentinels — catch `Exception`, or "
                        "re-`raise` what the loop cannot handle, so "
                        "drain/Ctrl-C can still stop it",
                    )
                    continue
                exits = any(
                    isinstance(n, (ast.Raise, ast.Return, ast.Break))
                    for n in body
                )
                if exits:
                    continue  # the loop ends on failure: not a retry
                if loop_has_backoff or self._has_backoff(body):
                    continue
                yield ctx.finding(
                    self.code, handler,
                    "broad `except` keeps this serving retry loop "
                    "running with no backoff or budget call in the loop "
                    "— a hot failure loop amplifies an outage; add a "
                    "backoff sleep/wait or a retry-budget check "
                    "(recognized call names: "
                    f"{', '.join(self.BACKOFF_HINTS)})",
                )


class WarmupCoverageRule(Rule):
    code = "TL011"
    name = "warmup-coverage"
    description = (
        "a jax.jit/pjit program constructed in serving/ that is never "
        "registered with the warmup/AOT-export ladder — it cold-compiles "
        "mid-traffic, so a warm-cache boot's zero-compile contract (and "
        "the compile cache's artifact inventory) silently drifts"
    )

    #: warmup discipline is a serving-engine contract; models/ops build
    #: jitted programs through their own cached builders, and training
    #: scripts compile eagerly by design
    SCOPED_DIRS = ("serving",)

    #: function/call name fragments that count as the warmup/AOT ladder.
    #: A jit call is covered when it is constructed INSIDE one of these
    #: (warmup methods, `_capture_cost`-style registration, the sharded
    #: engine's `_sharded_program` memo), or when its assignment target
    #: is referenced by one (the lazily-built `_decode_pixels_jit` that
    #: `_capture_decode_pixels_cost` registers). Heuristic with
    #: false-negative bias, like the rest of the pack.
    LADDER_FRAGMENTS = (
        "warmup", "capture", "register", "export", "sharded_program",
    )

    def _in_scope(self, ctx: FileContext) -> bool:
        return any(d in ctx.path.parts for d in self.SCOPED_DIRS)

    @staticmethod
    def _is_jit_call(call: ast.Call) -> bool:
        terminal = terminal_name(call.func)
        if terminal not in ("jit", "pjit"):
            return False
        dotted = dotted_name(call.func) or terminal
        # `self.jit(...)`-style methods are not program construction
        return not dotted.startswith("self.")

    @classmethod
    def _is_ladder_name(cls, name: str) -> bool:
        low = (name or "").lower()
        return any(f in low for f in cls.LADDER_FRAGMENTS)

    def _ladder_refs(self, tree: ast.Module) -> Set[str]:
        """Every identifier referenced inside a ladder-named function —
        the set a jit handle must intersect to count as registered."""
        refs: Set[str] = set()
        for func in _functions(tree):
            if self._is_ladder_name(getattr(func, "name", "")):
                for node in ast.walk(func):
                    if isinstance(node, ast.Attribute):
                        refs.add(node.attr)
                    elif isinstance(node, ast.Name):
                        refs.add(node.id)
        return refs

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        if not self._in_scope(ctx):
            return
        refs = self._ladder_refs(ctx.tree)
        yield from self._scan(ctx, ctx.tree, False, refs)

    def _scan(self, ctx: FileContext, node: ast.AST, covered: bool,
              refs: Set[str]) -> Iterator[Finding]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            covered = covered or self._is_ladder_name(node.name)
        elif isinstance(node, ast.Assign):
            # `self.X = jax.jit(...)` / `X = jax.jit(...)`: the handle
            # being referenced by a ladder function registers the program
            handles = set()
            for t in node.targets:
                if isinstance(t, ast.Attribute):
                    handles.add(t.attr)
                elif isinstance(t, ast.Name):
                    handles.add(t.id)
            if handles & refs:
                covered = True
        elif isinstance(node, ast.Call):
            callee = (dotted_name(node.func) or "").lower()
            if self._is_jit_call(node) and not covered:
                yield ctx.finding(
                    self.code, node,
                    "jit program constructed outside the warmup/AOT-"
                    "export ladder: it will cold-compile mid-traffic "
                    "after a warm-cache boot. Dispatch it from warmup() "
                    "(or register it through the `_capture_cost`/"
                    "`_sharded_program` ladder) so the compile cache "
                    "and the zero-recompile contract cover it",
                )
            if self._is_ladder_name(callee):
                # arguments of a ladder call (the sharded engine's
                # `_sharded_program("chunk", lambda: jax.jit(...))`)
                # are registered by construction
                covered = True
        for child in ast.iter_child_nodes(node):
            yield from self._scan(ctx, child, covered, refs)


class ChunkBoundarySnapshotRule(Rule):
    code = "TL012"
    name = "mid-chunk-snapshot"
    description = (
        "host decode-state snapshot/serialization call inside a serving "
        "loop without a chunk-boundary guard — migration/beacon work "
        "must leave the device only at chunk boundaries, at a bounded "
        "cadence, or every loop iteration pays a device sync"
    )

    #: chunk-boundary discipline is a serving-stack contract (the worker
    #: loop of serving/batcher.py); nothing else runs a chunk loop
    SCOPED_DIRS = ("serving",)

    #: call-name fragments that read or serialize decode state on the
    #: host. `harvest` is deliberately absent: the retirement harvest is
    #: the designed boundary sync, and flagging it would just force a
    #: suppression on the one legitimate call
    SNAPSHOT_FRAGMENTS = ("snapshot_rows", "encode_checkpoint")

    #: guard-test name fragments that count as a chunk-boundary /
    #: cadence condition (heuristic, false-negative bias like TL010's
    #: backoff hints)
    GUARD_HINTS = (
        "chunk", "boundary", "beacon", "migrat", "spool", "due", "pending",
    )

    def _in_scope(self, ctx: FileContext) -> bool:
        return any(d in ctx.path.parts for d in self.SCOPED_DIRS)

    @classmethod
    def _is_boundary_guard(cls, test: ast.AST) -> bool:
        """Does an `if` test look like a chunk-boundary/cadence guard?
        Any mentioned name containing a guard hint, or a `%` cadence
        expression (`chunk_index % every == 0`)."""
        for node in ast.walk(test):
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
                return True
            name = None
            if isinstance(node, ast.Attribute):
                name = node.attr
            elif isinstance(node, ast.Name):
                name = node.id
            if name and any(h in name.lower() for h in cls.GUARD_HINTS):
                return True
        return False

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        if not self._in_scope(ctx):
            return
        for func in _functions(ctx.tree):
            if isinstance(func, ast.Lambda):
                continue
            yield from self._outermost_loops(ctx, func)

    def _outermost_loops(self, ctx: FileContext,
                         func: ast.AST) -> Iterator[Finding]:
        """Visit each function's OUTERMOST `while` loops only — the loop
        scan itself descends into nested ones (guard context intact), so
        one unguarded call yields exactly one finding."""

        def rec(node: ast.AST) -> Iterator[ast.While]:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _ALL_FUNCS):
                    continue  # nested defs get their own check() pass
                if isinstance(child, ast.While):
                    yield child
                else:
                    yield from rec(child)

        for loop in rec(func):
            yield from self._check_loop(ctx, loop)

    def _check_loop(self, ctx: FileContext,
                    loop: ast.While) -> Iterator[Finding]:
        """Walk the loop body (nested `while` loops included — they are
        not visited separately) tracking whether each node sits under a
        boundary-guard `if`; snapshot calls outside every guard are the
        findings. Nested functions are not descended into."""

        def scan(node: ast.AST, guarded: bool) -> Iterator[Finding]:
            if isinstance(node, _ALL_FUNCS):
                return
            if isinstance(node, ast.If):
                covered = guarded or self._is_boundary_guard(node.test)
                for child in node.body:
                    yield from scan(child, covered)
                for child in node.orelse:
                    # the else of a boundary guard is NOT at the boundary
                    yield from scan(child, guarded)
                return
            if isinstance(node, ast.Call):
                dotted = (dotted_name(node.func) or "").lower()
                if (
                    any(f in dotted for f in self.SNAPSHOT_FRAGMENTS)
                    and not guarded
                ):
                    yield ctx.finding(
                        self.code, node,
                        "decode-state snapshot/serialization inside a "
                        "serving loop with no chunk-boundary guard: this "
                        "host read runs EVERY iteration — gate it on a "
                        "boundary condition or a %-cadence (recognized "
                        "guard names: "
                        f"{', '.join(self.GUARD_HINTS)}) so migration "
                        "never adds a mid-chunk device sync",
                    )
            for child in ast.iter_child_nodes(node):
                yield from scan(child, guarded)

        for stmt in loop.body:
            yield from scan(stmt, False)


# ----------------------------------------------------- thread-model rules


def _thread_index(ctx: FileContext):
    """One thread-model index per file, shared by TL013/TL014/TL016
    (memoized on the FileContext like `_jax_index`)."""
    from dalle_pytorch_tpu.analysis.threadctx import ThreadIndex

    idx = getattr(ctx, "_thread_index", None)
    if idx is None:
        idx = ThreadIndex(ctx.tree, frozenset(ctx.thread_marker_lines))
        ctx._thread_index = idx
    return idx


def _root_names(roots) -> str:
    return ", ".join(sorted(roots))


class SharedStateRule(Rule):
    code = "TL013"
    name = "unguarded-shared-state"
    description = (
        "a self.* attribute compound-written on one thread root and "
        "accessed on another with no common lock between the two sides "
        "(augassign counters, container mutations, check-then-act "
        "rebinds; plain write-only flag rebinds are exempt)"
    )

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        index = _thread_index(ctx)
        seen: Set[Tuple[str, int]] = set()  # (attr, line): inheritance dedupe
        for model in index.classes:
            if not model.threaded:
                continue
            for attr, accs in sorted(model.by_attr().items()):
                finding = self._check_attr(ctx, model, attr, accs)
                if finding is None:
                    continue
                key = (attr, finding.line)
                if key not in seen:
                    seen.add(key)
                    yield finding

    def _check_attr(self, ctx, model, attr, accs) -> Optional[Finding]:
        from dalle_pytorch_tpu.analysis.threadctx import cross_root

        for c in sorted(
            (a for a in accs if a.compound),
            key=lambda a: getattr(a.node, "lineno", 0),
        ):
            for o in accs:
                if o.kind == "iterate":
                    continue  # the iterate-side conflict is TL014's
                if o is c and len(c.roots) < 2:
                    continue
                if not cross_root(c, o):
                    continue
                if c.locks & o.locks:
                    continue
                where = (
                    "it races itself across roots "
                    f"{_root_names(c.roots)}"
                    if o is c
                    else (
                        f"root(s) {_root_names(o.roots)} "
                        f"{'write' if o.kind != 'read' else 'read'} it "
                        f"near line {getattr(o.node, 'lineno', '?')}"
                        + (
                            " holding a different lock"
                            if o.locks
                            else " with no lock"
                        )
                    )
                )
                return ctx.finding(
                    self.code, c.node,
                    f"`self.{attr}` is written here on root(s) "
                    f"{_root_names(c.roots)}"
                    + (" under a lock" if c.locks else " with no lock")
                    + f", but {where} — guard both sides with one lock "
                    f"(e.g. `with self.{model.suggest_lock()}:`)",
                )
        return None


class IterateWhileMutatedRule(Rule):
    code = "TL014"
    name = "iterate-while-mutated"
    description = (
        "iterating a shared list/deque/dict attribute while another "
        "thread root mutates it, with no common lock between the two "
        "sides — the sampler-vs-/healthz RuntimeError shape; snapshot "
        "under the lock instead"
    )

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        from dalle_pytorch_tpu.analysis.threadctx import cross_root

        index = _thread_index(ctx)
        seen: Set[Tuple[str, int]] = set()
        for model in index.classes:
            if not model.threaded:
                continue
            for attr, accs in sorted(model.by_attr().items()):
                mutes = [a for a in accs if a.kind == "mutate"]
                if not mutes:
                    continue
                # the lock(s) every mutation site holds — the guard the
                # iteration must share (empty when mutations are split
                # across different locks or unguarded)
                guard = frozenset.intersection(*(m.locks for m in mutes))
                for it in (a for a in accs if a.kind == "iterate"):
                    conflict = next(
                        (
                            m for m in mutes
                            if cross_root(it, m) and not (it.locks & m.locks)
                        ),
                        None,
                    )
                    if conflict is None:
                        continue
                    key = (attr, getattr(it.node, "lineno", 0))
                    if key in seen:
                        continue
                    seen.add(key)
                    if guard:
                        fix = (
                            f"snapshot under the guard instead: `with "
                            f"self.{sorted(guard)[0]}: snap = "
                            f"list(self.{attr})` and iterate the snapshot"
                        )
                    else:
                        fix = (
                            "its mutations are unguarded too — pick one "
                            "lock for both sides, then iterate a "
                            "snapshot taken under it"
                        )
                    yield ctx.finding(
                        self.code, it.node,
                        f"`self.{attr}` is iterated here on root(s) "
                        f"{_root_names(it.roots)} while root(s) "
                        f"{_root_names(conflict.roots)} mutate it (line "
                        f"{getattr(conflict.node, 'lineno', '?')}) with "
                        f"no common lock — a mid-iteration mutation "
                        f"raises RuntimeError or yields torn state; {fix}",
                    )


class LockOrderRule(Rule):
    code = "TL015"
    name = "lock-order-inversion"
    description = (
        "two locks acquired in opposite nesting orders anywhere in the "
        "package — each thread can hold one and wait forever on the "
        "other; package-scope acquisition graph, cycles reported once"
    )
    package_scope = True

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        return iter(())  # package-scope: the driver calls check_package

    def check_package(self, contexts, package) -> Iterator[Finding]:
        # edge (A, B): lock B acquired while A is held; site list kept in
        # source order for deterministic reporting
        edges: Dict[Tuple[str, str], List[Tuple]] = {}
        for ctx in contexts:
            index = _thread_index(ctx)
            dedupe: Set[Tuple[str, str, int]] = set()  # inheritance dupes
            for held, acquired, via, node in index.lock_edges():
                key = (held, acquired, getattr(node, "lineno", 0))
                if key in dedupe:
                    continue
                dedupe.add(key)
                edges.setdefault((held, acquired), []).append(
                    (ctx, node, via)
                )

        graph: Dict[str, Set[str]] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)

        def reaches(src: str, dst: str) -> bool:
            seen, stack = set(), [src]
            while stack:
                n = stack.pop()
                if n == dst:
                    return True
                if n in seen:
                    continue
                seen.add(n)
                stack.extend(graph.get(n, ()))
            return False

        # every edge that sits on a cycle, grouped so each cycle (SCC)
        # is reported once at its earliest site
        cyclic: Dict[FrozenSet[str], List[Tuple]] = {}
        for (a, b), sites in edges.items():
            if not reaches(b, a):
                continue
            scc = frozenset(
                n for n in graph
                if reaches(a, n) and reaches(n, a)
            )
            for ctx, node, via in sites:
                cyclic.setdefault(scc, []).append((ctx, node, via, a, b))
        for scc, sites in sorted(
            cyclic.items(), key=lambda kv: sorted(kv[0])
        ):
            sites.sort(
                key=lambda s: (s[0].display_path, getattr(s[1], "lineno", 0))
            )
            ctx, node, via, a, b = sites[0]
            others = [
                f"{s[0].display_path}:{getattr(s[1], 'lineno', '?')} "
                f"({s[3]} -> {s[4]})"
                for s in sites[1:]
            ]
            yield ctx.finding(
                self.code, node,
                f"lock-order inversion: `{b}` is acquired here ({via}) "
                f"while `{a}` is held, but elsewhere the same locks nest "
                f"in the opposite order ({'; '.join(others) or 'cycle'}) "
                "— two threads can each hold one lock and wait forever "
                "on the other; pick ONE global order and re-nest",
            )


#: call-name terminals that read/write a socket (blocking I/O)
_SOCKET_CALLS = {
    "urlopen", "getresponse", "recv", "recv_into", "sendall", "sendto",
    "accept", "connect", "create_connection",
}
#: engine method-name fragments that dispatch device work or sync it
_ENGINE_DISPATCH_FRAGMENTS = (
    "generate", "prefill", "chunk", "release", "harvest", "decode",
    "resume", "dispatch", "warmup", "snapshot",
)


class BlockingUnderLockRule(Rule):
    code = "TL016"
    name = "blocking-under-lock"
    description = (
        "blocking call (time.sleep, thread join, event wait, socket "
        "I/O, engine dispatch) inside a `with <lock>:` body in serving/ "
        "or obs/ — every other thread contending that lock stalls for "
        "the call's full duration (head-of-line blocking)"
    )

    #: the serving stack's locks sit on its hot paths; training scripts
    #: hold no latency-critical locks and stay out of scope
    SCOPED_DIRS = ("serving", "obs")

    def _in_scope(self, ctx: FileContext) -> bool:
        return any(d in ctx.path.parts for d in self.SCOPED_DIRS)

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        if not self._in_scope(ctx):
            return
        index = _thread_index(ctx)
        seen: Set[int] = set()  # line dedupe across inherited models
        for model in index.classes:
            if not model.locks:
                continue
            for mname, func in model.methods.items():
                if mname == "__init__":
                    # construction happens-before thread start: nothing
                    # can contend a lock held during __init__ (the same
                    # exemption threadctx applies to access collection)
                    continue
                for finding in self._check_method(ctx, model, func):
                    if finding.line not in seen:
                        seen.add(finding.line)
                        yield finding

    def _blocking(self, node: ast.Call, model, held) -> Optional[str]:
        from dalle_pytorch_tpu.analysis.threadctx import _self_attr

        dotted = dotted_name(node.func) or ""
        fname = terminal_name(node.func)
        if dotted in ("time.sleep", "sleep"):
            return "`time.sleep` parks the thread with the lock held"
        if fname in _SOCKET_CALLS:
            return f"socket I/O (`{fname}`) blocks for a network round trip"
        recv = (
            node.func.value if isinstance(node.func, ast.Attribute) else None
        )
        recv_name = terminal_name(recv) if recv is not None else None
        recv_attr = _self_attr(recv)
        if fname == "join":
            # str.join is everywhere: only receivers that look like a
            # thread/process handle count (false-negative bias)
            name = recv_attr or recv_name or ""
            if any(h in name.lower() for h in ("thread", "worker", "proc")):
                return f"`{name}.join()` waits out another thread"
            return None
        if fname in ("wait", "wait_for"):
            # a condition's own wait RELEASES the lock while parked —
            # that is the designed idiom, not head-of-line blocking
            if recv_attr is not None and model.locks.get(recv_attr) in held:
                return None
            return (
                f"`.{fname}()` parks the thread while the lock stays "
                "held (only the held condition's own wait releases it)"
            )
        if recv_attr is not None and "engine" in recv_attr.lower() and any(
            f in (fname or "").lower() for f in _ENGINE_DISPATCH_FRAGMENTS
        ):
            return (
                f"engine dispatch `self.{recv_attr}.{fname}(...)` runs "
                "device work under the lock — the batcher releases its "
                "lock around dispatch for exactly this reason"
            )
        if recv_name is not None and "engine" in recv_name.lower() and any(
            f in (fname or "").lower() for f in _ENGINE_DISPATCH_FRAGMENTS
        ):
            return (
                f"engine dispatch `{recv_name}.{fname}(...)` runs device "
                "work under the lock"
            )
        return None

    def _check_method(self, ctx, model, func) -> Iterator[Finding]:
        from dalle_pytorch_tpu.analysis.threadctx import _ALL_FUNCS, _self_attr

        def scan(node, held) -> Iterator[Finding]:
            if isinstance(node, _ALL_FUNCS):
                return
            if isinstance(node, ast.With):
                new = set()
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    if attr is not None and attr in model.locks:
                        new.add(model.locks[attr])
                held2 = held | frozenset(new)
                for stmt in node.body:
                    yield from scan(stmt, held2)
                return
            if isinstance(node, ast.Call) and held:
                why = self._blocking(node, model, held)
                if why is not None:
                    lock = sorted(held)[0]
                    yield ctx.finding(
                        self.code, node,
                        f"blocking call while holding `self.{lock}`: "
                        f"{why} — move it outside the `with` block or "
                        "justify the hold with a suppression",
                    )
            for child in ast.iter_child_nodes(node):
                yield from scan(child, held)

        body = func.body if isinstance(func.body, list) else []
        for stmt in body:
            yield from scan(stmt, frozenset())


# --------------------------------------------------------------------------
# TL017-TL021: sharding & donation dataflow (analysis/shardctx.py).
# The zero-compile serving contract rests on sharding invariants no test
# sees until they break at scale: every ladder program's out_shardings
# must be a fixed point of the donated decode state, donation must never
# silently degrade to allocate+copy, and no hot-path dispatch may
# introduce an implicit reshard. These rules read the per-file ShardIndex
# (mesh bindings, placements, program summaries, the hotloop frontier)
# and compare SpecRefs with three-valued `specs_differ` — UNKNOWN is
# always clean, per the pack's false-negative bias.


class OutShardingsPinRule(Rule):
    code = "TL017"
    name = "unpinned-ladder-sharding"
    description = (
        "mesh-aware jit program without pinned out_shardings: a program "
        "registered through the `_sharded_program` ladder cache, or a "
        "donating jit that declares in_shardings, must pin out_shardings "
        "— unpinned, GSPMD may hand back a drifted output sharding that "
        "re-keys the jit cache on the next dispatch (a silent warm-path "
        "recompile) or re-lays-out the donated state every cycle"
    )

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        idx = shard_index(ctx)
        for prog in idx.programs:
            if prog.kind != "jit" or prog.has_out:
                continue
            if prog.registered:
                yield ctx.finding(
                    self.code, prog.node,
                    f"ladder program {prog.name!r} is registered via "
                    "_sharded_program without out_shardings= — pin it to "
                    "the canonical state shardings so the donated "
                    "state's sharding is a fixed point from dispatch one "
                    "(the warm server's zero-recompile contract)",
                )
            elif prog.has_in and prog.donated:
                yield ctx.finding(
                    self.code, prog.node,
                    f"jit program {prog.name!r} declares in_shardings "
                    "and donates argument(s) "
                    f"{sorted(prog.donated)} but pins no out_shardings "
                    "— GSPMD chooses the output layout per dispatch, so "
                    "the donated buffer's sharding can drift and re-key "
                    "the jit cache (warm-path recompile)",
                )


class DonationShardingMismatchRule(Rule):
    code = "TL018"
    name = "donation-sharding-mismatch"
    description = (
        "donated jit argument whose declared input sharding matches NO "
        "declared output sharding: XLA can only reuse the donated buffer "
        "for an output with the identical layout, so the donation "
        "silently degrades to an allocate+copy on every dispatch"
    )

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        idx = shard_index(ctx)
        for prog in idx.programs:
            if prog.kind != "jit" or not prog.donated:
                continue
            if not prog.has_in or not prog.has_out:
                continue
            outs = prog.out_spec_candidates()
            if not outs:
                continue
            for k in prog.donated:
                in_ref = prog.in_spec_at(k)
                if in_ref is None:
                    continue
                verdicts = [specs_differ(in_ref, o) for o in outs]
                if verdicts and all(v is True for v in verdicts):
                    yield ctx.finding(
                        self.code, prog.node,
                        f"program {prog.name!r} donates argument {k} "
                        f"placed as {in_ref.render()}, but every "
                        "declared output sharding differs "
                        f"({', '.join(o.render() for o in outs)}) — the "
                        "donated buffer cannot be reused, so donation "
                        "becomes an allocate+copy each dispatch",
                    )


class ImplicitReshardRule(Rule):
    code = "TL019"
    name = "hotloop-implicit-reshard"
    description = (
        "a value placed under one sharding is passed, on a `# tracelint: "
        "hotloop`-reachable path, to a jit program or shard_map whose "
        "declared in sharding for that position differs — GSPMD inserts "
        "a resharding collective in front of EVERY dispatch (an implicit "
        "all-to-all per token). Package-scope: the program may be "
        "summarized in another file."
    )
    package_scope = True

    def check_package(self, contexts, package) -> Iterator[Finding]:
        summaries = package_summaries(contexts)
        for ctx in contexts:
            idx = shard_index(ctx)
            if not idx.hot:
                continue
            placements_of: Dict[int, Dict] = {}
            for func, call in iter_hot_calls(idx):
                name = terminal_name(call.func)
                entry = summaries.get(name or "")
                if entry is None:
                    continue
                prog, _owner = entry
                if not prog.has_in:
                    continue
                if id(func) not in placements_of:
                    placements_of[id(func)] = idx.local_placements(func)
                placements = placements_of[id(func)]
                for i, arg in enumerate(call.args):
                    sym = dotted_name(arg)
                    if sym is None or sym not in placements:
                        continue
                    have = placements[sym]
                    want = prog.in_spec_at(i)
                    if specs_differ(have, want) is True:
                        yield ctx.finding(
                            self.code, call,
                            f"hot-path dispatch reshards `{sym}`: placed "
                            f"as {have.render()} but {prog.kind} program "
                            f"{prog.name!r} declares "
                            f"{want.render()} for argument {i} — GSPMD "
                            "inserts a resharding collective on every "
                            "dispatch; place the value under the "
                            "program's sharding once, outside the loop",
                        )


class DivisibilityFallbackRule(Rule):
    code = "TL020"
    name = "divisibility-assumed"
    description = (
        "NamedSharding built from a literal axis-naming PartitionSpec "
        "with no `partition.py:_divisible` fallback (or explicit `%` "
        "divisibility check) anywhere in the enclosing function — an "
        "axis that does not divide the dimension must drop to replicated "
        "(the 2-head toy model on an 8-way mesh), not assume it divides"
    )

    @staticmethod
    def _guarded(scope_nodes) -> bool:
        """Does the scope call `_divisible` (any dotted terminal) or
        compute a `%` anywhere (divisibility assert/cadence guard)?"""
        for node in scope_nodes:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and terminal_name(
                    sub.func
                ) == "_divisible":
                    return True
                if isinstance(sub, ast.BinOp) and isinstance(
                    sub.op, ast.Mod
                ):
                    return True
        return False

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        from dalle_pytorch_tpu.analysis.shardctx import spec_ref_of

        # enclosing def chain per NamedSharding call (module body when
        # the call sits at top level)
        stack: List[ast.AST] = []
        hits: List[Tuple[ast.Call, Tuple[ast.AST, ...]]] = []

        def visit(node: ast.AST) -> None:
            if isinstance(node, ast.Call) and terminal_name(
                node.func
            ) == "NamedSharding":
                hits.append((node, tuple(stack)))
            is_func = isinstance(node, _ALL_FUNCS)
            if is_func:
                stack.append(node)
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_func:
                stack.pop()

        visit(ctx.tree)
        for call, chain in hits:
            ref = spec_ref_of(call)
            if ref is None or ref.kind != "literal":
                continue
            axes = ref.named_axes()
            if not axes:
                continue
            scope = chain if chain else (ctx.tree,)
            if self._guarded(scope):
                continue
            yield ctx.finding(
                self.code, call,
                f"NamedSharding names axis(es) {sorted(axes)} with no "
                "`_divisible` fallback (or `%` divisibility check) in "
                "the enclosing scope — a non-dividing dimension should "
                "drop to replicated, not error or shard unevenly; route "
                "the spec through partition.py:_divisible",
            )


#: host-read builtins whose argument leaves the device wholesale
_HOST_READ_BUILTINS = {"float", "int", "bool"}


class ShardedHostReadRule(Rule):
    code = "TL021"
    name = "hotloop-sharded-gather"
    description = (
        "host read (`jax.device_get`, `np.asarray`/`np.array`, "
        "float/int/bool) of a value placed under a mesh-splitting "
        "sharding inside a `# tracelint: hotloop`-reachable function — "
        "the read gathers the FULL array across the mesh every chunk; "
        "read a replicated leaf, or snapshot at chunk boundaries only"
    )

    @staticmethod
    def _read_target(call: ast.Call) -> Optional[ast.AST]:
        fname = terminal_name(call.func)
        if fname == "device_get" and call.args:
            return call.args[0]
        if _is_np_call(call, ("asarray", "array")) and call.args:
            return call.args[0]
        if (
            isinstance(call.func, ast.Name)
            and call.func.id in _HOST_READ_BUILTINS
            and len(call.args) == 1
        ):
            return call.args[0]
        return None

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        idx = shard_index(ctx)
        if not idx.hot:
            return
        placements_of: Dict[int, Dict] = {}
        for func, call in iter_hot_calls(idx):
            target = self._read_target(call)
            if target is None:
                continue
            # unwrap one indexing layer: np.asarray(state.row[rows]) is
            # still a host read of the sharded leaf
            if isinstance(target, ast.Subscript):
                target = target.value
            sym = dotted_name(target)
            if sym is None:
                continue
            if id(func) not in placements_of:
                placements_of[id(func)] = idx.local_placements(func)
            ref = placements_of[id(func)].get(sym)
            if ref is None or ref.kind != "literal":
                continue
            axes = ref.named_axes()
            if not axes:
                continue
            yield ctx.finding(
                self.code, call,
                f"hot-loop host read of `{sym}`, placed under "
                f"{ref.render()} (split over {sorted(axes)}) — this "
                "gathers the full array across the mesh on every "
                "iteration; keep host-read leaves replicated (the "
                "serving_partition row-scalar rule) or read at chunk "
                "boundaries only",
            )


class MetricsCardinalityRule(Rule):
    code = "TL022"
    name = "metrics-cardinality"
    description = (
        "request-scoped data (trace IDs, prompts, raw tenant/user "
        "strings) used as a metric label value — every distinct value "
        "mints a new child series, so an open endpoint grows the "
        "registry and every scrape body without bound; route the value "
        "through a bounding clamp (charset/length cap + `__other__` "
        "overflow) first"
    )

    #: label hygiene is a serving/observability contract; offline
    #: training scripts don't expose a scrape endpoint to open traffic
    SCOPED_DIRS = ("serving", "obs")

    #: identifier fragments that mark a value as request-scoped. A
    #: heuristic by design (false-negative bias, like TL010's backoff
    #: list): `trace_id`, `req.prompt`, `body["tenant"]`, `user_id`
    #: all match; opaque locals (`label`, `reason`, `name`) stay silent.
    REQUEST_HINTS = (
        "trace", "prompt", "request_id", "request_key", "tenant",
        "user_id",
    )
    REQUEST_EXACT = ("user",)

    #: call-name fragments that count as cardinality discipline — a
    #: value routed through one of these is trusted as bounded (the
    #: UsageLedger `_bounded_tenant` -> `__other__` pattern).
    BOUND_HINTS = ("bound", "clamp", "intern", "bucket", "canonical",
                   "cap")

    def _in_scope(self, ctx: FileContext) -> bool:
        return any(d in ctx.path.parts for d in self.SCOPED_DIRS)

    @classmethod
    def _risky_ident(cls, ident: Optional[str]) -> bool:
        if not ident:
            return False
        s = ident.lower()
        return s in cls.REQUEST_EXACT or any(
            h in s for h in cls.REQUEST_HINTS
        )

    @classmethod
    def _risky_source(cls, node: ast.AST) -> Optional[str]:
        """The identifier that makes `node` request-scoped, or None.
        Descends through pass-through calls (`str(...)`, f-strings,
        concats) but treats a bounding call as a trust boundary."""
        if isinstance(node, ast.Call):
            dotted = (dotted_name(node.func) or "").lower()
            if any(h in dotted for h in cls.BOUND_HINTS):
                return None  # clamped: trusted
            for arg in list(node.args) + [k.value for k in node.keywords]:
                hit = cls._risky_source(arg)
                if hit:
                    return hit
            return None
        if isinstance(node, ast.Subscript):
            key = node.slice
            if isinstance(key, ast.Constant) and isinstance(key.value, str):
                if cls._risky_ident(key.value):
                    return f'[{key.value!r}]'
            return cls._risky_source(node.value)
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Name, ast.Attribute)):
                ident = terminal_name(sub)
                if cls._risky_ident(ident):
                    return ident
        return None

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        if not self._in_scope(ctx):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("labels", "labels_extra")):
                continue
            values = list(node.args) + [k.value for k in node.keywords]
            for value in values:
                hit = self._risky_source(value)
                if hit is None:
                    continue
                yield ctx.finding(
                    self.code, node,
                    f"request-scoped value `{hit}` used as a metric "
                    "label — every distinct value mints a new child "
                    "series, so an open endpoint grows the registry "
                    "and every scrape body without bound; clamp it "
                    "first (charset/length cap with an `__other__` "
                    "overflow bucket — recognized call names: "
                    f"{', '.join(self.BOUND_HINTS)})",
                )
                break  # one finding per call site


ALL_RULES: Tuple[Rule, ...] = (
    TracerBranchRule(),
    HostSyncRule(),
    DonatedReuseRule(),
    KeyReuseRule(),
    DtypeDriftRule(),
    DebuggerArtifactRule(),
    ScanConstUploadRule(),
    MeshAxisRule(),
    SpanLeakRule(),
    RetryHygieneRule(),
    WarmupCoverageRule(),
    ChunkBoundarySnapshotRule(),
    SharedStateRule(),
    IterateWhileMutatedRule(),
    LockOrderRule(),
    BlockingUnderLockRule(),
    OutShardingsPinRule(),
    DonationShardingMismatchRule(),
    ImplicitReshardRule(),
    DivisibilityFallbackRule(),
    ShardedHostReadRule(),
    MetricsCardinalityRule(),
)
