"""JAX-awareness for tracelint: which functions run under tracing, which
of their parameters are traced (vs static), and which callables donate
which arguments.

Everything here is a HEURISTIC over the AST — intraprocedural by design
(ISSUE: arg-flow, not whole-program dataflow). The detectors cover the
idioms this codebase actually uses:

  traced functions
    * `@jax.jit` / `@jit` / `@pjit` decorators, plain or via
      `@functools.partial(jax.jit, static_argnums=..., static_argnames=...)`
    * `g = jax.jit(f, ...)` rebinding a local def
    * bodies passed to `jax.lax.scan` / `lax.scan` (first positional arg);
      every parameter of a scan body is traced
    * ONE-HOP cross-procedural propagation: a same-file def whose EVERY
      call site sits inside an already-traced function inherits
      tracedness (the `_*_impl` body factored out of a jitted entry
      point). Parameters are traced only where some call site passes a
      traced value; a single host call site disables the inheritance, and
      inherited functions never propagate further (one hop, no fixpoint —
      depth keeps the false-positive surface auditable)

  donated callables (for TL003)
    * `jax.jit(f, donate_argnums=(k,))` and the partial-decorator form
    * this repo's jit-cache idiom: a builder function tagged with a
      module-level `builder._donate_argnums = (k,)` assignment, dispatched
      through `_jit_sample(builder, model, static_key, *args)` — donated
      positional index among *args is k, i.e. call-site index 3 + k. A
      public wrapper whose body just returns such a `_jit_sample` call
      donates its own parameter at the matching position, so call sites in
      OTHER files (the serving engine) inherit the donation contract.

False-negative bias: when a construct is not recognized, the function is
simply not traced/donating and rules stay silent — a lint must earn trust
before it earns strictness.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterator, List, Optional, Sequence, Set, Tuple

FunctionNode = (ast.FunctionDef, ast.AsyncFunctionDef)

#: attribute accesses that are static under tracing even on a tracer
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval"}
#: calls that are static under tracing regardless of their argument
STATIC_CALLS = {
    "len", "isinstance", "hasattr", "type", "getattr", "id", "repr",
    "ndim", "shape", "result_type", "issubdtype", "format",
}
_JIT_NAMES = {"jit", "pjit"}


def terminal_name(node: ast.AST) -> Optional[str]:
    """`jax.lax.scan` -> "scan", `jit` -> "jit", else None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def dotted_name(node: ast.AST) -> Optional[str]:
    """`jax.lax.scan` -> "jax.lax.scan" (None when any link isn't a name)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _int_elements(node: ast.AST) -> Tuple[int, ...]:
    """Constant int / tuple-or-list of constant ints -> values; else ()."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                out.append(el.value)
            else:
                return ()
        return tuple(out)
    return ()


def _str_elements(node: ast.AST) -> Tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.append(el.value)
            else:
                return ()
        return tuple(out)
    return ()


def param_names(func: ast.AST) -> List[str]:
    a = func.args
    return [p.arg for p in a.posonlyargs + a.args]


@dataclass
class TracedInfo:
    func: ast.AST  # FunctionDef / Lambda
    kind: str  # "jit" | "scan"
    static_params: FrozenSet[str] = frozenset()

    def traced_params(self) -> Set[str]:
        names = set(param_names(self.func))
        if self.kind != "scan":
            # `self`-style first params of decorated methods stay module
            # references, not tracers
            names.discard("self")
        return names - set(self.static_params)


def _statics_from_jit_call(call: ast.Call, func: ast.AST) -> FrozenSet[str]:
    """static_argnums/static_argnames of a jit(...) call -> param names."""
    names = param_names(func)
    statics: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for i in _int_elements(kw.value):
                if 0 <= i < len(names):
                    statics.add(names[i])
        elif kw.arg == "static_argnames":
            statics.update(_str_elements(kw.value))
    return frozenset(statics)


def _donate_from_jit_call(
    call: ast.Call, func: Optional[ast.AST] = None
) -> Tuple[int, ...]:
    """Donated positional indices of a jit(...) call. `donate_argnames`
    resolves through `func`'s parameter list when the wrapped def/lambda is
    known; without it names cannot map to positions and are dropped."""
    out: List[int] = []
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            out.extend(_int_elements(kw.value))
        elif kw.arg == "donate_argnames" and func is not None:
            names = param_names(func)
            out.extend(
                names.index(n) for n in _str_elements(kw.value) if n in names
            )
    return tuple(out)


class JaxIndex:
    """Per-file index of traced functions, built once by the driver."""

    def __init__(self, tree: ast.Module):
        self.tree = tree
        self.traced: Dict[ast.AST, TracedInfo] = {}
        self._defs: Dict[str, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, FunctionNode):
                # last def wins on name collision; fine for an index that
                # only resolves scan bodies / jit rebinding heuristically
                self._defs[node.name] = node
        self._find_decorated()
        self._find_rebound()
        self._find_scan_bodies()
        self._find_called_from_traced()

    # ------------------------------------------------------------ detection

    def _mark(self, func: ast.AST, kind: str, statics: FrozenSet[str] = frozenset()):
        prev = self.traced.get(func)
        if prev is None or (prev.kind == "scan" and kind == "jit"):
            self.traced[func] = TracedInfo(func, kind, statics)

    def _find_decorated(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, FunctionNode):
                continue
            for dec in node.decorator_list:
                if terminal_name(dec) in _JIT_NAMES:
                    self._mark(node, "jit")
                elif isinstance(dec, ast.Call):
                    if terminal_name(dec.func) in _JIT_NAMES:
                        self._mark(node, "jit", _statics_from_jit_call(dec, node))
                    elif terminal_name(dec.func) == "partial" and dec.args:
                        if terminal_name(dec.args[0]) in _JIT_NAMES:
                            self._mark(
                                node, "jit", _statics_from_jit_call(dec, node)
                            )

    def _find_rebound(self) -> None:
        """`g = jax.jit(f, ...)`: mark f's def as traced."""
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            if terminal_name(node.func) not in _JIT_NAMES or not node.args:
                continue
            target = node.args[0]
            if isinstance(target, ast.Lambda):
                self._mark(target, "jit")
            else:
                name = terminal_name(target)
                if name and name in self._defs:
                    self._mark(
                        self._defs[name], "jit",
                        _statics_from_jit_call(node, self._defs[name]),
                    )

    def _find_scan_bodies(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            if terminal_name(node.func) != "scan" or not node.args:
                continue
            dotted = dotted_name(node.func) or ""
            if not (dotted.endswith("lax.scan") or dotted == "scan"):
                continue
            body = node.args[0]
            if isinstance(body, ast.Lambda):
                self._mark(body, "scan")
            else:
                name = terminal_name(body)
                if name and name in self._defs:
                    self._mark(self._defs[name], "scan")

    def _find_called_from_traced(self) -> None:
        """One-hop cross-procedural propagation: a def whose EVERY call
        site in this file sits inside an already-traced function body runs
        under tracing itself — the `_*_impl` helper factored out of a
        jitted entry point. A parameter is traced where ANY traced call
        site feeds it a traced value. One hop only: the snapshot below
        fixes the caller set, so an inherited function never propagates
        to ITS callees (no fixpoint — each extra hop multiplies the
        heuristic's error, and one covers the factoring idiom). Any host
        call site (including module level) disables inheritance: the
        helper demonstrably runs both ways, and flagging its host uses
        would be pure noise."""
        callers = dict(self.traced)  # snapshot: the one-hop frontier
        enclosing: Dict[int, Optional[ast.AST]] = {}

        def visit(node: ast.AST, owner: Optional[ast.AST]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.Call):
                    enclosing[id(child)] = owner
                visit(
                    child,
                    child
                    if isinstance(child, FunctionNode + (ast.Lambda,))
                    else owner,
                )

        visit(self.tree, None)
        sites: Dict[str, List[ast.Call]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                name = terminal_name(node.func)
                if name in self._defs:
                    sites.setdefault(name, []).append(node)
        for name, calls in sites.items():
            func = self._defs[name]
            if func in self.traced:
                continue
            owners = [enclosing.get(id(c)) for c in calls]
            if any(o is None or o not in callers for o in owners):
                continue
            names = param_names(func)
            traced_at_site: Set[str] = set()
            for call, owner in zip(calls, owners):
                info = callers[owner]
                taint = propagate_traced(info.func, info.traced_params())
                # attribute calls bind the receiver to `self`: positional
                # args start at the second parameter
                pos = (
                    names[1:]
                    if names[:1] == ["self"]
                    and isinstance(call.func, ast.Attribute)
                    else names
                )
                for i, arg in enumerate(call.args):
                    if i < len(pos) and mentions_traced(arg, taint):
                        traced_at_site.add(pos[i])
                for kw in call.keywords:
                    if kw.arg in names and mentions_traced(kw.value, taint):
                        traced_at_site.add(kw.arg)
            if traced_at_site:
                self._mark(
                    func, "jit-called",
                    frozenset(set(names) - traced_at_site),
                )


# --------------------------------------------------------------- arg flow


def mentions_traced(node: ast.AST, traced: Set[str]) -> bool:
    """Does evaluating `node` read a traced value (vs only static facts
    like .shape / len() / isinstance())?"""
    if isinstance(node, ast.Name):
        return node.id in traced
    if isinstance(node, ast.Attribute):
        if node.attr in STATIC_ATTRS:
            return False
        return mentions_traced(node.value, traced)
    if isinstance(node, ast.Call):
        if terminal_name(node.func) in STATIC_CALLS:
            return False
        parts = [node.func] + list(node.args) + [kw.value for kw in node.keywords]
        return any(mentions_traced(p, traced) for p in parts)
    if isinstance(node, ast.Constant):
        return False
    return any(
        mentions_traced(child, traced) for child in ast.iter_child_nodes(node)
    )


def _assign_targets(node: ast.AST) -> Iterator[ast.Name]:
    """Flat Name targets of an assignment target (tuples unpacked)."""
    if isinstance(node, ast.Name):
        yield node
    elif isinstance(node, (ast.Tuple, ast.List)):
        for el in node.elts:
            yield from _assign_targets(el)
    elif isinstance(node, ast.Starred):
        yield from _assign_targets(node.value)


def propagate_traced(func: ast.AST, traced: Set[str]) -> Set[str]:
    """One linear pass over the function body: a name assigned from an
    expression that mentions a traced value becomes traced itself
    (`a, b = carry`; `x = img_pos + 1`). Conservative: names are never
    un-tainted (no CFG)."""
    taint = set(traced)
    body = func.body if isinstance(func.body, list) else []
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Assign):
                if mentions_traced(node.value, taint):
                    for t in node.targets:
                        taint.update(n.id for n in _assign_targets(t))
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target, ast.Name) and mentions_traced(
                    node.value, taint
                ):
                    taint.add(node.target.id)
            elif isinstance(node, (ast.AnnAssign, ast.NamedExpr)):
                target = node.target
                if node.value is not None and mentions_traced(node.value, taint):
                    taint.update(n.id for n in _assign_targets(target))
    return taint


# ------------------------------------------------------- donation registry


@dataclass
class DonationRegistry:
    """Package-wide map of donating callables: bare name -> donated
    positional arg indices at the CALL SITE."""

    donors: Dict[str, FrozenSet[int]] = field(default_factory=dict)
    #: builder name -> donated index within the built fn's params
    builders: Dict[str, FrozenSet[int]] = field(default_factory=dict)

    @classmethod
    def build(cls, trees: Sequence[ast.Module]) -> "DonationRegistry":
        """Two passes: builder tags / direct jit donations first, THEN the
        wrapper inference — a wrapper in one file may dispatch a builder
        defined in another."""
        reg = cls()
        for tree in trees:
            reg._collect_jit_donations(tree)
            reg._collect_builder_tags(tree)
        for tree in trees:
            reg._collect_wrappers(tree)
        return reg

    def _collect_jit_donations(self, tree: ast.Module) -> None:
        defs: Dict[str, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, FunctionNode):
                defs[node.name] = node
        for node in ast.walk(tree):
            # g = jax.jit(f, donate_argnums=...) — resolve f for argnames
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                call = node.value
                if terminal_name(call.func) in _JIT_NAMES:
                    wrapped = call.args[0] if call.args else None
                    func = (
                        wrapped
                        if isinstance(wrapped, ast.Lambda)
                        else defs.get(terminal_name(wrapped) or "")
                    )
                    idx = _donate_from_jit_call(call, func)
                    if idx:
                        for t in node.targets:
                            name = terminal_name(t)
                            if name:
                                self.donors[name] = frozenset(idx)
            # @partial(jax.jit, donate_argnums=...) / @jax.jit(...) decorator
            if isinstance(node, FunctionNode):
                for dec in node.decorator_list:
                    if not isinstance(dec, ast.Call):
                        continue
                    is_jit = terminal_name(dec.func) in _JIT_NAMES or (
                        terminal_name(dec.func) == "partial"
                        and dec.args
                        and terminal_name(dec.args[0]) in _JIT_NAMES
                    )
                    if is_jit:
                        idx = _donate_from_jit_call(dec, node)
                        if idx:
                            self.donors[node.name] = frozenset(idx)

    def _collect_builder_tags(self, tree: ast.Module) -> None:
        """`builder._donate_argnums = (k,)` module-level assignments."""
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and t.attr == "_donate_argnums"
                    and isinstance(t.value, ast.Name)
                ):
                    idx = _int_elements(node.value)
                    if idx:
                        self.builders[t.value.id] = frozenset(idx)

    def _collect_wrappers(self, tree: ast.Module) -> None:
        """A function whose body returns `_jit_sample(builder, model, key,
        *args)` donates its own parameter standing at args position 3+k."""
        for node in ast.walk(tree):
            if not isinstance(node, FunctionNode):
                continue
            names = param_names(node)
            for ret in ast.walk(node):
                if not (isinstance(ret, ast.Return) and isinstance(ret.value, ast.Call)):
                    continue
                donated = self.call_donated_indices(ret.value)
                wrapper_idx = set()
                for i in donated:
                    if i < len(ret.value.args):
                        arg = ret.value.args[i]
                        if isinstance(arg, ast.Name) and arg.id in names:
                            wrapper_idx.add(names.index(arg.id))
                if wrapper_idx:
                    self.donors[node.name] = frozenset(wrapper_idx)

    # -------------------------------------------------------------- queries

    def call_donated_indices(self, call: ast.Call) -> FrozenSet[int]:
        """Positional arg indices of `call` whose buffers are donated."""
        fname = terminal_name(call.func)
        if fname is None:
            return frozenset()
        if fname in ("_jit_sample", "_jitted_sampler") and call.args:
            builder = terminal_name(call.args[0])
            if builder in self.builders:
                # _jit_sample(builder, model, static_key, *fn_args)
                return frozenset(3 + k for k in self.builders[builder])
        if fname in self.donors:
            return self.donors[fname]
        return frozenset()
