"""tracelint driver: walk files, run rules, apply suppressions + baseline.

Usage (CLI is also installed as `dalle-tpu-lint`):

    python -m dalle_pytorch_tpu.analysis                      # lint the package
    python -m dalle_pytorch_tpu.analysis path/ other.py       # explicit paths
    python -m dalle_pytorch_tpu.analysis --format json
    python -m dalle_pytorch_tpu.analysis --format github   # CI annotations
    python -m dalle_pytorch_tpu.analysis --select TL003,TL006
    python -m dalle_pytorch_tpu.analysis --rules TL013,TL014  # alias
    python -m dalle_pytorch_tpu.analysis --exclude-rules TL016
    python -m dalle_pytorch_tpu.analysis --watch              # incremental
    python -m dalle_pytorch_tpu.analysis --changed            # vs HEAD
    python -m dalle_pytorch_tpu.analysis --changed main       # vs a ref
    python -m dalle_pytorch_tpu.analysis --write-baseline     # grandfather

Exit codes are a severity bitmask: 0 clean, bit 0 (1) new error-tier
findings, bit 2 (4) new warning-tier findings (TL002's hot-loop tier) —
so 1 = errors only, 4 = warnings only, 5 = both; 2 stays the
usage/internal-error code. CI that only blocks on errors can test
`rc & 1`; `rc != 0` keeps the strict gate.

The driver builds the package-wide `DonationRegistry` over EVERY file it
was pointed at before running per-file rules, so TL003 sees donation
contracts across module boundaries (the serving engine donates state to
dispatchers defined in models/dalle.py).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional, Sequence, Set, Tuple

from dalle_pytorch_tpu.analysis.baseline import (
    DEFAULT_BASELINE,
    load_baseline,
    split_baselined,
    write_baseline,
)
from dalle_pytorch_tpu.analysis.core import FileContext, Finding, LintResult
from dalle_pytorch_tpu.analysis.jaxctx import DonationRegistry
from dalle_pytorch_tpu.analysis.rules import ALL_RULES

PACKAGE_DIR = Path(__file__).resolve().parents[1]

#: directories never descended into
_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def iter_python_files(paths: Sequence[Path]) -> List[Tuple[Path, str]]:
    """Expand `paths` to [(file, stable_path)]. `stable_path` is the file
    relative to the lint root it was found under (dir roots) or its name
    (file roots) — invocation-directory-independent, so baselines written
    anywhere keep matching. Raises FileNotFoundError on a path that
    doesn't exist: a typo'd CI path must be a loud usage error, not a
    permanently-green '0 findings over 0 files' run."""
    files: List[Tuple[Path, str]] = []
    for p in paths:
        if p.is_dir():
            for sub in sorted(p.rglob("*.py")):
                if not _SKIP_DIRS & set(part for part in sub.parts):
                    files.append((sub, sub.relative_to(p).as_posix()))
        elif p.is_file():
            if p.suffix == ".py":
                files.append((p, p.name))
        else:
            raise FileNotFoundError(f"no such file or directory: {p}")
    return files


def changed_python_files(ref: str = "HEAD") -> List[Path]:
    """Python files changed vs `ref` (committed, staged, or unstaged)
    plus untracked ones — the `--changed` pre-commit surface. Paths come
    back repo-root-anchored so the lint works from any subdirectory.
    Raises RuntimeError when git is unavailable, the cwd is not a work
    tree, or `ref` does not resolve."""
    import subprocess

    def git(*argv: str) -> str:
        try:
            proc = subprocess.run(
                ["git", *argv], capture_output=True, text=True
            )
        except OSError as exc:
            raise RuntimeError(f"git unavailable: {exc}")
        if proc.returncode != 0:
            detail = proc.stderr.strip().splitlines()
            raise RuntimeError(
                detail[-1] if detail else f"git {argv[0]} failed"
            )
        return proc.stdout

    top = Path(git("rev-parse", "--show-toplevel").strip())
    names = set(
        git(
            "diff", "--name-only", "--diff-filter=d", ref, "--", "*.py"
        ).splitlines()
    )
    names.update(
        git(
            "ls-files", "--others", "--exclude-standard", "--", "*.py"
        ).splitlines()
    )
    return sorted(
        top / n for n in names if n and (top / n).is_file()
    )


def _display_path(path: Path) -> str:
    try:
        return path.resolve().relative_to(Path.cwd()).as_posix()
    except ValueError:
        return path.as_posix()


def _apply_suppressions(
    ctx: FileContext,
    findings: List[Finding],
    unsuppressible: Set[str],
    result: LintResult,
) -> None:
    for f in findings:
        sup = None if f.rule in unsuppressible else ctx.suppressed(f)
        if sup is not None:
            result.suppressed.append((f, sup))
        else:
            result.findings.append(f)


def lint_paths(
    paths: Sequence[Path],
    select: Optional[Set[str]] = None,
    baseline_fingerprints: Optional[Set[str]] = None,
    cache=None,
) -> LintResult:
    """Run the rule pack over `paths` (files or directories).

    `select` restricts to a set of rule codes (TL000 framework findings
    are only emitted when unrestricted or explicitly selected).
    `cache` (an `analysis.watch.LintCache`) makes the run incremental:
    unchanged files (by content fingerprint) skip re-parsing, and skip
    rule execution too when the cross-file facts they depend on are
    unchanged. Per-rule wall time for the work actually executed lands
    in `LintResult.rule_times`.
    """
    import time as _time

    rules = [
        r for r in ALL_RULES if select is None or r.code in select
    ]
    # TL000 and opt-out-free rules (TL006) ignore suppression comments
    unsuppressible = {"TL000"} | {
        r.code for r in ALL_RULES if not r.suppressible
    }
    files = iter_python_files([Path(p) for p in paths])

    if cache is not None:
        cache.begin_run()
    contexts: List[FileContext] = []
    result = LintResult()
    for path, stable in files:
        try:
            ctx = None
            if cache is not None:
                ctx = cache.context_for(path, _display_path(path), stable)
            if ctx is None:
                source = path.read_text(encoding="utf-8")
                ctx = FileContext(path, _display_path(path), source, stable)
            contexts.append(ctx)
        except (SyntaxError, UnicodeDecodeError, OSError) as exc:
            result.findings.append(
                Finding(
                    rule="TL000",
                    path=_display_path(path),
                    line=getattr(exc, "lineno", 1) or 1,
                    message=f"file could not be parsed: {exc.__class__.__name__}",
                    stable_path=stable,
                )
            )
    result.files_checked = len(contexts)

    registry = DonationRegistry.build([c.tree for c in contexts])
    file_rules = [r for r in rules if not r.package_scope]
    package_rules = [r for r in rules if r.package_scope]
    emit_tl000 = select is None or "TL000" in select
    rule_times: dict = {}
    # the finding cache is valid only while the cross-file facts a
    # per-file rule can read are unchanged (TL003's donation registry);
    # the select set is part of the key so --rules runs don't alias
    xkey = None
    if cache is not None:
        xkey = cache.cross_file_key(registry, select)

    for ctx in contexts:
        cached = cache.findings_for(ctx, xkey) if cache is not None else None
        if cached is not None:
            kept, suppressed = cached
            result.findings.extend(kept)
            result.suppressed.extend(suppressed)
            continue
        mine: List[Finding] = []
        for rule in file_rules:
            t0 = _time.perf_counter()
            mine.extend(rule.check(ctx, registry))
            rule_times[rule.code] = (
                rule_times.get(rule.code, 0.0) + _time.perf_counter() - t0
            )
        if emit_tl000:
            mine.extend(ctx.malformed_suppressions())
        local = LintResult()
        _apply_suppressions(ctx, mine, unsuppressible, local)
        if cache is not None:
            cache.store_findings(ctx, xkey, local.findings, local.suppressed)
        result.findings.extend(local.findings)
        result.suppressed.extend(local.suppressed)

    # package-scope rules (TL015's cross-module lock graph) see every
    # context at once; their findings are never cached — any file edit
    # can change the graph — but they reuse the cached per-file indices
    ctx_by_path = {c.display_path: c for c in contexts}
    for rule in package_rules:
        t0 = _time.perf_counter()
        raw = list(rule.check_package(contexts, registry))
        rule_times[rule.code] = (
            rule_times.get(rule.code, 0.0) + _time.perf_counter() - t0
        )
        for f in raw:
            ctx = ctx_by_path.get(f.path)
            if ctx is None:
                result.findings.append(f)
            else:
                _apply_suppressions(ctx, [f], unsuppressible, result)

    if baseline_fingerprints:
        new, old = split_baselined(result.findings, baseline_fingerprints)
        result.findings = new
        result.baselined = old

    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    result.rule_times = rule_times
    if cache is not None:
        result.cache = cache.stats_dict()
    return result


# ------------------------------------------------------------------ output


def _render_text(result: LintResult) -> str:
    out: List[str] = []
    for f in result.findings:
        out.append(f.render())
    summary = (
        f"tracelint: {len(result.findings)} finding(s) over "
        f"{result.files_checked} file(s)"
    )
    extras = []
    if result.warnings:
        extras.append(f"{len(result.warnings)} warning-tier")
    if result.suppressed:
        extras.append(f"{len(result.suppressed)} suppressed")
    if result.baselined:
        extras.append(f"{len(result.baselined)} baselined")
    if extras:
        summary += f" ({', '.join(extras)})"
    out.append(summary)
    return "\n".join(out)


def _gh_escape(text: str, is_property: bool = False) -> str:
    """GitHub Actions workflow-command escaping: % first (it is the escape
    introducer), then newlines; property values additionally escape the
    delimiters `:` and `,`."""
    out = text.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")
    if is_property:
        out = out.replace(":", "%3A").replace(",", "%2C")
    return out


def _render_github(result: LintResult) -> str:
    """One `::error` workflow command per finding — GitHub Actions renders
    them as inline annotations on the PR diff — plus the human summary
    line (not a command, so it lands in the raw log only)."""
    out: List[str] = []
    for f in result.findings:
        command = "error" if f.severity == "error" else "warning"
        out.append(
            f"::{command} file={_gh_escape(f.path, True)},"
            f"line={f.line},"
            f"title={_gh_escape(f'tracelint {f.rule}', True)}"
            f"::{_gh_escape(f.message)}"
        )
    out.append(
        f"tracelint: {len(result.findings)} finding(s) over "
        f"{result.files_checked} file(s)"
    )
    return "\n".join(out)


def _render_json(result: LintResult) -> str:
    payload = {
        "findings": [f.as_json() for f in result.findings],
        "suppressed": [
            {**f.as_json(), "reason": sup.reason}
            for f, sup in result.suppressed
        ],
        "baselined": [f.as_json() for f in result.baselined],
        "files_checked": result.files_checked,
        # per-rule wall time for work actually executed this run, so a
        # slow rule is visible instead of hiding in the total (cache
        # hits in --watch contribute nothing by design)
        "rule_times_ms": {
            code: round(t * 1000.0, 3)
            for code, t in sorted(result.rule_times.items())
        },
    }
    if result.cache is not None:
        payload["cache"] = result.cache
    return json.dumps(payload, indent=2)


RENDERERS = {
    "text": _render_text,
    "json": _render_json,
    "github": _render_github,
}


def exit_code(result: LintResult) -> int:
    """Severity bitmask (module docstring): errors set bit 0, warning-
    tier findings set bit 2 — bit 1 stays reserved for usage errors."""
    return (1 if result.errors else 0) | (4 if result.warnings else 0)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="dalle-tpu-lint",
        description=(
            "tracelint: JAX-aware static analysis for recompilation, "
            "donation, host-sync, and RNG-reuse hazards"
        ),
    )
    parser.add_argument(
        "paths", nargs="*", type=Path,
        help=f"files/dirs to lint (default: the installed package, {PACKAGE_DIR})",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "github"), default="text",
        help="github emits ::error workflow commands so CI review shows "
        "findings as inline annotations",
    )
    parser.add_argument(
        "--select", "--rules", dest="select", default=None,
        metavar="TLxxx[,TLxxx...]",
        help="run only these rule codes (--rules is an alias)",
    )
    parser.add_argument(
        "--exclude-rules", default=None, metavar="TLxxx[,TLxxx...]",
        help="run everything except these rule codes (CI granularity "
        "while a new rule beds in)",
    )
    parser.add_argument(
        "--watch", action="store_true",
        help="incremental watch mode: poll for file changes and re-lint "
        "on every edit, re-parsing only changed files; --format json "
        "emits one JSON document per event",
    )
    parser.add_argument(
        "--watch-poll", type=float, default=0.5, metavar="SECONDS",
        help="mtime poll interval for --watch (default 0.5s)",
    )
    parser.add_argument(
        "--changed", nargs="?", const="HEAD", default=None, metavar="REF",
        help="lint only python files changed vs REF (default HEAD) plus "
        "untracked ones — the pre-commit surface; exits 0 when nothing "
        "changed",
    )
    parser.add_argument(
        "--baseline", type=Path, default=None,
        help=f"baseline file (default: {DEFAULT_BASELINE} when linting the package)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore any baseline file (zero-baseline run)",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="grandfather current findings into the baseline file and exit 0",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule pack and exit"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.code} {rule.name}: {rule.description}")
        return 0

    paths = args.paths or [PACKAGE_DIR]
    if args.changed is not None:
        if args.paths:
            print(
                "tracelint: --changed and explicit paths don't compose",
                file=sys.stderr,
            )
            return 2
        try:
            paths = changed_python_files(args.changed)
        except RuntimeError as exc:
            print(f"tracelint: --changed: {exc}", file=sys.stderr)
            return 2
        if not paths:
            print(
                f"tracelint: no python files changed vs {args.changed}"
            )
            return 0
    known = {r.code for r in ALL_RULES} | {"TL000"}
    select = None
    if args.select:
        select = {c.strip() for c in args.select.split(",") if c.strip()}
        unknown = select - known
        if unknown:
            print(f"unknown rule code(s): {sorted(unknown)}", file=sys.stderr)
            return 2
    if args.exclude_rules:
        excluded = {
            c.strip() for c in args.exclude_rules.split(",") if c.strip()
        }
        unknown = excluded - known
        if unknown:
            print(f"unknown rule code(s): {sorted(unknown)}", file=sys.stderr)
            return 2
        select = (select if select is not None else known) - excluded

    baseline_path = args.baseline
    if baseline_path is None and not args.paths:
        baseline_path = DEFAULT_BASELINE  # package lint uses the shipped file

    fingerprints: Set[str] = set()
    if baseline_path is not None and not args.no_baseline and not args.write_baseline:
        fingerprints = load_baseline(baseline_path)

    if args.watch:
        if args.write_baseline:
            print(
                "tracelint: --watch and --write-baseline don't compose",
                file=sys.stderr,
            )
            return 2
        from dalle_pytorch_tpu.analysis.watch import watch_paths

        try:
            return watch_paths(
                paths,
                select=select,
                baseline_fingerprints=fingerprints,
                fmt=args.format,
                poll_s=args.watch_poll,
            )
        except FileNotFoundError as exc:
            print(f"tracelint: {exc}", file=sys.stderr)
            return 2
        except KeyboardInterrupt:
            return 0

    try:
        result = lint_paths(
            paths, select=select, baseline_fingerprints=fingerprints
        )
    except FileNotFoundError as exc:
        print(f"tracelint: {exc}", file=sys.stderr)
        return 2

    if args.write_baseline:
        if baseline_path is None:
            # explicit paths + no --baseline: refusing to guess would
            # silently overwrite the shipped package baseline with
            # fingerprints for unrelated files
            print(
                "tracelint: --write-baseline with explicit paths requires "
                "--baseline <file>",
                file=sys.stderr,
            )
            return 2
        write_baseline(baseline_path, result.findings)
        print(
            f"tracelint: wrote {len(result.findings)} fingerprint(s) "
            f"to {baseline_path}"
        )
        return 0

    renderer = RENDERERS[args.format]
    print(renderer(result))
    return exit_code(result)


if __name__ == "__main__":
    sys.exit(main())
