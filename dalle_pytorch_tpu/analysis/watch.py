"""Incremental lint: content-fingerprinted AST/finding cache + `--watch`.

The editor-integration story (ROADMAP "LSP-style watch mode"): a re-lint
after a one-file edit should cost one file's parse + rule work, not the
package's. Two cache layers, both keyed on a sha1 of the file's CONTENT
(mtime only decides when to poll, never what to trust):

* AST layer: an unchanged file reuses its parsed `FileContext` —
  including the memoized per-file indices (`_jax_index`,
  `_thread_index`) the rules hang off it — so only edited files are
  re-parsed. This is the layer the acceptance criterion pins.
* Finding layer: a file's per-file rule findings are reused when the
  file AND the cross-file facts per-file rules consume (the donation
  registry, plus the select set) are unchanged. Package-scope rules
  (TL015's lock graph) re-run every time by design — any edit anywhere
  can change the graph — but they reuse the cached per-file indices, so
  the re-run is cheap.

`watch_paths` drives the loop: poll mtimes, re-lint through one
persistent `LintCache` on any change, render each run with the normal
`--format` renderer (one JSON document per event under `--format json`).
"""

from __future__ import annotations

import hashlib
import sys
import time
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from dalle_pytorch_tpu.analysis.core import FileContext


class LintCache:
    """Content-fingerprint cache for incremental lint runs. One instance
    persists across `lint_paths` calls; counters reset per run so tests
    (and the `--format json` `cache` block) can pin exactly how much
    work a re-lint did."""

    def __init__(self):
        self._ast: Dict[str, Tuple[str, FileContext]] = {}
        self._findings: Dict[str, Tuple[str, str, list, list]] = {}
        # per-run counters (begin_run resets)
        self.files = 0
        self.reparsed = 0
        self.ast_hits = 0
        self.finding_hits = 0

    def begin_run(self) -> None:
        self.files = 0
        self.reparsed = 0
        self.ast_hits = 0
        self.finding_hits = 0

    # ------------------------------------------------------------ AST layer

    def context_for(
        self, path: Path, display: str, stable: str
    ) -> FileContext:
        """The parsed context for `path`, reusing the cached parse when
        the content fingerprint matches. Raises like FileContext on
        unreadable/unparseable files (the driver maps that to TL000)."""
        self.files += 1
        key = str(path.resolve())
        source = path.read_text(encoding="utf-8")
        digest = hashlib.sha1(source.encode()).hexdigest()
        hit = self._ast.get(key)
        if hit is not None and hit[0] == digest:
            self.ast_hits += 1
            return hit[1]
        self.reparsed += 1
        self._findings.pop(key, None)  # stale by definition
        ctx = FileContext(path, display, source, stable)
        ctx._content_digest = digest
        self._ast[key] = (digest, ctx)
        return ctx

    # -------------------------------------------------------- finding layer

    @staticmethod
    def cross_file_key(registry, select: Optional[Set[str]]) -> str:
        """Digest of every cross-file fact a per-file rule can read: the
        donation registry (TL003) and the rule selection. A change
        anywhere in these invalidates every file's cached findings; an
        edit that leaves them unchanged (the common case) keeps the
        other files' findings warm."""
        h = hashlib.sha1()
        for name in sorted(registry.donors):
            h.update(f"d:{name}:{sorted(registry.donors[name])};".encode())
        for name in sorted(registry.builders):
            h.update(f"b:{name}:{sorted(registry.builders[name])};".encode())
        h.update(f"s:{sorted(select) if select is not None else '*'}".encode())
        return h.hexdigest()

    def findings_for(self, ctx: FileContext, xkey: str):
        key = str(ctx.path.resolve())
        digest = getattr(ctx, "_content_digest", None)
        hit = self._findings.get(key)
        if hit is not None and digest is not None and hit[0] == digest \
                and hit[1] == xkey:
            self.finding_hits += 1
            return list(hit[2]), list(hit[3])
        return None

    def store_findings(self, ctx, xkey, findings, suppressed) -> None:
        digest = getattr(ctx, "_content_digest", None)
        if digest is None:
            return
        key = str(ctx.path.resolve())
        self._findings[key] = (digest, xkey, list(findings), list(suppressed))

    def stats_dict(self) -> dict:
        return {
            "files": self.files,
            "reparsed": self.reparsed,
            "ast_hits": self.ast_hits,
            "finding_hits": self.finding_hits,
        }


def _snapshot(paths: Sequence[Path]) -> Dict[str, Tuple[float, int]]:
    """path -> (mtime, size) over the current expansion of `paths` —
    re-expanded every poll so created/deleted files register as changes."""
    from dalle_pytorch_tpu.analysis.lint import iter_python_files

    snap: Dict[str, Tuple[float, int]] = {}
    for path, _stable in iter_python_files([Path(p) for p in paths]):
        try:
            st = path.stat()
        except OSError:
            continue
        snap[str(path.resolve())] = (st.st_mtime, st.st_size)
    return snap


def watch_paths(
    paths: Sequence[Path],
    select: Optional[Set[str]] = None,
    baseline_fingerprints: Optional[Set[str]] = None,
    fmt: str = "text",
    poll_s: float = 0.5,
    max_events: Optional[int] = None,
    stream=None,
    sleep_fn: Callable[[float], None] = time.sleep,
) -> int:
    """Lint once, then re-lint on every observed mtime change until
    interrupted (or `max_events` lint runs, for tests/embedders). The
    return value is the LAST run's severity bitmask, so a bounded watch
    is scriptable. `sleep_fn` is the poll-wait seam — tests inject a
    function that edits files instead of sleeping."""
    from dalle_pytorch_tpu.analysis.lint import RENDERERS, exit_code, lint_paths

    stream = stream if stream is not None else sys.stdout
    render = RENDERERS[fmt]
    cache = LintCache()
    rc = 0
    events = 0
    snap = _snapshot(paths)
    while True:
        result = lint_paths(
            paths,
            select=select,
            baseline_fingerprints=baseline_fingerprints,
            cache=cache,
        )
        rc = exit_code(result)
        print(render(result), file=stream, flush=True)
        events += 1
        if max_events is not None and events >= max_events:
            return rc
        while True:
            sleep_fn(poll_s)
            fresh = _snapshot(paths)
            if fresh != snap:
                snap = fresh
                break
