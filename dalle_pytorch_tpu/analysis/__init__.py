"""tracelint: JAX-aware static analysis for this package.

Catches the hazard classes the serving/training stack's performance story
depends on keeping out — recompilation (TL001), hidden host syncs (TL002),
donated-buffer reuse (TL003), PRNG key reuse (TL004), dtype drift (TL005),
debugger artifacts (TL006), scan-body host-constant captures (TL007),
mesh-axis typos (TL008), span leaks (TL009), serving retry/warmup/
snapshot discipline (TL010-TL012), and the thread-model concurrency
rules over the serving fleet (TL013 unguarded shared state, TL014
iterate-while-mutated, TL015 lock-order inversion, TL016
blocking-under-lock; `analysis/threadctx.py` is the index underneath)
— before they ship. Run it with

    python -m dalle_pytorch_tpu.analysis        # or: dalle-tpu-lint

See analysis/README.md for the suppression syntax, the baseline workflow,
and a guide to writing a rule.
"""

from dalle_pytorch_tpu.analysis.core import FileContext, Finding, LintResult, Rule
from dalle_pytorch_tpu.analysis.lint import PACKAGE_DIR, lint_paths, main
from dalle_pytorch_tpu.analysis.rules import ALL_RULES

__all__ = [
    "ALL_RULES",
    "FileContext",
    "Finding",
    "LintResult",
    "PACKAGE_DIR",
    "Rule",
    "lint_paths",
    "main",
]
