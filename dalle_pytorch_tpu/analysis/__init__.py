"""tracelint: JAX-aware static analysis for this package.

Catches the hazard classes the serving/training stack's performance story
depends on keeping out — recompilation (TL001), hidden host syncs (TL002),
donated-buffer reuse (TL003), PRNG key reuse (TL004), dtype drift (TL005),
debugger artifacts (TL006), and scan-body host-constant captures (TL007)
— before they ship. Run it with

    python -m dalle_pytorch_tpu.analysis        # or: dalle-tpu-lint

See analysis/README.md for the suppression syntax, the baseline workflow,
and a guide to writing a rule.
"""

from dalle_pytorch_tpu.analysis.core import FileContext, Finding, LintResult, Rule
from dalle_pytorch_tpu.analysis.lint import PACKAGE_DIR, lint_paths, main
from dalle_pytorch_tpu.analysis.rules import ALL_RULES

__all__ = [
    "ALL_RULES",
    "FileContext",
    "Finding",
    "LintResult",
    "PACKAGE_DIR",
    "Rule",
    "lint_paths",
    "main",
]
