"""`python -m dalle_pytorch_tpu.analysis` entry point."""

import sys

from dalle_pytorch_tpu.analysis.lint import main

sys.exit(main())
