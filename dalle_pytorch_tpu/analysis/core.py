"""tracelint core: findings, suppressions, per-file context, rule registry.

The framework is deliberately stdlib-only (ast + re + dataclasses): the
linter must be runnable in CI images and pre-commit hooks without paying a
jax import, and must never execute the code it analyzes (the reference
codebase's import-time-breakpoint regression, SURVEY.md §0, is exactly what
happens when checking requires importing).

Vocabulary
----------
Finding      one diagnosed hazard at a (path, line), carrying a rule code.
Suppression  `# tracelint: disable=TL001[,TL002] -- <reason>` on the
             offending line, or alone on the line directly above it. The
             reason is MANDATORY: a suppression without one is itself a
             finding (TL000) so silent opt-outs cannot accumulate.
Hot loop     `# tracelint: hotloop` on (or directly above) a `def` marks a
             host-side function as latency-critical: TL002 then treats any
             device->host sync inside it as a finding needing justification.
Threads      `# tracelint: threads` on (or directly above) a `class` marks
             it as concurrently shared (its public methods are entered
             from many threads at once — the ThreadingHTTPServer handler
             fan-in); the thread-model rules (TL013+) then treat each
             public method as its own concurrent root.
"""

from __future__ import annotations

import ast
import hashlib
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterator, List, Optional, Tuple

#: the rule code reserved for framework-level diagnoses (malformed
#: suppressions); real rules use TL001..TL999.
FRAMEWORK_CODE = "TL000"

_SUPPRESS_RE = re.compile(
    r"#\s*tracelint:\s*disable=(?P<codes>TL\d{3}(?:\s*,\s*TL\d{3})*)"
    r"(?:\s*--\s*(?P<reason>\S.*))?"
)
_HOTLOOP_RE = re.compile(r"#\s*tracelint:\s*hotloop\b")
_THREADS_RE = re.compile(r"#\s*tracelint:\s*threads\b")


@dataclass(frozen=True)
class Finding:
    rule: str  # e.g. "TL001"
    path: str  # display path (cwd-relative when possible; for humans)
    line: int  # 1-indexed
    message: str
    snippet: str = ""
    #: invocation-independent path (relative to the lint root the file was
    #: found under) — fingerprints use THIS, so a baseline written from one
    #: directory still matches when the linter runs from another
    stable_path: str = ""
    #: "error" (always a bug: sync under tracing, donated reuse, ...) or
    #: "warning" (needs justification: sync in a `# tracelint: hotloop`
    #: loop). Severity is presentation + exit-code tier only — it is NOT
    #: part of the fingerprint, so retiering a rule never invalidates a
    #: baseline.
    severity: str = "error"

    def fingerprint(self) -> str:
        """Stable identity for baselining: rule + root-relative path +
        normalized source line. No line number (edits above a grandfathered
        finding don't resurrect it), no cwd dependence (the burn-down
        workflow survives CI invoking from a different directory)."""
        norm = " ".join(self.snippet.split())
        raw = f"{self.rule}|{self.stable_path or self.path}|{norm}".encode()
        return hashlib.sha1(raw).hexdigest()[:16]

    def render(self) -> str:
        sev = "" if self.severity == "error" else f" {self.severity}:"
        out = f"{self.path}:{self.line}: {self.rule}{sev} {self.message}"
        if self.snippet:
            out += f"\n    {self.snippet.strip()}"
        return out

    def as_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "severity": self.severity,
            "message": self.message,
            "snippet": self.snippet.strip(),
            "fingerprint": self.fingerprint(),
        }


@dataclass
class Suppression:
    line: int  # line the comment sits on
    codes: Tuple[str, ...]
    reason: Optional[str]
    standalone: bool  # comment-only line: covers the NEXT line instead

    @property
    def covered_line(self) -> int:
        return self.line + 1 if self.standalone else self.line


class FileContext:
    """Parsed view of one source file shared by every rule.

    Parsing happens once here; rules receive the AST plus the suppression
    and hot-loop maps, and must not re-read the file.
    """

    def __init__(
        self,
        path: Path,
        display_path: str,
        source: str,
        stable_path: str = "",
    ):
        self.path = path
        self.display_path = display_path
        self.stable_path = stable_path or display_path
        self.source = source
        self.lines: List[str] = source.splitlines()
        self.tree: ast.Module = ast.parse(source, filename=str(path))
        self.suppressions: List[Suppression] = []
        self.hotloop_lines: set = set()  # lines carrying a hotloop marker
        #: lines carrying `# tracelint: threads` — marks a CLASS whose
        #: public methods are called from many threads at once (HTTP
        #: handler fan-in) so the thread-model rules treat each public
        #: method as its own concurrent root (analysis/threadctx.py)
        self.thread_marker_lines: set = set()
        self._scan_comments()

    def _scan_comments(self) -> None:
        # real COMMENT tokens only — a docstring describing the suppression
        # syntax must not register as a suppression
        import io
        import tokenize

        try:
            tokens = list(tokenize.generate_tokens(io.StringIO(self.source).readline))
        except (tokenize.TokenError, IndentationError):
            return  # the AST parsed, so this is unreachable in practice
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            i = tok.start[0]
            m = _SUPPRESS_RE.search(tok.string)
            if m:
                codes = tuple(
                    c.strip() for c in m.group("codes").split(",")
                )
                standalone = tok.line[: tok.start[1]].strip() == ""
                self.suppressions.append(
                    Suppression(i, codes, m.group("reason"), standalone)
                )
            if _HOTLOOP_RE.search(tok.string):
                self.hotloop_lines.add(i)
            if _THREADS_RE.search(tok.string):
                self.thread_marker_lines.add(i)

    # ------------------------------------------------------------- helpers

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""

    def finding(
        self, rule: str, node: ast.AST, message: str,
        severity: str = "error",
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=rule,
            path=self.display_path,
            line=line,
            message=message,
            snippet=self.snippet(line),
            stable_path=self.stable_path,
            severity=severity,
        )

    def is_hotloop(self, func: ast.AST) -> bool:
        """True if `func`'s def line (or the line above it / above its first
        decorator) carries a `# tracelint: hotloop` marker."""
        line = getattr(func, "lineno", None)
        if line is None:
            return False
        candidates = {line, line - 1}
        for dec in getattr(func, "decorator_list", []):
            candidates.add(dec.lineno - 1)
        return bool(candidates & self.hotloop_lines)

    def suppressed(self, finding: Finding) -> Optional[Suppression]:
        """The suppression covering `finding`, or None. Suppressions without
        a reason never suppress — they surface as TL000 instead."""
        for sup in self.suppressions:
            if sup.covered_line != finding.line:
                continue
            if finding.rule in sup.codes and sup.reason:
                return sup
        return None

    def malformed_suppressions(self) -> Iterator[Finding]:
        for sup in self.suppressions:
            if not sup.reason:
                yield Finding(
                    rule=FRAMEWORK_CODE,
                    path=self.display_path,
                    line=sup.line,
                    message=(
                        "suppression without a reason; write "
                        "'# tracelint: disable=TLxxx -- <why this is safe>'"
                    ),
                    snippet=self.snippet(sup.line),
                    stable_path=self.stable_path,
                )


class Rule:
    """Base class for tracelint rules.

    Subclasses set `code`/`name`/`description` and implement `check`,
    yielding findings via `ctx.finding(self.code, node, message)`. Rules
    must be pure functions of the FileContext (+ the package-wide
    `DonationRegistry` passed by the driver): no filesystem access, no
    imports of the analyzed code. See analysis/README.md for a worked
    example of adding one.
    """

    code: str = ""
    name: str = ""
    description: str = ""
    #: False makes the rule's findings immune to inline suppressions —
    #: for gates with no legitimate exception (TL006: a debugger artifact
    #: is never justified in shipped code; the old regex scan it replaced
    #: had no opt-out either, and neither does this)
    suppressible: bool = True
    #: True for rules whose unit of analysis is the whole lint run, not
    #: one file (TL015's lock-acquisition graph spans modules): the
    #: driver calls `check_package(contexts, package)` once instead of
    #: `check(ctx, package)` per file. Findings still anchor to a
    #: (path, line) so suppressions and baselines work unchanged.
    package_scope: bool = False

    def check(self, ctx: FileContext, package) -> Iterator[Finding]:
        raise NotImplementedError

    def check_package(self, contexts, package) -> Iterator[Finding]:
        raise NotImplementedError


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)
    suppressed: List[Tuple[Finding, Suppression]] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    files_checked: int = 0
    #: per-rule wall time (seconds) actually spent executing rule checks
    #: this run — cache hits contribute nothing, so a slow rule is
    #: visible in `--format json` instead of hiding in the total
    rule_times: dict = field(default_factory=dict)
    #: incremental-cache counters for this run (None outside --watch /
    #: cached runs): files, reparsed, ast_hits, finding_hits
    cache: Optional[dict] = None

    @property
    def clean(self) -> bool:
        """No findings of ANY severity: warnings still need an inline
        justification before the package gate goes green."""
        return not self.findings

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "warning"]
