"""Baseline handling: grandfather existing findings, fail only on NEW ones.

The baseline is a checked-in JSON file of finding fingerprints
(rule + path + normalized source line — line-number free, so edits above a
grandfathered finding don't resurrect it). The shipped baseline is EMPTY
(`analysis/baseline.json`): every hazard in the package is either fixed or
carries an inline suppression with a reason. The file exists so the
workflow generalizes — a repo adopting a new rule over a large surface can
`--write-baseline` first and burn findings down over time without turning
the linter off.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Set

from dalle_pytorch_tpu.analysis.core import Finding

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"

_FORMAT_VERSION = 1


def load_baseline(path: Path) -> Set[str]:
    """Fingerprints from `path`; empty set if the file doesn't exist."""
    if not path.exists():
        return set()
    data = json.loads(path.read_text(encoding="utf-8"))
    assert data.get("version") == _FORMAT_VERSION, (
        f"baseline {path} has version {data.get('version')!r}; "
        f"this linter reads version {_FORMAT_VERSION}"
    )
    return set(data.get("fingerprints", []))


def occurrence_fingerprints(findings: List[Finding]):
    """[(finding, fingerprint)] where duplicate (rule, path, snippet)
    findings get an occurrence suffix (`abc123:1`, `:2`, ...) in line
    order — so a NEW copy of an already-grandfathered line is still a new
    finding, while pure line drift of existing ones stays matched."""
    counts: Dict[str, int] = {}
    out = []
    for f in sorted(
        findings, key=lambda f: (f.stable_path or f.path, f.line, f.rule)
    ):
        base = f.fingerprint()
        k = counts.get(base, 0)
        counts[base] = k + 1
        out.append((f, base if k == 0 else f"{base}:{k}"))
    return out


def write_baseline(path: Path, findings: List[Finding]) -> None:
    """Persist `findings` as the new grandfathered set (sorted for stable
    diffs; `entries` is a human-readable mirror of the fingerprints)."""
    entries = sorted(
        (
            {
                "fingerprint": fp,
                "rule": f.rule,
                "path": f.stable_path or f.path,
                "snippet": f.snippet.strip(),
            }
            for f, fp in occurrence_fingerprints(findings)
        ),
        key=lambda e: (e["path"], e["rule"], e["fingerprint"]),
    )
    payload = {
        "version": _FORMAT_VERSION,
        "fingerprints": [e["fingerprint"] for e in entries],
        "entries": entries,
    }
    path.write_text(json.dumps(payload, indent=2) + "\n", encoding="utf-8")


def split_baselined(findings: List[Finding], fingerprints: Set[str]):
    """(new, grandfathered) partition of `findings`, occurrence-aware."""
    new: List[Finding] = []
    old: List[Finding] = []
    for f, fp in occurrence_fingerprints(findings):
        (old if fp in fingerprints else new).append(f)
    return new, old
