"""Sharding-awareness for tracelint: where data LIVES — which mesh a
program runs over, which spec each placed value carries, which jit
programs pin which shardings, and which host functions sit on the
latency-critical (`# tracelint: hotloop`) frontier.

`jaxctx.py` answers "does this run under tracing"; this module answers
the orthogonal question the mesh-sharded serving stack depends on:
"under WHICH sharding". Everything is a HEURISTIC over the AST, per-file
plus one hop, with the pack's usual false-negative bias — when a mesh, a
spec, or a program cannot be resolved, the consumer rules stay silent.

Resolved constructs (the idioms this codebase actually uses):

  mesh constructions
    * literal `Mesh(devs, ("a", "b"))` / `Mesh(..., axis_names=(...))`
    * the repo's factories: `make_mesh` / `build_serving_mesh` (the
      4-axis dp/fsdp/tp/sp vocabulary) and `make_pp_mesh` (("pp",)) —
      the same table TL008 resolves against (the vocabulary constants
      live HERE; rules.py re-exports them for the lockstep test)
    * `self.mesh = build_serving_mesh(...)`-style attribute binds

  placements (symbol -> SpecRef)
    * `x = jax.device_put(v, NamedSharding(mesh, P("tp")))` — literal
    * `x = jax.device_put(v, self._state_shardings)` — symbolic
    * `s = NamedSharding(mesh, P(...))` spec handles, reused by name
    * `self.attr = ...` forms of all of the above (class-level registry)

  program summaries (one per `jax.jit`/`pjit`/`shard_map` call)
    * donated positional indices (jaxctx `_donate_from_jit_call`)
    * `in_shardings`/`out_shardings` (jit) and `in_specs`/`out_specs`
      (shard_map) parsed to SpecRefs — positionally when a tuple/list
      literal, broadcast when a single expression
    * mesh identity: the normalized mesh expression (`self.mesh`,
      `mesh`) read off the first NamedSharding/shard_map mesh operand
    * the registration name when the call sits inside this repo's
      `*._sharded_program("name", ...)` pinned-program cache idiom, else
      the name it is assigned to, else the wrapped callable's name
    * ONE-HOP propagation: a def whose body just returns a summarized
      program applied to its own parameters in positional order exports
      that summary under its own name — call sites in other files see
      through the wrapper, mirroring the jaxctx frontier (one hop, no
      fixpoint)

  hot frontier
    * functions marked `# tracelint: hotloop`, plus (one hop) same-file
      defs whose EVERY call site sits inside a marked function — the
      "hotloop-reachable path" TL019/TL021 police

SpecRef comparison semantics (`specs_differ`) are deliberately
three-valued: two literal specs compare by value (trailing-None
normalized, the jax equivalence), two identical symbols compare equal,
and every mixed or unresolved pairing is UNKNOWN — consumer rules treat
UNKNOWN as clean. A lint must earn trust before it earns strictness.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from dalle_pytorch_tpu.analysis.jaxctx import (
    FunctionNode,
    _assign_targets,
    _donate_from_jit_call,
    dotted_name,
    terminal_name,
)

_ALL_FUNCS = FunctionNode + (ast.Lambda,)
_JIT_NAMES = {"jit", "pjit"}

#: the 4-axis `make_mesh` vocabulary (parallel/mesh.py MESH_AXES) — kept
#: in lockstep by tests/test_analysis.py; re-declared here because the
#: linter must never pay a jax import (analysis/core.py docstring)
_MAKE_MESH_AXES = ("dp", "fsdp", "tp", "sp")
#: known mesh factories -> the axis vocabulary of the mesh they build
_MESH_FACTORY_AXES = {
    "make_mesh": _MAKE_MESH_AXES,
    "build_serving_mesh": _MAKE_MESH_AXES,
    "make_pp_mesh": ("pp",),
}


def walk_shallow(func: ast.AST) -> Iterator[ast.AST]:
    """Pre-order walk of a function body WITHOUT descending into nested
    function defs (they get their own analysis pass)."""

    def rec(node: ast.AST) -> Iterator[ast.AST]:
        for child in ast.iter_child_nodes(node):
            yield child
            if not isinstance(child, _ALL_FUNCS):
                yield from rec(child)

    return rec(func)


# ----------------------------------------------------------------- SpecRef


@dataclass(frozen=True)
class SpecRef:
    """A resolved-or-symbolic sharding reference.

    kind "literal": `axes` holds the PartitionSpec entries — per-dim
    axis name (str), None, or a tuple of axis names — with trailing
    Nones stripped (jax's `P("tp")` == `P("tp", None)` equivalence).
    kind "symbol": `symbol` holds the normalized source expression
    (`self._state_shardings`) — equal symbols are the SAME handle, so
    comparisons against an identical symbol resolve; everything else
    about a symbol is opaque.
    """

    kind: str  # "literal" | "symbol"
    axes: Tuple = ()
    symbol: str = ""

    @property
    def replicated(self) -> bool:
        return self.kind == "literal" and not self.named_axes()

    def named_axes(self) -> Set[str]:
        out: Set[str] = set()
        for entry in self.axes:
            if isinstance(entry, str):
                out.add(entry)
            elif isinstance(entry, tuple):
                out.update(entry)
        return out

    def render(self) -> str:
        if self.kind == "symbol":
            return self.symbol
        inner = ", ".join(
            repr(e) if not isinstance(e, tuple) else repr(tuple(e))
            for e in self.axes
        )
        return f"P({inner})"


def _spec_entries(call: ast.Call) -> Optional[Tuple]:
    """`P("tp")` / `PartitionSpec(None, ("dp", "fsdp"))` -> entry tuple,
    or None when any entry is not a literal."""
    entries: List = []
    if call.keywords:
        return None
    for arg in call.args:
        if isinstance(arg, ast.Constant) and (
            arg.value is None or isinstance(arg.value, str)
        ):
            entries.append(arg.value)
        elif isinstance(arg, (ast.Tuple, ast.List)) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in arg.elts
        ):
            entries.append(tuple(e.value for e in arg.elts))
        else:
            return None
    while entries and entries[-1] is None:
        entries.pop()
    return tuple(entries)


def spec_ref_of(expr: Optional[ast.AST]) -> Optional[SpecRef]:
    """Best-effort SpecRef for an expression standing where a sharding
    (or a bare PartitionSpec) is expected. None = unresolvable."""
    if expr is None:
        return None
    if isinstance(expr, ast.Call):
        fname = terminal_name(expr.func)
        if fname in ("P", "PartitionSpec"):
            entries = _spec_entries(expr)
            return None if entries is None else SpecRef("literal", entries)
        if fname == "NamedSharding":
            spec_expr = (
                expr.args[1]
                if len(expr.args) >= 2
                else next(
                    (kw.value for kw in expr.keywords if kw.arg == "spec"),
                    None,
                )
            )
            return spec_ref_of(spec_expr)
        if fname == "_replicated_sharding" and not expr.args:
            # the mixin's NamedSharding(self.mesh, P()) helper
            return SpecRef("literal", ())
        return None
    dotted = dotted_name(expr)
    if dotted is not None:
        return SpecRef("symbol", symbol=dotted)
    return None


def specs_differ(a: Optional[SpecRef], b: Optional[SpecRef]) -> Optional[bool]:
    """Three-valued spec comparison: True = provably different, False =
    provably the same placement, None = unknown (consumers stay silent)."""
    if a is None or b is None:
        return None
    if a.kind == "literal" and b.kind == "literal":
        return a.axes != b.axes
    if a.kind == "symbol" and b.kind == "symbol":
        # identical handles are the same placement; DIFFERENT symbols may
        # still alias the same shardings — unknown, not a finding
        return False if a.symbol == b.symbol else None
    return None


def mesh_expr_name(expr: Optional[ast.AST]) -> Optional[str]:
    """Normalized identity of a mesh operand (`self.mesh`, `mesh`)."""
    if expr is None:
        return None
    return dotted_name(expr)


# ----------------------------------------------------------- mesh resolve


def literal_mesh_axes(call: ast.Call) -> Optional[Set[str]]:
    """Axis vocabulary of a mesh-constructing call: a literal
    `Mesh(devs, ("a", "b"))` / `Mesh(..., axis_names=(...))`, or one of
    the repo's known factories. None = unresolvable (silent)."""
    fname = terminal_name(call.func)
    if fname in _MESH_FACTORY_AXES:
        return set(_MESH_FACTORY_AXES[fname])
    if fname != "Mesh":
        return None
    cands = []
    if len(call.args) >= 2:
        cands.append(call.args[1])
    cands.extend(kw.value for kw in call.keywords if kw.arg == "axis_names")
    for cand in cands:
        if isinstance(cand, (ast.Tuple, ast.List)) and cand.elts and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in cand.elts
        ):
            return {e.value for e in cand.elts}
    return None


def mesh_axis_bindings(tree: ast.Module) -> Dict[str, Set[str]]:
    """symbol (`mesh`, `self.mesh`) -> union of axis vocabularies it was
    ever bound to (a name rebound to different meshes unions rather than
    guesses — conservative toward silence)."""
    axes_of: Dict[str, Set[str]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or not isinstance(
            node.value, ast.Call
        ):
            continue
        axes = literal_mesh_axes(node.value)
        if axes is None:
            continue
        for t in node.targets:
            for n in _assign_targets(t):
                axes_of.setdefault(n.id, set()).update(axes)
            dotted = dotted_name(t)
            if dotted is not None and "." in dotted:
                axes_of.setdefault(dotted, set()).update(axes)
    return axes_of


# ------------------------------------------------------- program summaries


def _sharding_list(expr: Optional[ast.AST]):
    """An `in_shardings=`/`out_shardings=`/`in_specs=` operand -> either
    a tuple of per-position Optional[SpecRef] (tuple/list literal) or a
    single broadcast Optional[SpecRef]."""
    if expr is None:
        return None
    if isinstance(expr, (ast.Tuple, ast.List)):
        return tuple(spec_ref_of(e) for e in expr.elts)
    return spec_ref_of(expr)


def _first_mesh_operand(call: ast.Call) -> Optional[str]:
    """Mesh identity of a jit/shard_map call: the `mesh=` kwarg
    (shard_map) or the mesh operand of the first NamedSharding among its
    sharding kwargs."""
    for kw in call.keywords:
        if kw.arg == "mesh":
            return mesh_expr_name(kw.value)
    for kw in call.keywords:
        if kw.arg not in ("in_shardings", "out_shardings"):
            continue
        for node in ast.walk(kw.value):
            if isinstance(node, ast.Call) and terminal_name(
                node.func
            ) == "NamedSharding":
                mesh = (
                    node.args[0]
                    if node.args
                    else next(
                        (k.value for k in node.keywords if k.arg == "mesh"),
                        None,
                    )
                )
                name = mesh_expr_name(mesh)
                if name is not None:
                    return name
    return None


@dataclass
class ProgramSummary:
    """One jitted (or shard_map-wrapped) program's sharding contract."""

    name: str
    node: ast.Call  # the jit/pjit/shard_map call
    kind: str  # "jit" | "shard_map"
    donated: Tuple[int, ...] = ()
    #: tuple of per-position Optional[SpecRef], a single broadcast
    #: SpecRef, or None when the kwarg is absent
    in_shardings: object = None
    out_shardings: object = None
    has_in: bool = False
    has_out: bool = False
    mesh: Optional[str] = None
    #: registered through `*._sharded_program("name", ...)` — the
    #: serving engines' pinned-program cache, i.e. a LADDER program
    registered: bool = False

    def in_spec_at(self, pos: int) -> Optional[SpecRef]:
        if isinstance(self.in_shardings, tuple):
            if 0 <= pos < len(self.in_shardings):
                return self.in_shardings[pos]
            return None
        return self.in_shardings  # broadcast or None

    def out_spec_candidates(self) -> Optional[List[SpecRef]]:
        """The resolvable output placements (flattened one level). None
        when out_shardings is absent or nothing resolved."""
        if not self.has_out:
            return None
        refs = (
            list(self.out_shardings)
            if isinstance(self.out_shardings, tuple)
            else [self.out_shardings]
        )
        resolved = [r for r in refs if r is not None]
        return resolved or None


class ShardIndex:
    """Per-file sharding index, built once and memoized on the
    FileContext (`_shard_index`, mirroring `_jax_index`)."""

    def __init__(self, ctx):
        self.ctx = ctx
        tree = ctx.tree
        self.mesh_axes: Dict[str, Set[str]] = mesh_axis_bindings(tree)
        #: symbol -> SpecRef of the sharding it was placed under /
        #: bound to: `x = jax.device_put(v, S)`, `s = NamedSharding(...)`,
        #: and the `self.attr = ...` forms
        self.placements: Dict[str, SpecRef] = {}
        self.programs: List[ProgramSummary] = []
        #: name -> summary (first binding wins; rebinding a program name
        #: to a second program would make lookups guesses)
        self.by_name: Dict[str, ProgramSummary] = {}
        #: hot frontier: `# tracelint: hotloop`-marked defs plus one-hop
        #: same-file defs called ONLY from marked defs
        self.hot: List[ast.AST] = []
        self._collect_placements(tree)
        self._collect_programs(tree)
        self._propagate_wrappers(tree)
        self._collect_hot(tree)

    # ------------------------------------------------------------ builders

    @staticmethod
    def _placement_ref(value: ast.AST) -> Optional[SpecRef]:
        if not isinstance(value, ast.Call):
            return None
        fname = terminal_name(value.func)
        if fname == "device_put":
            sharding = (
                value.args[1]
                if len(value.args) >= 2
                else next(
                    (
                        kw.value
                        for kw in value.keywords
                        if kw.arg in ("device", "sharding")
                    ),
                    None,
                )
            )
            return spec_ref_of(sharding)
        if fname == "NamedSharding":
            return spec_ref_of(value)
        return None

    def _collect_placements(self, tree: ast.Module) -> None:
        """File-level registry: dotted symbols (`self._cache`) from
        anywhere, plain names from module level only — a plain local in
        one function must not leak a placement into another function's
        analysis."""
        module_level = set(id(s) for s in tree.body)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            ref = self._placement_ref(node.value)
            if ref is None:
                continue
            for t in node.targets:
                dotted = dotted_name(t)
                if dotted is None:
                    continue
                if "." in dotted or id(node) in module_level:
                    self.placements[dotted] = ref

    def _collect_programs(self, tree: ast.Module) -> None:
        """Recursive visit carrying the enclosing `_sharded_program`
        registration name and assignment target, so each jit/shard_map
        call lands in a summary under its best available name."""

        def reg_name(call: ast.Call) -> Optional[str]:
            if (
                isinstance(call.func, ast.Attribute)
                and call.func.attr == "_sharded_program"
                and call.args
                and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, str)
            ):
                return call.args[0].value
            return None

        def visit(node: ast.AST, registrar: Optional[str],
                  assigned: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                child_reg, child_asn = registrar, assigned
                if isinstance(child, ast.Assign):
                    names = [
                        dotted_name(t)
                        for t in child.targets
                        if dotted_name(t) is not None
                    ]
                    child_asn = names[0] if names else None
                if isinstance(child, ast.Call):
                    name = reg_name(child)
                    if name is not None:
                        child_reg = name
                    fname = terminal_name(child.func)
                    if fname in _JIT_NAMES:
                        self._summarize(child, "jit", child_reg, child_asn)
                    elif fname == "shard_map":
                        self._summarize(child, "shard_map", child_reg,
                                        child_asn)
                visit(child, child_reg, child_asn)

        visit(tree, None, None)

    def _summarize(self, call: ast.Call, kind: str,
                   registrar: Optional[str], assigned: Optional[str]) -> None:
        wrapped = call.args[0] if call.args else None
        func = wrapped if isinstance(wrapped, _ALL_FUNCS) else None
        name = (
            registrar
            or assigned
            or (terminal_name(wrapped) if wrapped is not None else None)
            or "<anonymous>"
        )
        if kind == "jit":
            in_kw = next(
                (kw.value for kw in call.keywords
                 if kw.arg == "in_shardings"), None
            )
            out_kw = next(
                (kw.value for kw in call.keywords
                 if kw.arg == "out_shardings"), None
            )
            donated = _donate_from_jit_call(call, func)
        else:
            in_kw = next(
                (kw.value for kw in call.keywords if kw.arg == "in_specs"),
                None,
            )
            out_kw = next(
                (kw.value for kw in call.keywords if kw.arg == "out_specs"),
                None,
            )
            donated = ()
        summary = ProgramSummary(
            name=name,
            node=call,
            kind=kind,
            donated=tuple(donated),
            in_shardings=_sharding_list(in_kw),
            out_shardings=_sharding_list(out_kw),
            has_in=in_kw is not None,
            has_out=out_kw is not None,
            mesh=_first_mesh_operand(call),
            registered=registrar is not None,
        )
        self.programs.append(summary)
        if name != "<anonymous>" and name not in self.by_name:
            self.by_name[name] = summary

    def _propagate_wrappers(self, tree: ast.Module) -> None:
        """One-hop summary propagation: `def f(a, b): return prog(a, b)`
        exports prog's summary under f's name — call sites (in this or
        other files, via the package union) see through the wrapper.
        Positional-identity only: a wrapper that reorders or wraps its
        arguments would shift every spec position, so it stays opaque."""
        for node in ast.walk(tree):
            if not isinstance(node, FunctionNode):
                continue
            if node.name in self.by_name:
                continue
            body = [
                s for s in node.body
                if not isinstance(s, ast.Expr)
                or not isinstance(s.value, ast.Constant)
            ]
            if len(body) != 1 or not isinstance(body[0], ast.Return):
                continue
            ret = body[0].value
            if not isinstance(ret, ast.Call) or ret.keywords:
                continue
            callee = terminal_name(ret.func)
            summary = self.by_name.get(callee or "")
            if summary is None:
                continue
            params = [
                p.arg
                for p in node.args.posonlyargs + node.args.args
                if p.arg != "self"
            ]
            passed = [
                a.id if isinstance(a, ast.Name) else None for a in ret.args
            ]
            if passed and passed == params[: len(passed)]:
                self.by_name[node.name] = summary

    def _collect_hot(self, tree: ast.Module) -> None:
        marked = [
            f
            for f in ast.walk(tree)
            if isinstance(f, FunctionNode) and self.ctx.is_hotloop(f)
        ]
        self.hot = list(marked)
        if not marked:
            return
        # one hop: a same-file def whose EVERY call site is inside a
        # marked function is hotloop-reachable itself (no fixpoint —
        # mirrors the jaxctx frontier depth argument)
        defs: Dict[str, ast.AST] = {
            f.name: f for f in ast.walk(tree) if isinstance(f, FunctionNode)
        }
        enclosing: Dict[int, Optional[ast.AST]] = {}

        def visit(node: ast.AST, owner: Optional[ast.AST]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.Call):
                    enclosing[id(child)] = owner
                visit(
                    child,
                    child if isinstance(child, _ALL_FUNCS) else owner,
                )

        visit(tree, None)
        sites: Dict[str, List[ast.Call]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                name = terminal_name(node.func)
                if name in defs:
                    sites.setdefault(name, []).append(node)
        marked_set = set(id(f) for f in marked)
        for name, calls in sites.items():
            func = defs[name]
            if id(func) in marked_set:
                continue
            owners = [enclosing.get(id(c)) for c in calls]
            if owners and all(
                o is not None and id(o) in marked_set for o in owners
            ):
                self.hot.append(func)

    # ------------------------------------------------------------- queries

    def local_placements(self, func: ast.AST) -> Dict[str, SpecRef]:
        """The file-level placement map extended with `func`-local
        `x = jax.device_put(v, S)` binds and one aliasing pass
        (`y = x` where x is placed)."""
        out = dict(self.placements)
        for node in walk_shallow(func):
            if isinstance(node, ast.Assign):
                ref = self._placement_ref(node.value)
                if ref is None:
                    alias = dotted_name(node.value)
                    if alias is not None and alias in out:
                        ref = out[alias]
                if ref is None:
                    continue
                for t in node.targets:
                    dotted = dotted_name(t)
                    if dotted is not None:
                        out[dotted] = ref
        return out


def shard_index(ctx) -> ShardIndex:
    """One sharding index per file, memoized on the FileContext (the
    watch-mode AST cache keeps it warm across incremental runs)."""
    idx = getattr(ctx, "_shard_index", None)
    if idx is None:
        idx = ShardIndex(ctx)
        ctx._shard_index = idx
    return idx


def package_summaries(
    contexts: Sequence,
) -> Dict[str, Tuple[ProgramSummary, object]]:
    """Union of every file's named program summaries: name ->
    (summary, owning FileContext). First binding wins on collisions —
    a name meaning two different programs in two files is ambiguous, and
    ambiguity must not become findings."""
    out: Dict[str, Tuple[ProgramSummary, object]] = {}
    for ctx in contexts:
        idx = shard_index(ctx)
        for name, summary in idx.by_name.items():
            out.setdefault(name, (summary, ctx))
    return out


def iter_hot_calls(
    idx: ShardIndex,
) -> Iterator[Tuple[ast.AST, ast.Call]]:
    """(hot function, call inside it) pairs, skipping nested defs."""
    for func in idx.hot:
        for node in walk_shallow(func):
            if isinstance(node, ast.Call):
                yield func, node
