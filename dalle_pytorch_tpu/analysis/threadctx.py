"""Thread-model index for tracelint: which code runs on which thread,
which attribute-bound locks exist, and which shared ``self.*`` attributes
each thread root touches under which locks.

Sibling of `jaxctx.py` (traced-context index) for the concurrency rules
TL013-TL016. Everything is a HEURISTIC over the AST, per file, with the
same false-negative bias as the rest of the pack: an unrecognized
construct means *silent*, never *flagged*.

Vocabulary
----------
Thread root   an entry point that executes on its own thread:
              * a method passed as ``threading.Thread(target=self.X)``
                anywhere in the class (the batcher/vitals/aggregate/
                supervisor ``start()`` idiom) -> root ``thread:X``
              * a ``do_GET``/``do_POST``/... handler method (each HTTP
                request runs on its own ThreadingHTTPServer thread)
                -> root ``handler:do_X``
              * the implicit ``caller`` root: once a class owns any
                worker/handler root, its public methods are presumed
                entered from OTHER threads (the API surface the HTTP
                layer and tests call) — one collective root
              * ``# tracelint: threads`` on (or directly above) a class
                promotes EVERY public method to its own concurrent root
                ``caller:X`` (the handler fan-in shape: N request threads
                entering N different methods of one shared object)
              A method reachable from a root through ``self.m()`` calls
              (transitively, within the class) executes on that root's
              thread; a method reachable from several roots executes on
              all of them.

Lock          an attribute bound to ``threading.Lock()`` / ``RLock()`` /
              ``Condition()`` in any method (``__init__`` in practice).
              ``Condition(self._lock)`` ALIASES the wrapped lock — the
              router's ``_drained = Condition(self._lock)`` acquires the
              same mutex as ``with self._lock``. Only ``with self.X:``
              acquisitions are tracked; bare ``.acquire()`` calls and
              locks passed across objects are not (known limit).

Access        one read/write/mutate/iterate of a ``self.*`` attribute,
              recorded with the set of locks held at that point and the
              roots that can execute it. ``__init__`` (and helpers
              reachable only from it) is never recorded: construction
              happens-before thread start. Threading primitives
              (locks, events, queues, thread handles) are never shared
              state themselves.

Compound write (the TL013 currency): an AugAssign (``self.n += 1``), a
              container mutation (``self.q.append``, ``self.d[k] = v``),
              or a plain rebind in a method that ALSO reads the same
              attribute (check-then-act: the PR 14 export-claim shape).
              A plain write-only rebind (``self._running = False``) is
              the GIL-atomic flag idiom and stays exempt — flagging it
              would bury the real races in noise.

Known limits (document in analysis/README.md, keep in mind when reading
findings): locks held through local aliases (``lock = self._lock``),
cross-object state (``self.server.engine...``), dynamically-created
locks, cross-process state, and ``.acquire()``/``.release()`` pairs are
all invisible; inheritance resolves within one file only.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from dalle_pytorch_tpu.analysis.jaxctx import terminal_name

FunctionNode = (ast.FunctionDef, ast.AsyncFunctionDef)
_ALL_FUNCS = FunctionNode + (ast.Lambda,)

#: constructors that bind a mutual-exclusion lock to an attribute
_LOCK_CTORS = {"Lock", "RLock"}
_COND_CTOR = "Condition"
#: constructors whose product is itself thread-safe (or a thread handle):
#: attributes bound to these are never treated as shared mutable state
_PRIMITIVE_CTORS = _LOCK_CTORS | {
    _COND_CTOR, "Event", "Semaphore", "BoundedSemaphore", "Barrier",
    "Thread", "Timer", "local",
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
}
#: http.server handler entry points — each runs on its own request thread
_HANDLER_METHODS = {
    "do_GET", "do_POST", "do_PUT", "do_DELETE", "do_HEAD", "do_PATCH",
}
#: method names that mutate their receiver container in place
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert",
    "add", "discard", "remove", "pop", "popleft", "popitem",
    "clear", "update", "setdefault", "move_to_end", "rotate",
    "sort", "reverse",
}
#: `self.X.<m>()` reads that walk the whole container (snapshot targets)
_ITER_METHODS = {"items", "values", "keys"}
#: call wrappers that iterate their (single) argument
_ITER_WRAPPERS = {
    "list", "tuple", "sorted", "set", "dict", "frozenset",
    "sum", "min", "max", "any", "all",
}
#: wrappers transparent to the iteration target in a `for`/comprehension
_ITER_UNWRAP = {"enumerate", "reversed", "sorted", "list", "tuple", "iter"}


def _self_attr(node: Optional[ast.AST]) -> Optional[str]:
    """`self.X` -> "X" (one level only; `self.a.b` resolves to None)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


@dataclass
class Access:
    attr: str
    kind: str  # "read" | "write" | "mutate" | "iterate"
    compound: bool  # read-modify-write / container mutation (see module doc)
    locks: FrozenSet[str]  # canonical lock attrs held at this point
    roots: FrozenSet[str]  # root labels that can execute this statement
    method: str
    node: ast.AST


def cross_root(a: Access, b: Access) -> bool:
    """Can `a` and `b` execute on two different threads? True when their
    root sets span more than one label — including a==b for a statement
    reachable from several roots (it races itself)."""
    return len(a.roots | b.roots) >= 2


@dataclass
class ClassModel:
    name: str
    node: ast.ClassDef
    #: effective method table (same-file base classes merged, overrides win)
    methods: Dict[str, ast.AST] = field(default_factory=dict)
    #: lock attr -> canonical lock attr (Condition(self._lock) -> "_lock")
    locks: Dict[str, str] = field(default_factory=dict)
    #: attrs bound to any threading primitive (never shared state)
    primitives: Set[str] = field(default_factory=set)
    thread_targets: Set[str] = field(default_factory=set)
    handler_methods: Set[str] = field(default_factory=set)
    shared_marked: bool = False  # `# tracelint: threads`
    roots_of: Dict[str, FrozenSet[str]] = field(default_factory=dict)
    accesses: List[Access] = field(default_factory=list)
    #: callee -> [(caller, locks held at that `self.callee()` call site)]
    #: — feeds the inherited-lock pass (the `_viable_head` "caller holds
    #: the lock" helper convention)
    call_sites: Dict[str, List[Tuple[str, FrozenSet[str]]]] = field(
        default_factory=dict
    )

    @property
    def threaded(self) -> bool:
        """Does any concurrency exist to analyze? A class with no worker
        thread, no handler methods and no threads marker has one caller
        and the shared-state rules stay silent on it."""
        return bool(
            self.thread_targets or self.handler_methods or self.shared_marked
        )

    def by_attr(self) -> Dict[str, List[Access]]:
        out: Dict[str, List[Access]] = {}
        for a in self.accesses:
            out.setdefault(a.attr, []).append(a)
        return out

    def suggest_lock(self) -> str:
        """A lock name for fix-suggestion messages."""
        for canon in self.locks.values():
            return canon
        return "_lock"


class ThreadIndex:
    """Per-file thread-model index, built once per FileContext (memoized
    by the rules through `ctx._thread_index`)."""

    def __init__(self, tree: ast.Module, marker_lines: frozenset = frozenset()):
        self.tree = tree
        self._marker_lines = set(marker_lines)
        self._class_defs: Dict[str, ast.ClassDef] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                # last def wins on collision, like jaxctx's def table
                self._class_defs[node.name] = node
        self.classes: List[ClassModel] = [
            self._build(node)
            for node in ast.walk(tree)
            if isinstance(node, ast.ClassDef)
        ]

    # ------------------------------------------------------------ building

    def _base_chain(self, cdef: ast.ClassDef) -> List[ast.ClassDef]:
        """[most-base .. cdef] resolved by name within this file; cycles
        and foreign bases are simply not expanded."""
        chain: List[ast.ClassDef] = []
        seen: Set[str] = set()

        def rec(node: ast.ClassDef) -> None:
            if node.name in seen:
                return
            seen.add(node.name)
            for base in node.bases:
                name = terminal_name(base)
                if name and name in self._class_defs:
                    rec(self._class_defs[name])
            chain.append(node)

        rec(cdef)
        return chain

    def _build(self, cdef: ast.ClassDef) -> ClassModel:
        model = ClassModel(cdef.name, cdef)
        for node in self._base_chain(cdef):
            for stmt in node.body:
                if isinstance(stmt, FunctionNode):
                    model.methods[stmt.name] = stmt
        model.shared_marked = self._is_marked(cdef)
        self._find_locks(model)
        self._find_roots(model)
        self._attribute_roots(model)
        self._collect_accesses(model)
        self._inherit_locks(model)
        return model

    def _inherit_locks(self, model: ClassModel) -> None:
        """The `_viable_head` convention: a PRIVATE helper called only
        with a lock held runs under that lock even though it never
        acquires it. inherited(m) = the intersection over every internal
        call site of (locks held at the site | inherited(caller)), to a
        fixpoint; entry points (public methods, thread targets, handler
        methods — anything an external caller enters lock-free) inherit
        nothing."""
        entry = model.thread_targets | model.handler_methods | {
            m for m in model.methods if not m.startswith("_")
        }
        inherited: Dict[str, FrozenSet[str]] = {}
        changed = True
        while changed:
            changed = False
            for callee, sites in model.call_sites.items():
                if callee in entry:
                    continue
                new = frozenset.intersection(*(
                    held | inherited.get(caller, frozenset())
                    for caller, held in sites
                ))
                if new != inherited.get(callee, frozenset()):
                    inherited[callee] = new
                    changed = True
        for access in model.accesses:
            extra = inherited.get(access.method)
            if extra:
                access.locks = access.locks | extra

    def _is_marked(self, cdef: ast.ClassDef) -> bool:
        candidates = {cdef.lineno, cdef.lineno - 1}
        for dec in cdef.decorator_list:
            candidates.add(dec.lineno - 1)
        return bool(candidates & self._marker_lines)

    def _find_locks(self, model: ClassModel) -> None:
        """Two passes so `Condition(self._lock)` can alias a lock bound
        later in the same `__init__` (binding order is irrelevant)."""
        assigns: List[Tuple[str, ast.Call]] = []
        for func in model.methods.values():
            for node in ast.walk(func):
                # plain and annotated bindings both count: an invisible
                # `self._lock: threading.Lock = threading.Lock()` would
                # make every correctly guarded access look unguarded
                if isinstance(node, ast.Assign):
                    targets = node.targets
                    value = node.value
                elif isinstance(node, ast.AnnAssign):
                    targets = [node.target]
                    value = node.value
                else:
                    continue
                if not isinstance(value, ast.Call):
                    continue
                ctor = terminal_name(value.func)
                if ctor not in _PRIMITIVE_CTORS:
                    continue
                for t in targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        assigns.append((attr, value))
                        model.primitives.add(attr)
        for attr, call in assigns:
            ctor = terminal_name(call.func)
            if ctor in _LOCK_CTORS:
                model.locks[attr] = attr
        for attr, call in assigns:
            ctor = terminal_name(call.func)
            if ctor == _COND_CTOR:
                wrapped = _self_attr(call.args[0]) if call.args else None
                if wrapped is not None and wrapped in model.locks:
                    model.locks[attr] = model.locks[wrapped]
                else:
                    model.locks[attr] = attr

    def _find_roots(self, model: ClassModel) -> None:
        for func in model.methods.values():
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                if terminal_name(node.func) != "Thread":
                    continue
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    attr = _self_attr(kw.value)
                    if attr is not None and attr in model.methods:
                        model.thread_targets.add(attr)
        for name in model.methods:
            if name in _HANDLER_METHODS:
                model.handler_methods.add(name)

    def _call_edges(self, model: ClassModel) -> Dict[str, Set[str]]:
        edges: Dict[str, Set[str]] = {}
        for name, func in model.methods.items():
            out: Set[str] = set()
            for node in ast.walk(func):
                if isinstance(node, ast.Call):
                    callee = _self_attr(node.func)
                    if callee is not None and callee in model.methods:
                        out.add(callee)
            edges[name] = out
        return edges

    def _attribute_roots(self, model: ClassModel) -> None:
        if not model.threaded:
            return
        edges = self._call_edges(model)

        def reach(entries: Set[str]) -> Set[str]:
            seen: Set[str] = set()
            stack = [e for e in entries if e in model.methods]
            while stack:
                m = stack.pop()
                if m in seen:
                    continue
                seen.add(m)
                stack.extend(edges.get(m, ()))
            return seen

        root_entries: Dict[str, Set[str]] = {}
        for t in sorted(model.thread_targets):
            root_entries[f"thread:{t}"] = {t}
        for h in sorted(model.handler_methods):
            root_entries[f"handler:{h}"] = {h}
        taken = model.thread_targets | model.handler_methods
        publics = {
            m for m in model.methods
            if not m.startswith("_") and m not in taken
        }
        if model.shared_marked:
            # handler fan-in: every public method is its own concurrent root
            for m in sorted(publics):
                root_entries[f"caller:{m}"] = {m}
        elif publics:
            # worker/handler class: external callers form ONE collective
            # root (we can't tell how many threads call the API, but they
            # are not the worker's thread — that conflict is real)
            root_entries["caller"] = publics

        memo: Dict[str, FrozenSet[str]] = {}
        for label, entries in root_entries.items():
            for m in reach(entries):
                memo[m] = frozenset(memo.get(m, frozenset()) | {label})
        model.roots_of = memo

    # ------------------------------------------------------ access walking

    def _collect_accesses(self, model: ClassModel) -> None:
        for name, func in model.methods.items():
            if name == "__init__":
                continue  # construction happens-before thread start
            roots = model.roots_of.get(name)
            if not roots:
                continue  # unreachable from any root: unattributable
            self._walk_method(model, name, func, roots)

    def _walk_method(
        self, model: ClassModel, mname: str, func: ast.AST,
        roots: FrozenSet[str],
    ) -> None:
        accesses: List[Access] = []
        consumed: Set[int] = set()  # attribute nodes already classified

        def add(attr: Optional[str], kind: str, compound: bool,
                locks: FrozenSet[str], node: ast.AST) -> None:
            if attr is None:
                return
            if attr in model.primitives or attr in model.locks:
                return
            if attr in model.methods:
                return  # bound methods are code, not shared state
            accesses.append(
                Access(attr, kind, compound, locks, roots, mname, node)
            )

        def with_locks(stmt: ast.With) -> FrozenSet[str]:
            out: Set[str] = set()
            for item in stmt.items:
                attr = _self_attr(item.context_expr)
                if attr is not None and attr in model.locks:
                    out.add(model.locks[attr])
            return frozenset(out)

        def iter_target(expr: ast.AST) -> Optional[ast.Attribute]:
            """The `self.X` attribute an iteration expression walks, if
            recognizable: `self.X`, `self.X.items()`, or a transparent
            wrapper (`enumerate`, `reversed`, ...) around either."""
            if _self_attr(expr) is not None:
                return expr  # type: ignore[return-value]
            if isinstance(expr, ast.Call):
                if (
                    isinstance(expr.func, ast.Attribute)
                    and expr.func.attr in _ITER_METHODS
                    and not expr.args
                    and _self_attr(expr.func.value) is not None
                ):
                    return expr.func.value  # type: ignore[return-value]
                if (
                    terminal_name(expr.func) in _ITER_UNWRAP
                    and len(expr.args) >= 1
                ):
                    return iter_target(expr.args[0])
            return None

        def classify_iter(expr: ast.AST, held: FrozenSet[str]) -> None:
            target = iter_target(expr)
            if target is not None and id(target) not in consumed:
                add(_self_attr(target), "iterate", False, held, expr)
                consumed.add(id(target))

        def store_target(t: ast.AST, held: FrozenSet[str]) -> None:
            attr = _self_attr(t)
            if attr is not None:
                add(attr, "write", False, held, t)
                consumed.add(id(t))
                return
            if isinstance(t, ast.Subscript):
                attr = _self_attr(t.value)
                if attr is not None:
                    add(attr, "mutate", True, held, t)
                    consumed.add(id(t.value))
                scan(t.slice, held)
                return
            if isinstance(t, (ast.Tuple, ast.List)):
                for el in t.elts:
                    store_target(el, held)
                return
            if isinstance(t, ast.Starred):
                store_target(t.value, held)
                return
            scan(t, held)

        def scan(node: ast.AST, held: FrozenSet[str]) -> None:
            if isinstance(node, _ALL_FUNCS):
                return  # nested defs: execution thread unknowable — silent
            if isinstance(node, ast.With):
                for item in node.items:
                    if _self_attr(item.context_expr) not in model.locks:
                        scan(item.context_expr, held)
                held2 = held | with_locks(node)
                for stmt in node.body:
                    scan(stmt, held2)
                return
            if isinstance(node, ast.Assign):
                scan(node.value, held)
                for t in node.targets:
                    store_target(t, held)
                return
            if isinstance(node, ast.AugAssign):
                scan(node.value, held)
                attr = _self_attr(node.target)
                if attr is not None:
                    add(attr, "write", True, held, node.target)
                    consumed.add(id(node.target))
                elif isinstance(node.target, ast.Subscript):
                    sub = _self_attr(node.target.value)
                    if sub is not None:
                        add(sub, "mutate", True, held, node.target)
                        consumed.add(id(node.target.value))
                    scan(node.target.slice, held)
                else:
                    scan(node.target, held)
                return
            if isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                        if attr is not None:
                            add(attr, "mutate", True, held, t)
                            consumed.add(id(t.value))
                        scan(t.slice, held)
                    else:
                        attr = _self_attr(t)
                        if attr is not None:
                            add(attr, "write", True, held, t)
                            consumed.add(id(t))
                return
            if isinstance(node, (ast.For, ast.AsyncFor)):
                classify_iter(node.iter, held)
                scan(node.iter, held)
                store_target(node.target, held)
                for stmt in node.body + node.orelse:
                    scan(stmt, held)
                return
            if isinstance(node, ast.comprehension):
                classify_iter(node.iter, held)
                scan(node.iter, held)
                for cond in node.ifs:
                    scan(cond, held)
                return
            if isinstance(node, ast.Call):
                callee = _self_attr(node.func)
                if callee is not None and callee in model.methods:
                    model.call_sites.setdefault(callee, []).append(
                        (mname, held)
                    )
                recv = (
                    node.func.value
                    if isinstance(node.func, ast.Attribute)
                    else None
                )
                recv_attr = _self_attr(recv)
                if recv_attr is not None and node.func.attr in _MUTATORS:
                    add(recv_attr, "mutate", True, held, node)
                    consumed.add(id(recv))
                elif (
                    terminal_name(node.func) in _ITER_WRAPPERS
                    and len(node.args) == 1
                ):
                    classify_iter(node.args[0], held)
                for child in ast.iter_child_nodes(node):
                    scan(child, held)
                return
            if isinstance(node, ast.Attribute):
                attr = _self_attr(node)
                if attr is not None and id(node) not in consumed:
                    if isinstance(node.ctx, ast.Load):
                        add(attr, "read", False, held, node)
                    else:
                        add(attr, "write", False, held, node)
                    return
            for child in ast.iter_child_nodes(node):
                scan(child, held)

        body = func.body if isinstance(func.body, list) else []
        for stmt in body:
            scan(stmt, frozenset())

        # check-then-act promotion: a plain rebind in a method that also
        # READS the same attribute is a read-modify-write (the PR 14
        # export-claim shape) — promote those writes to compound
        read_attrs = {
            a.attr for a in accesses if a.kind in ("read", "iterate", "mutate")
        }
        for a in accesses:
            if a.kind == "write" and not a.compound and a.attr in read_attrs:
                a.compound = True
        model.accesses.extend(accesses)

    # ------------------------------------------------------- lock ordering

    def lock_edges(self) -> Iterator[Tuple[str, str, str, ast.AST]]:
        """(held_key, acquired_key, via, site) acquisition-order edges.
        Keys are "<ClassName>.<canonical attr>". Direct nesting
        (`with self.A: with self.B:`) and ONE hop through a same-class
        method call made while holding A (`with self.A: self.m()` where
        `m` acquires B) are covered; self-edges are skipped (Condition's
        default RLock makes reentry legal, and the one-hop heuristic
        cannot see a release between)."""
        for model in self.classes:
            if not model.locks:
                continue
            acquires = self._method_acquires(model)
            for mname, func in model.methods.items():
                yield from self._edges_in(model, mname, func, acquires)

    def _method_acquires(self, model: ClassModel) -> Dict[str, Set[str]]:
        """method -> canonical lock attrs it acquires anywhere inside."""
        out: Dict[str, Set[str]] = {}
        for name, func in model.methods.items():
            found: Set[str] = set()
            for node in ast.walk(func):
                if isinstance(node, ast.With):
                    for item in node.items:
                        attr = _self_attr(item.context_expr)
                        if attr is not None and attr in model.locks:
                            found.add(model.locks[attr])
            out[name] = found
        return out

    def _edges_in(
        self, model: ClassModel, mname: str, func: ast.AST,
        acquires: Dict[str, Set[str]],
    ) -> Iterator[Tuple[str, str, str, ast.AST]]:
        key = lambda attr: f"{model.name}.{attr}"  # noqa: E731

        def scan(node: ast.AST, held: FrozenSet[str]) -> Iterator[
            Tuple[str, str, str, ast.AST]
        ]:
            if isinstance(node, _ALL_FUNCS):
                return
            if isinstance(node, ast.With):
                new = set()
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    if attr is not None and attr in model.locks:
                        new.add(model.locks[attr])
                for h in held:
                    for n in new:
                        if n != h:
                            yield key(h), key(n), f"`with self.{n}:`", node
                held2 = held | frozenset(new)
                for stmt in node.body:
                    yield from scan(stmt, held2)
                return
            if isinstance(node, ast.Call) and held:
                callee = _self_attr(node.func)
                if callee is not None and callee in model.methods:
                    for n in acquires.get(callee, ()):
                        for h in held:
                            if n != h:
                                yield (
                                    key(h), key(n),
                                    f"call to `self.{callee}()` which "
                                    f"acquires `self.{n}`",
                                    node,
                                )
            for child in ast.iter_child_nodes(node):
                yield from scan(child, held)

        body = func.body if isinstance(func.body, list) else []
        for stmt in body:
            yield from scan(stmt, frozenset())
