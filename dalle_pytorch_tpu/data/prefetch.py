"""Host-side input/compute overlap.

The reference overlaps input with compute via DataLoader worker processes
(`/root/reference/train_dalle.py:309-316`). The TPU-native equivalent here
is a background assembly thread + bounded queue: while step N runs on
device, batch N+1 is decoded/tokenized/`device_put` on the host, so the
chip never idles on PIL decode. One thread is enough — batch assembly is
numpy/PIL work that releases the GIL, and `device_put` overlaps with device
execution by design.

`Prefetcher.wait_fraction` is the measured input-boundedness: the share of
wall time the consumer spent blocked on the queue. ~0 means fully
overlapped; ~1 means the input pipeline is the bottleneck (add workers or
precompute tokens).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, Optional


class _Sentinel:
    pass


_DONE = _Sentinel()


class Prefetcher:
    """Wrap a batch iterator; assemble + transform batches ahead of use.

    transform: host->device assembly (e.g. jnp.asarray + device_put with
    shardings) run in the background thread. depth bounds host memory:
    at most `depth` assembled batches exist beyond the one in use.
    """

    def __init__(
        self,
        batches: Iterable[Any],
        transform: Optional[Callable[[Any], Any]] = None,
        depth: int = 2,
    ):
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._transform = transform
        self._err: Optional[BaseException] = None
        self._wait_s = 0.0
        self._t_start = time.perf_counter()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._produce, args=(iter(batches),), daemon=True
        )
        self._thread.start()

    def _produce(self, it: Iterator[Any]) -> None:
        try:
            for raw in it:
                batch = self._transform(raw) if self._transform else raw
                while not self._stop.is_set():
                    try:
                        self._q.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
        except BaseException as e:  # propagate into the consumer
            self._err = e
        finally:
            while not self._stop.is_set():
                try:
                    self._q.put(_DONE, timeout=0.1)
                    return
                except queue.Full:
                    continue

    def __iter__(self):
        return self

    def __next__(self):
        t0 = time.perf_counter()
        item = self._q.get()
        self._wait_s += time.perf_counter() - t0
        if isinstance(item, _Sentinel):
            if self._err is not None:
                err, self._err = self._err, None
                raise err
            raise StopIteration
        return item

    def close(self) -> None:
        """Stop the producer early (break out of a partial epoch)."""
        self._stop.set()
        # drain so a blocked producer can observe the stop flag
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        # last-resort cleanup if the consumer abandoned iteration (e.g. the
        # train step raised): unblock the producer so it stops pinning
        # device-resident prefetched batches
        try:
            self._stop.set()
        except AttributeError:  # partially-constructed instance
            pass

    @property
    def wait_fraction(self) -> float:
        """Fraction of consumer wall time spent waiting on input."""
        total = time.perf_counter() - self._t_start
        return self._wait_s / total if total > 0 else 0.0
