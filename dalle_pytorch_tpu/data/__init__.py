from dalle_pytorch_tpu.data.tokenizer import (
    SimpleTokenizer,
    ByteTokenizer,
    HugTokenizer,
    ChineseTokenizer,
    YttmTokenizer,
    get_tokenizer,
)
from dalle_pytorch_tpu.data.rainbow import RainbowDataset
from dalle_pytorch_tpu.data.loader import (
    TextImageDataset, Cub2011, MnistDataset, TokenDataset,
)
from dalle_pytorch_tpu.data.webdataset import TarImageTextDataset
