"""Synthetic "rainbow shapes" dataset: compositional captions -> images.

The reference's only end-to-end correctness bar is a notebook that renders
~9k cairo-drawn 32x32 geometric shapes with captions like "small orange
circle", trains dVAE then DALLE, and checks exact image-token-sequence
accuracy (1.0 train / ~0.3 held out)
(`/root/reference/examples/rainbow_dalle.ipynb`, SURVEY.md §4). This module
re-creates that dataset as a deterministic numpy renderer (no cairo
dependency) usable both as a pytest fixture and as a real training set for
the integration run.

Like the notebook (cell 8: 4 scales x 2 fills x 3 ditherers x 12 colors x
8 shapes x 4 rotations = 9216 variations, one image file PER caption), the
dataset is a full cross-product in which **the caption uniquely determines
the image** — the property that makes "exact token-sequence accuracy 1.0
on train" achievable at all. (The map is not injective: rotation words on
rotation-symmetric shapes — e.g. any rotated circle — yield distinct
captions with pixel-identical images, exactly as in the notebook's 9,216
files; a held-out caption can therefore share its image with a training
caption, which mildly flatters held-out exact-match, as it did in the
reference.) Captions:
"<size> [outline] [texture] <color> <shape> [rotation]" over 4 sizes,
12 colors, 8 shapes, filled/outline, 3 textures, 4 rotations = 9216 combos.

When ``num_samples`` exceeds the number of unique combos the dataset falls
back to cycling combos with a small deterministic center jitter; repeated
captions then map to several slightly different images, so exact-match is
capped below 1.0 by construction — per-token accuracy is the cleaner
signal in that regime.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import numpy as np

SIZE_RADII = {"tiny": 0.10, "small": 0.16, "large": 0.24, "huge": 0.32}
SIZES = tuple(SIZE_RADII)
COLORS = {
    "red": (0.9, 0.1, 0.1),
    "orange": (1.0, 0.55, 0.0),
    "yellow": (0.95, 0.9, 0.1),
    "green": (0.1, 0.75, 0.2),
    "cyan": (0.1, 0.8, 0.85),
    "blue": (0.15, 0.25, 0.9),
    "purple": (0.55, 0.15, 0.8),
    "pink": (0.95, 0.5, 0.7),
    "white": (0.95, 0.95, 0.95),
    "gray": (0.55, 0.55, 0.55),
    "brown": (0.55, 0.33, 0.12),
    "magenta": (0.85, 0.1, 0.85),
}
SHAPES = (
    "circle", "square", "triangle", "rhombus",
    "rectangle", "star", "hexagon", "cross",
)
FILLS = ("", "outline")  # "" = filled (like the notebook's unnamed default)
TEXTURES = ("", "striped", "checker")
ROTATIONS = ("", "rotated", "rotated twice", "rotated thrice")


def _sdf(shape: str, dx: np.ndarray, dy: np.ndarray, r: float) -> np.ndarray:
    """Signed distance (px) to the shape boundary; negative = inside."""
    if shape == "circle":
        return np.sqrt(dx**2 + dy**2) - r
    if shape == "square":
        return np.maximum(np.abs(dx), np.abs(dy)) - r * 0.9
    if shape == "triangle":
        h = r * 1.2
        d1 = dy - h * 0.6
        d2 = 0.866 * dx + 0.5 * dy - h * 0.6
        d3 = -0.866 * dx + 0.5 * dy - h * 0.6
        return np.maximum.reduce([d1, d2, d3])
    if shape == "rhombus":  # narrow diamond (distinct from a rotated square)
        return (np.abs(dx) * 1.6 + np.abs(dy)) * 0.75 - r
    if shape == "rectangle":  # wide: half-width r, half-height r/2.2
        return np.maximum(np.abs(dx), np.abs(dy) * 2.2) - r
    if shape == "star":  # hexagram = union of up and down triangles
        up = _sdf("triangle", dx, dy, r)
        down = _sdf("triangle", dx, -dy, r)
        return np.minimum(up, down)
    if shape == "hexagon":
        return (
            np.maximum(0.866 * np.abs(dx) + 0.5 * np.abs(dy), np.abs(dy))
            - r * 0.9
        )
    if shape == "cross":  # union of a wide and a tall bar
        wide = np.maximum(np.abs(dx), np.abs(dy) * 2.8) - r
        tall = np.maximum(np.abs(dx) * 2.8, np.abs(dy)) - r
        return np.minimum(wide, tall)
    raise ValueError(f"unknown shape {shape}")


def render_shape(
    shape: str,
    color: Tuple[float, float, float],
    size: str,
    image_size: int = 32,
    jitter: Tuple[float, float] = (0.0, 0.0),
    *,
    fill: str = "",
    texture: str = "",
    rotation: int = 0,
) -> np.ndarray:
    """Render one anti-aliased shape on a black background. [H, W, 3] in [0,1].

    ``fill="outline"`` draws only a ~2 px interior ring; ``texture`` dims
    alternating stripes/checker cells; ``rotation`` is the number of 90°
    turns applied to the rendered image (mirrors the notebook's np.rot90
    post-pass, cell 7).
    """
    n = image_size
    yy, xx = np.mgrid[0:n, 0:n].astype(np.float64) + 0.5
    cx = n / 2 + jitter[0] * n * 0.1
    cy = n / 2 + jitter[1] * n * 0.1
    r = n * SIZE_RADII[size]

    dist = _sdf(shape, xx - cx, yy - cy, r)
    if fill == "outline":
        # band centered 1 px inside the boundary, ~2 px wide
        alpha = np.clip(0.5 - (np.abs(dist + 1.0) - 1.0), 0.0, 1.0)
    else:
        alpha = np.clip(0.5 - dist, 0.0, 1.0)  # 1px anti-alias band

    if texture == "striped":
        tex = np.where((yy.astype(np.int64) // 2) % 2 == 0, 1.0, 0.3)
    elif texture == "checker":
        tex = np.where(
            ((xx.astype(np.int64) // 3) + (yy.astype(np.int64) // 3)) % 2 == 0,
            1.0, 0.3,
        )
    else:
        tex = 1.0

    img = np.zeros((n, n, 3))
    shade = alpha * tex
    for c in range(3):
        img[..., c] = shade * color[c]
    if rotation:
        img = np.rot90(img, rotation, axes=(0, 1)).copy()
    return img.astype(np.float32)


def _all_combos():
    return [
        {"size": s, "fill": f, "texture": t, "color": c, "shape": sh,
         "rotation": rot}
        for s in SIZES
        for f in FILLS
        for t in TEXTURES
        for c in COLORS
        for sh in SHAPES
        for rot in range(len(ROTATIONS))
    ]


@dataclass
class RainbowDataset:
    """Deterministic caption->image dataset (caption-unique cross-product).

    Up to 9,216 unique (size, fill, texture, color, shape, rotation) combos
    are sampled without replacement in a seed-shuffled order, so every
    caption maps to exactly one image — the property behind the reference
    notebook's exact-match bar. Past the combo count, combos cycle with a
    small deterministic center jitter (caption-ambiguous; see module doc).
    """

    num_samples: int = 1024
    image_size: int = 32
    seed: int = 0

    def __post_init__(self):
        rng = np.random.RandomState(self.seed)
        combos = _all_combos()
        order = rng.permutation(len(combos))
        idx = order[np.arange(self.num_samples) % len(combos)]
        self._combos = [combos[i] for i in idx]
        self.unique = self.num_samples <= len(combos)
        if self.unique:
            self._jitter = np.zeros((self.num_samples, 2))
        else:
            self._jitter = rng.uniform(-1, 1, size=(self.num_samples, 2))

    def __len__(self) -> int:
        return self.num_samples

    def caption(self, i: int) -> str:
        c = self._combos[i]
        words = [c["size"], c["fill"], c["texture"], c["color"], c["shape"],
                 ROTATIONS[c["rotation"]]]
        return " ".join(w for w in words if w)

    def image(self, i: int) -> np.ndarray:
        c = self._combos[i]
        return render_shape(
            c["shape"], COLORS[c["color"]], c["size"], self.image_size,
            tuple(self._jitter[i]), fill=c["fill"], texture=c["texture"],
            rotation=c["rotation"],
        )

    def __getitem__(self, i: int):
        return self.caption(i), self.image(i)

    def batches(self, batch_size: int, tokenizer, text_seq_len: int, *,
                shuffle_seed: int | None = None, shard: Tuple[int, int] = (0, 1),
                drop_last: bool = True, start_batch: int = 0):
        """Yield {"text": [B,T] int32, "images": [B,H,W,3] float32} batches.

        `shard=(i, n)` gives host i of n its interleaved subset — the
        host-sharded replacement for DistributedSampler
        (`/root/reference/train_dalle.py:298-305`).
        """
        from dalle_pytorch_tpu.data.loader import host_shard_order

        order = np.arange(self.num_samples)
        if shuffle_seed is not None:
            np.random.RandomState(shuffle_seed).shuffle(order)
        order = host_shard_order(order, shard)
        for start in range(start_batch * batch_size, len(order), batch_size):
            sel = order[start : start + batch_size]
            if drop_last and len(sel) < batch_size:
                return
            texts = [self.caption(i) for i in sel]
            yield {
                "text": tokenizer.tokenize(texts, text_seq_len, truncate_text=True),
                "images": np.stack([self.image(i) for i in sel]),
                "captions": texts,
            }
