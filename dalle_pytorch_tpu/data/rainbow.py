"""Synthetic "rainbow shapes" dataset: compositional captions -> images.

The reference's only end-to-end correctness bar is a notebook that renders
~9k cairo-drawn 32x32 geometric shapes with captions like "small orange
circle", trains dVAE then DALLE, and checks exact image-token-sequence
accuracy (1.0 train / ~0.3 held out)
(`/root/reference/examples/rainbow_dalle.ipynb`, SURVEY.md §4). This module
re-creates that dataset as a deterministic numpy renderer (no cairo
dependency) usable both as a pytest fixture and as a real training set for
the integration run.

Captions: "<size> <color> <shape>" over sizes {small, large},
9 colors, shapes {circle, square, triangle}.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

SIZES = ("small", "large")
COLORS = {
    "red": (0.9, 0.1, 0.1),
    "orange": (1.0, 0.55, 0.0),
    "yellow": (0.95, 0.9, 0.1),
    "green": (0.1, 0.75, 0.2),
    "cyan": (0.1, 0.8, 0.85),
    "blue": (0.15, 0.25, 0.9),
    "purple": (0.55, 0.15, 0.8),
    "pink": (0.95, 0.5, 0.7),
    "white": (0.95, 0.95, 0.95),
}
SHAPES = ("circle", "square", "triangle")


def render_shape(
    shape: str,
    color: Tuple[float, float, float],
    size: str,
    image_size: int = 32,
    jitter: Tuple[float, float] = (0.0, 0.0),
) -> np.ndarray:
    """Render one anti-aliased shape on a black background. [H, W, 3] in [0,1]."""
    n = image_size
    yy, xx = np.mgrid[0:n, 0:n].astype(np.float64) + 0.5
    cx = n / 2 + jitter[0] * n * 0.1
    cy = n / 2 + jitter[1] * n * 0.1
    r = n * (0.18 if size == "small" else 0.34)

    if shape == "circle":
        dist = np.sqrt((xx - cx) ** 2 + (yy - cy) ** 2) - r
    elif shape == "square":
        dist = np.maximum(np.abs(xx - cx), np.abs(yy - cy)) - r
    elif shape == "triangle":
        # equilateral triangle pointing up: intersection of 3 half-planes
        h = r * 1.2
        d1 = (yy - cy) - h * 0.6  # below the base
        d2 = 0.866 * (xx - cx) + 0.5 * (yy - cy) - h * 0.6
        d3 = -0.866 * (xx - cx) + 0.5 * (yy - cy) - h * 0.6
        dist = np.maximum.reduce([d1, d2, d3])
    else:
        raise ValueError(f"unknown shape {shape}")

    alpha = np.clip(0.5 - dist, 0.0, 1.0)  # 1px anti-alias band
    img = np.zeros((n, n, 3))
    for c in range(3):
        img[..., c] = alpha * color[c]
    return img.astype(np.float32)


@dataclass
class RainbowDataset:
    """Deterministic caption->image dataset.

    num_samples combinations are cycled over (size, color, shape) with a
    small deterministic center jitter so repeated combos differ slightly.
    """

    num_samples: int = 1024
    image_size: int = 32
    seed: int = 0

    def __post_init__(self):
        rng = np.random.RandomState(self.seed)
        combos = [
            (s, c, sh) for s in SIZES for c in COLORS for sh in SHAPES
        ]
        idx = np.arange(self.num_samples) % len(combos)
        rng.shuffle(idx)
        self._combos = [combos[i] for i in idx]
        self._jitter = rng.uniform(-1, 1, size=(self.num_samples, 2))

    def __len__(self) -> int:
        return self.num_samples

    def caption(self, i: int) -> str:
        size, color, shape = self._combos[i]
        return f"{size} {color} {shape}"

    def image(self, i: int) -> np.ndarray:
        size, color, shape = self._combos[i]
        return render_shape(
            shape, COLORS[color], size, self.image_size, tuple(self._jitter[i])
        )

    def __getitem__(self, i: int):
        return self.caption(i), self.image(i)

    def batches(self, batch_size: int, tokenizer, text_seq_len: int, *,
                shuffle_seed: int | None = None, shard: Tuple[int, int] = (0, 1),
                drop_last: bool = True, start_batch: int = 0):
        """Yield {"text": [B,T] int32, "images": [B,H,W,3] float32} batches.

        `shard=(i, n)` gives host i of n its interleaved subset — the
        host-sharded replacement for DistributedSampler
        (`/root/reference/train_dalle.py:298-305`).
        """
        from dalle_pytorch_tpu.data.loader import host_shard_order

        order = np.arange(self.num_samples)
        if shuffle_seed is not None:
            np.random.RandomState(shuffle_seed).shuffle(order)
        order = host_shard_order(order, shard)
        for start in range(start_batch * batch_size, len(order), batch_size):
            sel = order[start : start + batch_size]
            if drop_last and len(sel) < batch_size:
                return
            texts = [self.caption(i) for i in sel]
            yield {
                "text": tokenizer.tokenize(texts, text_seq_len, truncate_text=True),
                "images": np.stack([self.image(i) for i in sel]),
                "captions": texts,
            }
