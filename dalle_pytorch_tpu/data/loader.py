"""Host-side image+caption datasets and the batching pipeline.

Equivalent of the reference's data layer
(`/root/reference/dalle_pytorch/loader.py`, `cub2011.py`): a
`TextImageDataset` keyed on the folder argument — "cub200" -> CUB-200-2011,
"mnist" -> MNIST IDX files, anything else -> an image-folder tree where
captions derive from the parent directory name (optionally mapped through
a user-supplied JSON, generalizing the reference's vendored imagenet.json)
or from a sibling `<stem>.txt` caption file (upstream's paired-caption
mode, `loader.py:56-62`).

TPU-shaped differences:
  * no torch DataLoader worker processes — batches are assembled on the
    host in numpy and fed to jit'ted steps; per-host sharding replaces
    DistributedSampler (`train_dalle.py:298-305`) via `shard=(i, n)`;
  * RandomResizedCrop (`loader.py:70-77`) reimplemented with PIL + numpy
    (same scale/ratio semantics);
  * corrupt images are skipped with a deterministic fallback sample
    (`loader.py:95-98,131-136`).
"""

from __future__ import annotations

import json
import struct
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

IMAGE_EXTS = {".jpg", ".jpeg", ".png", ".bmp", ".webp"}

# ImageNet synset directory names, e.g. n01440764
import re as _re

_WNID_RE = _re.compile(r"n\d{8}")
_IMAGENET_MAP: Optional[Dict[str, str]] = None


def _imagenet_class_map() -> Dict[str, str]:
    """Shipped {wnid: class name} map (data/imagenet_classes.json), loaded
    lazily so non-ImageNet folder datasets never pay for the parse."""
    global _IMAGENET_MAP
    if _IMAGENET_MAP is None:
        path = Path(__file__).parent / "imagenet_classes.json"
        _IMAGENET_MAP = json.loads(path.read_text()) if path.exists() else {}
    return _IMAGENET_MAP


def host_shard_order(order: np.ndarray, shard: Tuple[int, int]) -> np.ndarray:
    """Equal-length interleaved host split.

    Trims `order` to a multiple of the host count BEFORE interleaving so
    every host yields the SAME number of samples (and therefore batches) —
    unequal per-host batch counts would deadlock the collective train step
    on a pod. This re-establishes the invariant DistributedSampler's
    padding provides in the reference (`train_dalle.py:298-305`).
    """
    i, n = shard
    if n <= 1:
        return order
    usable = (len(order) // n) * n
    return order[:usable][i::n]

DIGIT_WORDS = (
    "zero", "one", "two", "three", "four",
    "five", "six", "seven", "eight", "nine",
)


def _load_image(path: Path) -> np.ndarray:
    from PIL import Image

    with Image.open(path) as im:
        return np.asarray(im.convert("RGB"), dtype=np.uint8)


def random_resized_crop(
    img: np.ndarray,
    out_size: int,
    rng: np.random.RandomState,
    scale: Tuple[float, float] = (0.75, 1.0),
    ratio: Tuple[float, float] = (3 / 4, 4 / 3),
) -> np.ndarray:
    """Area-scaled random crop + resize to out_size; [0,1] float32 output."""
    from PIL import Image

    h, w = img.shape[:2]
    area = h * w
    for _ in range(10):
        target_area = area * rng.uniform(*scale)
        aspect = np.exp(rng.uniform(np.log(ratio[0]), np.log(ratio[1])))
        cw = int(round(np.sqrt(target_area * aspect)))
        ch = int(round(np.sqrt(target_area / aspect)))
        if cw <= w and ch <= h:
            x = rng.randint(0, w - cw + 1)
            y = rng.randint(0, h - ch + 1)
            crop = img[y : y + ch, x : x + cw]
            break
    else:  # central fallback
        side = min(h, w)
        y, x = (h - side) // 2, (w - side) // 2
        crop = img[y : y + side, x : x + side]
    out = Image.fromarray(crop).resize((out_size, out_size), Image.BILINEAR)
    return np.asarray(out, dtype=np.float32) / 255.0


# ------------------------------------------------------------------ datasets


class _Dataset:
    """Minimal protocol: __len__ + get(i) -> (caption, uint8 image array)."""

    def __len__(self) -> int:
        raise NotImplementedError

    def get(self, i: int) -> Tuple[str, np.ndarray]:
        raise NotImplementedError


class ImageFolderDataset(_Dataset):
    """Generic folder tree; caption = parent-dir name (mapped/cleaned) or
    sibling .txt file."""

    def __init__(
        self,
        folder: str,
        class_name_json: Optional[str] = None,
        prefer_txt_captions: bool = True,
    ):
        self.root = Path(folder)
        self.paths: List[Path] = sorted(
            p for p in self.root.rglob("*") if p.suffix.lower() in IMAGE_EXTS
        )
        assert len(self.paths) > 0, f"no images found under {folder}"
        self.class_map: Dict[str, str] = {}
        if class_name_json:
            with open(class_name_json) as f:
                self.class_map = json.load(f)
        self.prefer_txt = prefer_txt_captions

    def __len__(self) -> int:
        return len(self.paths)

    def _caption(self, path: Path) -> str:
        if self.prefer_txt:
            txt = path.with_suffix(".txt")
            if txt.exists():
                return txt.read_text().strip()
        key = path.parent.name
        if key in self.class_map:
            return str(self.class_map[key])
        if _WNID_RE.fullmatch(key):
            # ImageNet-style wnid directory names caption out of the box
            # via the shipped class map (the reference vendors the same
            # mapping as `dalle_pytorch/imagenet.json`, `loader.py:43-54`)
            name = _imagenet_class_map().get(key)
            if name:
                return name
        return key.replace("_", " ").replace("-", " ").strip()

    def get(self, i: int) -> Tuple[str, np.ndarray]:
        path = self.paths[i]
        return self._caption(path), _load_image(path)


class Cub2011(_Dataset):
    """CUB-200-2011 from the standard extracted layout (`cub2011.py:10-83`).

    Reads images.txt / train_test_split.txt / image_class_labels.txt /
    classes.txt with pandas; captions come from class names
    ("001.Black_footed_Albatross" -> "black footed albatross",
    reference `loader.py:101-110`). No download (zero-egress build).
    """

    def __init__(self, root: str, train: bool = True):
        import pandas as pd

        self.root = Path(root)
        base = self.root / "CUB_200_2011"
        if not base.exists():
            base = self.root
        images = pd.read_csv(
            base / "images.txt", sep=" ", names=["img_id", "filepath"]
        )
        labels = pd.read_csv(
            base / "image_class_labels.txt", sep=" ", names=["img_id", "target"]
        )
        split = pd.read_csv(
            base / "train_test_split.txt", sep=" ", names=["img_id", "is_training_img"]
        )
        classes = pd.read_csv(
            base / "classes.txt", sep=" ", names=["class_id", "class_name"]
        )
        data = images.merge(labels, on="img_id").merge(split, on="img_id")
        data = data[data.is_training_img == (1 if train else 0)]
        self.data = data.reset_index(drop=True)
        self.class_names = {
            int(r.class_id): str(r.class_name) for r in classes.itertuples()
        }
        self.images_dir = base / "images"
        missing = [
            r.filepath
            for r in self.data.head(16).itertuples()
            if not (self.images_dir / r.filepath).exists()
        ]
        assert not missing, f"CUB-200 integrity check failed; missing {missing[:3]}"

    def __len__(self) -> int:
        return len(self.data)

    def get(self, i: int) -> Tuple[str, np.ndarray]:
        row = self.data.iloc[i]
        name = self.class_names[int(row.target)]
        caption = name.split(".", 1)[-1].replace("_", " ").lower()
        return caption, _load_image(self.images_dir / row.filepath)


class MnistDataset(_Dataset):
    """MNIST from raw IDX files; captions are digit words
    (reference `loader.py:111-119` via torchvision)."""

    def __init__(self, root: str, train: bool = True):
        base = Path(root)
        stem = "train" if train else "t10k"
        img_path = self._find(base, f"{stem}-images-idx3-ubyte")
        lbl_path = self._find(base, f"{stem}-labels-idx1-ubyte")
        with open(img_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            assert magic == 2051, f"bad MNIST image magic {magic}"
            self.images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
        with open(lbl_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            assert magic == 2049, f"bad MNIST label magic {magic}"
            self.labels = np.frombuffer(f.read(), np.uint8)

    @staticmethod
    def _find(base: Path, name: str) -> Path:
        for cand in (base / name, base / "MNIST" / "raw" / name):
            if cand.exists():
                return cand
        raise FileNotFoundError(f"{name} not found under {base}")

    def __len__(self) -> int:
        return len(self.images)

    def get(self, i: int) -> Tuple[str, np.ndarray]:
        img = np.repeat(self.images[i][..., None], 3, axis=-1)
        return DIGIT_WORDS[int(self.labels[i])], img


# ------------------------------------------------------------------ pipeline


class TextImageDataset:
    """Folder-keyed dataset + tokenize/crop/batch pipeline
    (`loader.py:16-139` equivalent).
    """

    def __init__(
        self,
        folder: str,
        text_len: int = 256,
        image_size: int = 128,
        truncate_captions: bool = False,
        resize_ratio: float = 0.75,
        tokenizer=None,
        train: bool = True,
        class_name_json: Optional[str] = None,
        seed: int = 0,
    ):
        name = Path(folder).name.lower()
        if name == "cub200":
            self.dataset: _Dataset = Cub2011(folder, train=train)
        elif name == "mnist":
            self.dataset = MnistDataset(folder, train=train)
        else:
            self.dataset = ImageFolderDataset(folder, class_name_json)
        if tokenizer is None:
            from dalle_pytorch_tpu.data.tokenizer import ByteTokenizer

            tokenizer = ByteTokenizer()
        self.tokenizer = tokenizer
        self.text_len = text_len
        self.image_size = image_size
        self.truncate_captions = truncate_captions
        self.resize_ratio = resize_ratio
        self.rng = np.random.RandomState(seed)
        # caption -> token ids; captions are deterministic across epochs,
        # only the image crop is stochastic, so tokenize each caption once
        self._token_cache: dict = {}

    def __len__(self) -> int:
        return len(self.dataset)

    def _sample(self, i: int) -> Tuple[str, np.ndarray]:
        """Fetch with corrupt-image fallback (`loader.py:95-98,131-136`)."""
        for attempt in range(8):
            try:
                caption, img = self.dataset.get(i)
                return caption, img
            except Exception:
                i = int(self.rng.randint(0, len(self.dataset)))
        raise RuntimeError("too many corrupt samples in a row")

    def item(self, i: int) -> Tuple[np.ndarray, np.ndarray, str]:
        caption, img = self._sample(i)
        text = self._token_cache.get(caption)
        if text is None:
            text = self.tokenizer.tokenize(
                caption, self.text_len, truncate_text=self.truncate_captions
            )[0]
            if len(self._token_cache) < 500_000:  # ~0.5 GB worst case
                self._token_cache[caption] = text
        img = random_resized_crop(
            img, self.image_size, self.rng, scale=(self.resize_ratio, 1.0)
        )
        return text, img, caption

    def batches(
        self,
        batch_size: int,
        shuffle_seed: Optional[int] = None,
        shard: Tuple[int, int] = (0, 1),
        drop_last: bool = True,
        start_batch: int = 0,
    ) -> Iterator[dict]:
        """Host-sharded minibatch stream: {"text": [B,T] token ids,
        "images": [B,H,W,3], "captions": [B] raw strings} — raw captions
        ride along so consumers (precompute_tokens, sample logging) never
        have to lossily decode token ids back to text. `start_batch` skips
        the first N batches by index (O(1) — mid-epoch resume without
        paying decode/augment for already-consumed data)."""
        order = np.arange(len(self.dataset))
        if shuffle_seed is not None:
            np.random.RandomState(shuffle_seed).shuffle(order)
        order = host_shard_order(order, shard)
        for start in range(start_batch * batch_size, len(order), batch_size):
            sel = order[start : start + batch_size]
            if drop_last and len(sel) < batch_size:
                return
            texts, images, caps = zip(*(self.item(int(i)) for i in sel))
            yield {
                "text": np.stack(texts),
                "images": np.stack(images),
                "captions": list(caps),
            }


class TokenDataset:
    """Precomputed-token dataset (`precompute_tokens.py` output).

    The offline-encode counterpart of the reference's in-forward frozen-VAE
    encode (`dalle_pytorch.py:619-627`): batches carry `image_tokens`
    directly, so the train step skips the VAE entirely (SURVEY.md §7 hard
    parts: "precompute tokens as an offline pass — better TPU pattern").
    """

    def __init__(self, npz_path, tokenizer, text_len: int):
        data = np.load(npz_path, allow_pickle=False)
        self.captions = [str(c) for c in data["captions"]]
        self.image_tokens = np.asarray(data["image_tokens"], np.int32)
        self.num_tokens = int(data["num_tokens"])
        self.image_size = int(data["image_size"])
        self.num_layers = int(data["num_layers"])
        self.vae_class_name = str(data["vae_class_name"])
        self.tokenizer = tokenizer
        self.text_len = text_len
        assert len(self.captions) == self.image_tokens.shape[0]

    def __len__(self) -> int:
        return len(self.captions)

    def batches(
        self,
        batch_size: int,
        shuffle_seed: Optional[int] = None,
        shard: Tuple[int, int] = (0, 1),
        drop_last: bool = True,
        start_batch: int = 0,
    ) -> Iterator[dict]:
        order = np.arange(len(self))
        if shuffle_seed is not None:
            np.random.RandomState(shuffle_seed).shuffle(order)
        order = host_shard_order(order, shard)
        for start in range(start_batch * batch_size, len(order), batch_size):
            sel = order[start : start + batch_size]
            if drop_last and len(sel) < batch_size:
                return
            caps = [self.captions[i] for i in sel]
            yield {
                "text": self.tokenizer.tokenize(
                    caps, self.text_len, truncate_text=True
                ),
                "image_tokens": self.image_tokens[sel],
                "captions": caps,
            }
