"""Host-side text tokenizers.

Functional equivalents of the reference's four tokenizers
(`/root/reference/dalle_pytorch/tokenizer.py:55,158,196,232`), all sharing
the contract `tokenize(texts, context_length, truncate_text) ->
int32 [B, ctx]` zero-padded (id 0 is reserved: it becomes the
per-position unique padding token inside DALLE) and `decode(ids)`.

Differences from the reference, by design:
  * tokenization is pure host-side numpy — tokens are fed to jit'ted
    steps as arrays, so no torch dependency;
  * the CLIP BPE vocabulary file is NOT vendored (262k lines; and this
    build environment has no egress) — `SimpleTokenizer` accepts any
    CLIP-format merges file via `bpe_path` and is byte-exact against the
    published CLIP BPE (tests/test_tokenizer_goldens.py);
  * the DEFAULT is the shipped CLIP-scale 32k-merge native C++ BPE
    vocabulary (`default_bpe_32k.model`, `NativeBPETokenizer`) — the
    in-repo replacement for the reference's youtokentome dependency;
  * `ByteTokenizer` is a dependency-free fallback (raw UTF-8 bytes +
    offset) so the full pipeline runs with zero data files.
"""

from __future__ import annotations

import html
import warnings
from functools import lru_cache
from pathlib import Path
from typing import List, Optional, Sequence, Union

import numpy as np

try:
    import regex as re
except ImportError:  # pragma: no cover
    import re  # type: ignore


# ---------------------------------------------------------------- helpers


@lru_cache()
def _byte_unicode_table() -> dict:
    """Reversible byte -> printable-unicode mapping (GPT-2/CLIP scheme).

    Insertion order matters beyond the mapping itself: the CLIP vocabulary
    lists the printable bytes first (in codepoint order) and the remapped
    non-printables after, and single-symbol token ids are positions in that
    list — so this dict iterates in CLIP vocab order, not byte order
    (verified byte-exact by tests/test_tokenizer_goldens.py).
    """
    printable = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    mapping = {b: chr(b) for b in printable}
    extra = 0
    for b in range(256):
        if b not in mapping:
            mapping[b] = chr(256 + extra)
            extra += 1
    return mapping


def _clean_text(text: str) -> str:
    try:
        import ftfy

        text = ftfy.fix_text(text)
    except ImportError:
        pass
    text = html.unescape(html.unescape(text))
    return " ".join(text.split()).strip()


def _pack(
    token_lists: Sequence[List[int]],
    context_length: int,
    truncate_text: bool,
    texts: Sequence[str],
) -> np.ndarray:
    out = np.zeros((len(token_lists), context_length), dtype=np.int32)
    for i, toks in enumerate(token_lists):
        if len(toks) > context_length:
            if not truncate_text:
                raise RuntimeError(
                    f"Input {texts[i]!r} is too long for context length "
                    f"{context_length}"
                )
            toks = toks[:context_length]
        out[i, : len(toks)] = toks
    return out


class _TokenizerBase:
    vocab_size: int

    def encode(self, text: str) -> List[int]:
        raise NotImplementedError

    def decode(self, tokens, pad_tokens: set = frozenset()) -> str:
        raise NotImplementedError

    def tokenize(
        self,
        texts: Union[str, Sequence[str]],
        context_length: int = 256,
        truncate_text: bool = False,
    ) -> np.ndarray:
        if isinstance(texts, str):
            texts = [texts]
        return _pack([self.encode(t) for t in texts], context_length, truncate_text, texts)

    @staticmethod
    def _to_list(tokens) -> List[int]:
        if hasattr(tokens, "tolist"):
            return [int(t) for t in np.asarray(tokens).reshape(-1)]
        return list(tokens)


# ---------------------------------------------------------- byte fallback


class ByteTokenizer(_TokenizerBase):
    """Dependency-free byte-level tokenizer: ids = utf-8 bytes + 1.

    Not in the reference; exists so the framework runs end-to-end with no
    vocabulary file (id 0 stays reserved for padding).
    """

    def __init__(self):
        self.vocab_size = 257

    def encode(self, text: str) -> List[int]:
        return [b + 1 for b in _clean_text(text).lower().encode("utf-8")]

    def decode(self, tokens, pad_tokens: set = frozenset()) -> str:
        toks = [t for t in self._to_list(tokens) if t > 0 and t not in pad_tokens]
        return bytes(t - 1 for t in toks).decode("utf-8", errors="replace")


# ------------------------------------------------------------- CLIP BPE


class SimpleTokenizer(_TokenizerBase):
    """Byte-level BPE in the OpenAI-CLIP vocabulary format.

    Loads a CLIP `bpe_simple_vocab_16e6.txt`-style merges file (first line
    is a header; merges are space-separated pairs). Vocabulary layout
    matches CLIP: 256 byte symbols, 256 end-of-word symbols, one id per
    merge, then <|startoftext|>/<|endoftext|> (total 49,408 for the
    standard file — reference `tokenizer.py:68`).
    """

    MAX_MERGES = 49152 - 256 - 2

    def __init__(self, bpe_path: Union[str, Path]):
        bpe_path = Path(bpe_path)
        assert bpe_path.exists(), f"BPE merges file {bpe_path} does not exist"
        self.byte_to_unicode = _byte_unicode_table()
        self.unicode_to_byte = {v: k for k, v in self.byte_to_unicode.items()}

        lines = bpe_path.read_text(encoding="utf8").split("\n")
        merges = [tuple(m.split()) for m in lines[1 : self.MAX_MERGES + 1] if m]

        symbols = list(self.byte_to_unicode.values())
        vocab = symbols + [s + "</w>" for s in symbols]
        vocab += ["".join(pair) for pair in merges]
        vocab += ["<|startoftext|>", "<|endoftext|>"]

        self.token_to_id = {tok: i for i, tok in enumerate(vocab)}
        self.id_to_token = {i: tok for tok, i in self.token_to_id.items()}
        self.merge_rank = {pair: i for i, pair in enumerate(merges)}
        self.vocab_size = len(vocab)
        self._cache: dict[str, List[str]] = {}
        self.pattern = re.compile(
            r"<\|startoftext\|>|<\|endoftext\|>|'s|'t|'re|'ve|'m|'ll|'d"
            r"|[\p{L}]+|[\p{N}]|[^\s\p{L}\p{N}]+",
            re.IGNORECASE,
        )
        self.sot = self.token_to_id["<|startoftext|>"]
        self.eot = self.token_to_id["<|endoftext|>"]

    def _bpe(self, token: str) -> List[str]:
        if token in self._cache:
            return self._cache[token]
        parts = list(token[:-1]) + [token[-1] + "</w>"]
        while len(parts) > 1:
            pairs = [(parts[i], parts[i + 1]) for i in range(len(parts) - 1)]
            ranked = min(pairs, key=lambda p: self.merge_rank.get(p, float("inf")))
            if ranked not in self.merge_rank:
                break
            merged: List[str] = []
            i = 0
            while i < len(parts):
                if (
                    i < len(parts) - 1
                    and (parts[i], parts[i + 1]) == ranked
                ):
                    merged.append(parts[i] + parts[i + 1])
                    i += 2
                else:
                    merged.append(parts[i])
                    i += 1
            parts = merged
        self._cache[token] = parts
        return parts

    def encode(self, text: str) -> List[int]:
        ids: List[int] = []
        for word in re.findall(self.pattern, _clean_text(text).lower()):
            if word in ("<|startoftext|>", "<|endoftext|>"):
                # control tokens pass through whole (the pattern matches
                # them as single words; they must not be byte-BPE'd)
                ids.append(self.token_to_id[word])
                continue
            mapped = "".join(self.byte_to_unicode[b] for b in word.encode("utf-8"))
            ids.extend(self.token_to_id[p] for p in self._bpe(mapped))
        return ids

    def decode(self, tokens, pad_tokens: set = frozenset()) -> str:
        skip = set(pad_tokens) | {0, self.sot, self.eot}
        toks = [t for t in self._to_list(tokens) if t not in skip]
        text = "".join(self.id_to_token.get(t, "") for t in toks)
        raw = bytes(self.unicode_to_byte[c] for c in text if c in self.unicode_to_byte)
        return raw.decode("utf-8", errors="replace").replace("</w>", " ").strip()


# --------------------------------------------------- HuggingFace bridges


class HugTokenizer(_TokenizerBase):
    """tokenizers-json bridge (reference `tokenizer.py:158-192`)."""

    def __init__(self, bpe_path: Union[str, Path]):
        from transformers import PreTrainedTokenizerFast

        bpe_path = Path(bpe_path)
        assert bpe_path.exists(), f"BPE json path {bpe_path} does not exist"
        self.tokenizer = PreTrainedTokenizerFast(tokenizer_file=str(bpe_path))
        self.vocab_size = self.tokenizer.vocab_size

    def encode(self, text: str) -> List[int]:
        return self.tokenizer.encode(text, add_special_tokens=False)

    def decode(self, tokens, pad_tokens: set = frozenset()) -> str:
        skip = set(pad_tokens) | {0}
        toks = [t for t in self._to_list(tokens) if t not in skip]
        return self.tokenizer.decode(toks, skip_special_tokens=True)


class ChineseTokenizer(_TokenizerBase):
    """bert-base-chinese wordpiece (reference `tokenizer.py:196-228`).

    Requires the model files locally (no egress in this build env).
    """

    def __init__(self, model_name: str = "bert-base-chinese"):
        from transformers import BertTokenizerFast

        self.tokenizer = BertTokenizerFast.from_pretrained(model_name)
        self.vocab_size = self.tokenizer.vocab_size

    def encode(self, text: str) -> List[int]:
        return self.tokenizer.encode(text, add_special_tokens=False)

    def decode(self, tokens, pad_tokens: set = frozenset()) -> str:
        skip = set(pad_tokens) | {0}
        toks = [t for t in self._to_list(tokens) if t not in skip]
        return self.tokenizer.decode(toks)


class YttmTokenizer(_TokenizerBase):
    """youtokentome-model bridge (reference `tokenizer.py:232-266`).

    youtokentome (C++ BPE) is not in this environment; raise with
    guidance. `NativeBPETokenizer` (native/bpe.cpp) is the in-repo
    replacement for new vocabularies; this bridge exists for users with
    existing yttm model files and an installed youtokentome.
    """

    def __init__(self, bpe_path: Union[str, Path]):
        try:
            import youtokentome as yttm  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "youtokentome is not installed; use SimpleTokenizer/"
                "HugTokenizer, or convert the yttm model to a tokenizers json"
            ) from e
        import youtokentome as yttm

        self.tokenizer = yttm.BPE(model=str(bpe_path))
        self.vocab_size = self.tokenizer.vocab_size()

    def encode(self, text: str) -> List[int]:
        import youtokentome as yttm

        return self.tokenizer.encode([text], output_type=yttm.OutputType.ID)[0]

    def decode(self, tokens, pad_tokens: set = frozenset()) -> str:
        return self.tokenizer.decode(
            [self._to_list(tokens)], ignore_ids=list(set(pad_tokens) | {0})
        )[0]


class NativeBPETokenizer(_TokenizerBase):
    """Framework-native C++ BPE (native/bpe.cpp via ctypes) — the in-repo
    replacement for the reference's youtokentome C++ dependency
    (`tokenizer.py:232-266`). Same tokenize/decode contract; batch encode
    runs threaded in native code.
    """

    def __init__(self, bpe_path: Union[str, Path]):
        from dalle_pytorch_tpu.data.native_bpe import NativeBPE

        self.bpe = NativeBPE.load(bpe_path)
        self.vocab_size = self.bpe.vocab_size

    @classmethod
    def train(cls, corpus: str, model_path: Union[str, Path], vocab_size: int = 8192):
        from dalle_pytorch_tpu.data.native_bpe import NativeBPE

        NativeBPE.train(corpus, vocab_size).save(model_path)
        return cls(model_path)

    def encode(self, text: str) -> List[int]:
        return self.bpe.encode(_clean_text(text))

    def tokenize(
        self,
        texts: Union[str, Sequence[str]],
        context_length: int = 256,
        truncate_text: bool = False,
    ) -> np.ndarray:
        if isinstance(texts, str):
            texts = [texts]
        return self.bpe.encode_batch(
            [_clean_text(t) for t in texts], context_length, truncate=truncate_text
        )

    def decode(self, tokens, pad_tokens: set = frozenset()) -> str:
        ids = [t for t in self._to_list(tokens) if t not in pad_tokens]
        return self.bpe.decode(ids)


# Cached default-vocabulary decision: ("native", Path) once a probe
# succeeds. `get_tokenizer()` with no flags probes the shipped
# default_bpe_*.model files, warning for each unusable candidate — but
# builders construct tokenizers repeatedly (trainer, generate CLI, serving
# engine), and re-probing a broken vocabulary re-emitted the same
# `default_bpe_32k.model unusable` UserWarning every time. Only SUCCESS is
# cached: the ByteTokenizer fallback keeps re-probing (so a transiently
# unusable vocabulary — e.g. the native extension still compiling — can
# recover later in the process) but its warnings fire once per process via
# `_warned_default_probe`. A cached "native" decision that stops
# constructing (toolchain vanished, monkeypatched test double) invalidates
# itself and re-probes.
_default_decision = None
_warned_default_probe = False


def get_tokenizer(
    bpe_path: Optional[str] = None,
    hug: bool = False,
    chinese: bool = False,
    yttm: bool = False,
    native: bool = False,
) -> _TokenizerBase:
    """Tokenizer selection mirroring the trainer flags
    (`/root/reference/train_dalle.py:131-135`), plus the framework-native
    C++ BPE backend."""
    if chinese:
        return ChineseTokenizer()
    if native:
        assert bpe_path, "--bpe_path required for native BPE tokenizer"
        return NativeBPETokenizer(bpe_path)
    if yttm:
        assert bpe_path, "--bpe_path required for yttm tokenizer"
        return YttmTokenizer(bpe_path)
    if hug:
        assert bpe_path, "--bpe_path required for huggingface tokenizer"
        return HugTokenizer(bpe_path)
    if bpe_path:
        return SimpleTokenizer(bpe_path)
    # No flags: use the shipped native BPE vocabulary (the analogue of the
    # reference's vendored CLIP vocab, `tokenizer.py:64-68`) — trained by
    # scripts/train_default_vocab.py and committed to the repo. Discovery is
    # by glob so any regenerated default_bpe_<N>k.model is picked up;
    # largest vocabulary wins (the CLIP-scale 32k model over the lighter 8k
    # fallback kept for fast tests).
    global _default_decision, _warned_default_probe
    if _default_decision is not None:
        kind, model_path = _default_decision
        try:
            return NativeBPETokenizer(model_path)
        except Exception:
            _default_decision = None  # stale decision: re-probe (and re-warn)
            _warned_default_probe = False

    def _vocab_k(p: Path) -> int:
        try:
            return int(p.stem[len("default_bpe_"):].rstrip("k"))
        except ValueError:
            return 0

    existing = sorted(
        Path(__file__).parent.glob("default_bpe_*.model"),
        key=_vocab_k, reverse=True,
    )
    for default_model in existing:
        try:
            tok = NativeBPETokenizer(default_model)
            _default_decision = ("native", default_model)
            return tok
        except Exception as e:  # e.g. no C++ toolchain, corrupt model file
            if _warned_default_probe:
                continue
            next_step = (
                "trying the next candidate"
                if default_model != existing[-1]
                else "falling back to the 257-symbol ByteTokenizer"
            )
            warnings.warn(
                f"default BPE vocabulary {default_model.name} unusable "
                f"({e}); {next_step}",
                stacklevel=2,
            )
    if not existing and not _warned_default_probe:
        warnings.warn(
            "no default BPE vocabulary "
            f"(no {Path(__file__).parent}/default_bpe_*.model — run "
            "scripts/train_default_vocab.py); "
            "falling back to the 257-symbol ByteTokenizer, which trains "
            "byte-level models only",
            stacklevel=2,
        )
    # fallback is NOT cached — the next call re-probes (silently), so a
    # vocabulary that becomes usable later in the process is picked up
    _warned_default_probe = True
    return ByteTokenizer()
