"""Streaming tar-shard dataset (WebDataset-style).

Equivalent of the reference's WebDataset pipeline
(`/root/reference/train_dalle.py:97-117,257-278,309-313`): samples are
stored as `key.jpg` + `key.txt` pairs inside (possibly many) tar shards;
sources can be local tar files, brace-expanded shard patterns
(`shard-{0000..0042}.tar`), directories of tars, or `pipe:` commands
(e.g. `pipe:gsutil cat gs://...` — the reference's GCS path). Implemented
directly on `tarfile` — no webdataset dependency.

Decode errors follow the reference's `warn_and_continue` handler; the
image/caption column names are configurable like `--wds img,cap`.
"""

from __future__ import annotations

import io
import re
import subprocess
import tarfile
from pathlib import Path
from typing import Iterator, List, Optional, Tuple

import numpy as np

from dalle_pytorch_tpu.data.loader import random_resized_crop

IMAGE_KEYS = ("jpg", "jpeg", "png", "img", "image")
TEXT_KEYS = ("txt", "text", "cap", "caption")


def expand_shards(url: str) -> List[str]:
    """Expand `{0000..0099}` brace patterns / directories into shard lists."""
    m = re.search(r"\{(\d+)\.\.(\d+)\}", url)
    if m:
        lo, hi = m.group(1), m.group(2)
        width = len(lo)
        return [
            url[: m.start()] + str(i).zfill(width) + url[m.end() :]
            for i in range(int(lo), int(hi) + 1)
        ]
    p = Path(url)
    if p.is_dir():
        return [str(t) for t in sorted(p.glob("*.tar"))]
    return [url]


def _open_stream(url: str):
    """Returns (fileobj, proc_or_None)."""
    if url.startswith("pipe:"):
        proc = subprocess.Popen(
            url[len("pipe:") :], shell=True, stdout=subprocess.PIPE
        )
        return proc.stdout, proc
    return open(url, "rb"), None


def _iter_tar_samples(url: str) -> Iterator[dict]:
    """Group tar members by sample key ('dir/stem') preserving order."""
    stream, proc = _open_stream(url)
    try:
        with tarfile.open(fileobj=stream, mode="r|*") as tar:
            current_key, fields = None, {}
            for member in tar:
                if not member.isfile():
                    continue
                name = member.name
                stem, _, ext = name.rpartition(".")
                if current_key is not None and stem != current_key and fields:
                    yield fields
                    fields = {}
                current_key = stem
                data = tar.extractfile(member)
                if data is not None:
                    fields[ext.lower()] = data.read()
            if fields:
                yield fields
    finally:
        stream.close()
        if proc is not None:
            ret = proc.wait()
            if ret != 0:
                raise RuntimeError(
                    f"pipe command for shard {url!r} exited with status {ret} "
                    "— stream may be truncated"
                )


class TarImageTextDataset:
    """Iterable tar-shard dataset -> host-sharded numpy batches."""

    def __init__(
        self,
        urls: str,
        image_key: str = "jpg",
        text_key: str = "txt",
        text_len: int = 256,
        image_size: int = 128,
        truncate_captions: bool = True,
        resize_ratio: float = 0.75,
        tokenizer=None,
        seed: int = 0,
        shuffle_buffer: int = 1000,
    ):
        self.shards = expand_shards(urls)
        assert self.shards, f"no shards matched {urls}"
        self.image_keys = (image_key,) + IMAGE_KEYS
        self.text_keys = (text_key,) + TEXT_KEYS
        if tokenizer is None:
            from dalle_pytorch_tpu.data.tokenizer import ByteTokenizer

            tokenizer = ByteTokenizer()
        self.tokenizer = tokenizer
        self.text_len = text_len
        self.image_size = image_size
        self.truncate = truncate_captions
        self.resize_ratio = resize_ratio
        self.rng = np.random.RandomState(seed)
        self.shuffle_buffer = shuffle_buffer

    def _decode(self, sample: dict) -> Optional[Tuple[str, np.ndarray]]:
        from PIL import Image

        img_bytes = next(
            (sample[k] for k in self.image_keys if k in sample), None
        )
        txt_bytes = next(
            (sample[k] for k in self.text_keys if k in sample), None
        )
        if img_bytes is None or txt_bytes is None:
            return None  # filter: both columns required (`train_dalle.py:269-274`)
        try:
            with Image.open(io.BytesIO(img_bytes)) as im:
                img = np.asarray(im.convert("RGB"), dtype=np.uint8)
            return txt_bytes.decode("utf-8", errors="replace").strip(), img
        except Exception as e:  # warn_and_continue (`train_dalle.py:276`)
            print(f"[wds] skipping undecodable sample: {e}")
            return None

    def samples(
        self,
        shard: Tuple[int, int] = (0, 1),
        shuffle_seed: Optional[int] = None,
    ) -> Iterator[Tuple[str, np.ndarray]]:
        """Shard-level host split: host i reads every n-th tar shard.

        With `shuffle_seed`, the per-host shard order is permuted and
        samples pass through a reservoir-style shuffle buffer — the
        streaming equivalent of the reference's `wds.WebDataset` shuffle
        stage (`/root/reference/train_dalle.py:257-278`). Different seeds
        (e.g. seed+epoch) give a fresh order every epoch.
        """
        if shard[1] > 1 and len(self.shards) < shard[1]:
            raise ValueError(
                f"{len(self.shards)} tar shards cannot be split across "
                f"{shard[1]} hosts — provide at least one shard per host"
            )
        my_shards = self.shards[shard[0] :: shard[1]]
        rng = None
        if shuffle_seed is not None:
            rng = np.random.RandomState(shuffle_seed)
            my_shards = [my_shards[i] for i in rng.permutation(len(my_shards))]

        def raw_stream() -> Iterator[dict]:
            for url in my_shards:
                yield from _iter_tar_samples(url)

        def shuffled_raw() -> Iterator[dict]:
            # Buffer RAW tar samples (compressed bytes, ~100KB each), not
            # decoded arrays — decoding before the 1000-slot buffer would
            # hold ~GBs of pixels per host. Decode happens on yield, with
            # failures filtered after the shuffle stage, exactly like the
            # reference's shuffle->decode(warn_and_continue) pipeline order.
            if rng is None or self.shuffle_buffer <= 1:
                yield from raw_stream()
                return
            buf: List[dict] = []
            for item in raw_stream():
                buf.append(item)
                if len(buf) >= self.shuffle_buffer:
                    j = rng.randint(len(buf))
                    buf[j], buf[-1] = buf[-1], buf[j]
                    yield buf.pop()
            rng.shuffle(buf)
            yield from buf

        for raw in shuffled_raw():
            decoded = self._decode(raw)
            if decoded is not None:
                yield decoded

    def batches(
        self,
        batch_size: int,
        shuffle_seed: Optional[int] = None,
        shard: Tuple[int, int] = (0, 1),
        start_batch: int = 0,
    ) -> Iterator[dict]:
        """`start_batch` skips already-consumed batches on resume. For a
        streaming tar source the skip must still read+decode the stream to
        keep the sample order identical — unavoidable without an index."""
        stream = self.samples(shard, shuffle_seed=shuffle_seed)
        if start_batch:
            import itertools

            stream = itertools.islice(stream, start_batch * batch_size, None)
        texts, images, captions = [], [], []
        for caption, img in stream:
            texts.append(
                self.tokenizer.tokenize(caption, self.text_len, self.truncate)[0]
            )
            images.append(
                random_resized_crop(
                    img, self.image_size, self.rng, scale=(self.resize_ratio, 1.0)
                )
            )
            captions.append(caption)
            if len(texts) == batch_size:
                yield {
                    "text": np.stack(texts),
                    "images": np.stack(images),
                    "captions": captions,
                }
                texts, images, captions = [], [], []
