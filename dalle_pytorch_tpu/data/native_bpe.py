"""ctypes bindings for the native C++ byte-level BPE core (native/bpe.cpp).

This supplies the capability the reference gets from the external
youtokentome C++ library (`/root/reference/dalle_pytorch/tokenizer.py:232-266`)
— fast host-side BPE train/encode/decode — as part of this framework's own
native runtime. The shared library is built on demand with g++ (cached by
source mtime); tokenization is host-side, so no TPU involvement.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path
from typing import List, Optional, Sequence, Union

import numpy as np

_REPO_ROOT = Path(__file__).resolve().parents[2]
_NATIVE_DIR = _REPO_ROOT / "native"
_SRC = _NATIVE_DIR / "bpe.cpp"
_LIB = _NATIVE_DIR / "build" / "libdalle_bpe.so"

_lib = None


def _build_library() -> Path:
    if _LIB.exists() and _LIB.stat().st_mtime >= _SRC.stat().st_mtime:
        return _LIB
    _LIB.parent.mkdir(parents=True, exist_ok=True)
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O2", "-std=c++17", "-fPIC", "-shared", "-Wall",
        "-o", str(_LIB), str(_SRC), "-lpthread",
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"native BPE build failed ({' '.join(cmd)}):\n{proc.stderr}"
        )
    return _LIB


def _load_library():
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(str(_build_library()))
    lib.bpe_train.restype = ctypes.c_void_p
    lib.bpe_train.argtypes = [ctypes.c_char_p, ctypes.c_int32]
    lib.bpe_load.restype = ctypes.c_void_p
    lib.bpe_load.argtypes = [ctypes.c_char_p]
    lib.bpe_save.restype = ctypes.c_int
    lib.bpe_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.bpe_free.argtypes = [ctypes.c_void_p]
    lib.bpe_vocab_size.restype = ctypes.c_int32
    lib.bpe_vocab_size.argtypes = [ctypes.c_void_p]
    lib.bpe_encode.restype = ctypes.c_int32
    lib.bpe_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
    ]
    lib.bpe_encode_batch.restype = ctypes.c_int32
    lib.bpe_encode_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32,
    ]
    lib.bpe_decode.restype = ctypes.c_int32
    lib.bpe_decode.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
        ctypes.c_char_p, ctypes.c_int32,
    ]
    _lib = lib
    return lib


class NativeBPE:
    """Handle to a trained native BPE model."""

    def __init__(self, handle: int):
        assert handle, "null native BPE handle"
        self._lib = _load_library()
        self._handle = handle

    # ------------------------------------------------------- constructors

    @classmethod
    def train(cls, corpus: str, vocab_size: int = 8192) -> "NativeBPE":
        lib = _load_library()
        h = lib.bpe_train(corpus.encode("utf-8"), vocab_size)
        return cls(h)

    @classmethod
    def train_file(cls, corpus_path: Union[str, Path], vocab_size: int = 8192):
        return cls.train(Path(corpus_path).read_text(), vocab_size)

    @classmethod
    def load(cls, model_path: Union[str, Path]) -> "NativeBPE":
        lib = _load_library()
        h = lib.bpe_load(str(model_path).encode("utf-8"))
        if not h:
            raise FileNotFoundError(f"cannot load native BPE model {model_path}")
        return cls(h)

    def save(self, model_path: Union[str, Path]) -> None:
        rc = self._lib.bpe_save(self._handle, str(model_path).encode("utf-8"))
        if rc != 0:
            raise IOError(f"cannot save native BPE model to {model_path}")

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle and _lib is not None:
            _lib.bpe_free(handle)
            self._handle = None

    # -------------------------------------------------------------- codec

    @property
    def vocab_size(self) -> int:
        return self._lib.bpe_vocab_size(self._handle)

    def encode(self, text: str, max_len: int = 1 << 16) -> List[int]:
        buf = (ctypes.c_int32 * max_len)()
        n = self._lib.bpe_encode(self._handle, text.encode("utf-8"), buf, max_len)
        return list(buf[: min(n, max_len)])

    def encode_batch(
        self,
        texts: Sequence[str],
        max_len: int,
        truncate: bool = True,
        n_threads: Optional[int] = None,
    ) -> np.ndarray:
        """Threaded batch encode -> zero-padded int32 [n, max_len]."""
        if n_threads is None:
            n_threads = min(len(texts), os.cpu_count() or 1, 8)
        encoded = [t.encode("utf-8") for t in texts]
        blob = b"\0".join(encoded) + b"\0"
        offsets = np.zeros(len(texts), dtype=np.int64)
        pos = 0
        for i, e in enumerate(encoded):
            offsets[i] = pos
            pos += len(e) + 1
        out = np.zeros((len(texts), max_len), dtype=np.int32)
        rc = self._lib.bpe_encode_batch(
            self._handle,
            blob,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(texts),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            max_len,
            1 if truncate else 0,
            n_threads,
        )
        if rc != 0:
            raise RuntimeError(
                f"Input {texts[rc - 1]!r} is too long for context length {max_len}"
            )
        return out

    def decode(self, ids: Sequence[int]) -> str:
        arr = np.asarray(list(ids), dtype=np.int32)
        max_bytes = max(len(arr) * 64, 256)
        buf = ctypes.create_string_buffer(max_bytes)
        n = self._lib.bpe_decode(
            self._handle,
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(arr),
            buf,
            max_bytes,
        )
        return buf.raw[:n].decode("utf-8", errors="replace")
