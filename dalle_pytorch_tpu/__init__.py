"""dalle_pytorch_tpu: a TPU-native (JAX/XLA/Pallas/pjit) framework with the
capabilities of DALLE-pytorch (discrete VAE + autoregressive text->image
transformer + CLIP), re-designed TPU-first.

Public API mirrors the reference package surface
(`/root/reference/dalle_pytorch/__init__.py:1-2`): DALLE, CLIP, DiscreteVAE,
plus pretrained-VAE import wrappers.
"""

from dalle_pytorch_tpu.version import __version__
from dalle_pytorch_tpu.models.dvae import DiscreteVAE
from dalle_pytorch_tpu.models.dalle import DALLE
from dalle_pytorch_tpu.models.clip import CLIP
from dalle_pytorch_tpu.models.vae_io import OpenAIDiscreteVAE, VQGanVAE

__all__ = [
    "DALLE",
    "CLIP",
    "DiscreteVAE",
    "OpenAIDiscreteVAE",
    "VQGanVAE",
    "__version__",
]
