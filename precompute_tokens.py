#!/usr/bin/env python
"""Precompute frozen-VAE image tokens for a dataset (offline pass).

The reference encodes images through the frozen VAE inside every training
forward (`/root/reference/dalle_pytorch/dalle_pytorch.py:619-627`), paying
the encoder cost each step. The better TPU pattern (SURVEY.md §7 hard
parts) is to run the encode ONCE offline and train the transformer from
tokens — this CLI produces that artifact:

  python precompute_tokens.py --image_text_folder data/ --vae_path vae.npz \\
      --output tokens.npz
  python train_dalle.py --tokens_path tokens.npz --vae_path vae.npz ...

The .npz stores raw captions (tokenized at train time with whatever
tokenizer the run selects) plus int32 image tokens and the VAE geometry.
"""

from __future__ import annotations

import argparse


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--image_text_folder", type=str, required=True)
    p.add_argument("--vae_path", type=str, default=None)
    p.add_argument("--taming", action="store_true")
    p.add_argument("--vqgan_model_path", type=str, default=None)
    p.add_argument("--vqgan_config_path", type=str, default=None)
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--output", type=str, default="tokens.npz")
    # tokenizer flags only affect the dataset's tokenize pass (which this
    # CLI ignores — captions are stored RAW); exposed so folder modes that
    # tokenize eagerly never error on long captions with exotic vocabs
    p.add_argument("--bpe_path", type=str, default=None)
    p.add_argument("--native", action="store_true")
    p.add_argument("--hug", action="store_true")
    p.add_argument("--chinese", action="store_true")
    p.add_argument("--yttm", action="store_true")
    return p.parse_args()


def main():
    args = parse_args()
    import jax
    import os

    if os.environ.get("DALLE_TPU_FORCE_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["DALLE_TPU_FORCE_PLATFORM"])
    import jax.numpy as jnp
    import numpy as np

    from dalle_pytorch_tpu.models.dvae import DiscreteVAE
    from dalle_pytorch_tpu.training.config import TrainConfig
    from dalle_pytorch_tpu.training.pipeline import (
        build_dataset, build_tokenizer, load_vae_checkpoint,
    )

    if args.taming:
        from dalle_pytorch_tpu.models.vae_io import VQGanVAE

        vae = VQGanVAE(args.vqgan_model_path, args.vqgan_config_path)
        vae_params = None
        vae_class = "VQGanVAE"
        encode = vae.get_codebook_indices
    else:
        assert args.vae_path, "--vae_path or --taming required"
        vae, vae_params = load_vae_checkpoint(args.vae_path)
        vae_class = "DiscreteVAE"
        encode = jax.jit(
            lambda imgs: vae.apply(
                {"params": vae_params}, imgs,
                method=DiscreteVAE.get_codebook_indices,
            )
        )

    cfg = TrainConfig()
    cfg.image_text_folder = args.image_text_folder
    cfg.truncate_captions = True
    for flag in ("bpe_path", "native", "hug", "chinese", "yttm"):
        if getattr(args, flag):
            setattr(cfg, flag, getattr(args, flag))
    tokenizer = build_tokenizer(cfg)
    dataset = build_dataset(cfg, tokenizer, image_size=vae.image_size)
    print(f"encoding {len(dataset)} samples at {vae.image_size}px")

    captions, token_chunks = [], []
    # every dataset's batch stream carries RAW caption strings — stored
    # verbatim, so the artifact is tokenizer-agnostic and lossless
    # (train-time runs tokenize them with whatever tokenizer they select)
    n_done = 0
    for batch in dataset.batches(args.batch_size, shuffle_seed=None,
                                 drop_last=False):
        toks = np.asarray(encode(jnp.asarray(batch["images"])), np.int32)
        token_chunks.append(toks)
        captions.extend(batch["captions"])
        n_done += toks.shape[0]
        if n_done % (args.batch_size * 10) < args.batch_size:
            print(f"  {n_done} done")

    image_tokens = np.concatenate(token_chunks, axis=0)
    np.savez_compressed(
        args.output,
        captions=np.array(captions),
        image_tokens=image_tokens,
        num_tokens=vae.num_tokens,
        image_size=vae.image_size,
        num_layers=vae.num_layers,
        vae_class_name=vae_class,
    )
    print(f"wrote {image_tokens.shape[0]} x {image_tokens.shape[1]} tokens "
          f"-> {args.output}")


if __name__ == "__main__":
    main()
