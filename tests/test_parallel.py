import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dalle_pytorch_tpu.parallel import (
    make_mesh,
    batch_sharding,
    partition_params,
    state_shardings,
    ring_attention,
)
from dalle_pytorch_tpu.parallel.ring import ring_attention_sharded
from dalle_pytorch_tpu.ops.attention_core import dense_attention


class TestMesh:
    def test_make_mesh_fills_dp(self):
        mesh = make_mesh(fsdp=2, tp=2)
        assert dict(mesh.shape) == {"dp": 2, "fsdp": 2, "tp": 2, "sp": 1}

    def test_make_mesh_all_axes(self):
        mesh = make_mesh(dp=1, fsdp=2, tp=2, sp=2)
        assert dict(mesh.shape) == {"dp": 1, "fsdp": 2, "tp": 2, "sp": 2}

    def test_bad_mesh_raises(self):
        with pytest.raises(AssertionError):
            make_mesh(dp=3, fsdp=3)


class TestPartition:
    def test_rules(self):
        from dalle_pytorch_tpu.models.dalle import DALLE

        model = DALLE(
            dim=32, depth=1, num_image_tokens=16, image_fmap_size=4,
            num_text_tokens=26, text_seq_len=6, heads=2, dim_head=8,
        )
        text = jnp.zeros((1, 6), jnp.int32)
        img = jnp.zeros((1, 16), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), text, img)["params"]
        mesh = make_mesh(dp=2, fsdp=2, tp=2)
        shardings = partition_params(params, mesh)

        flat = {
            "/".join(str(k.key) for k in path): s
            for path, s in jax.tree_util.tree_flatten_with_path(shardings)[0]
        }
        qkv = next(v for k, v in flat.items() if "to_qkv/kernel" in k)
        assert qkv.spec == P("fsdp", "tp")
        out = next(v for k, v in flat.items() if "to_out/kernel" in k)
        assert out.spec == P("tp", "fsdp")
        scale = next(v for k, v in flat.items() if "scale" in k)
        assert scale.spec == P()

    def test_nondivisible_dims_fall_back_to_replicated(self):
        mesh = make_mesh(dp=1, fsdp=4, tp=2)
        params = {"to_qkv": {"kernel": jnp.zeros((6, 10))}}  # 6 % 4 != 0
        sh = partition_params(params, mesh)
        assert sh["to_qkv"]["kernel"].spec == P(None, "tp")

    def test_scan_executor_stacked_kernels_shard(self):
        """Rank-3 (depth-stacked) scan-executor kernels must pick up the
        fsdp/tp specs with the depth axis unsharded — and a sharded train
        step must actually run on the virtual mesh."""
        from dalle_pytorch_tpu.models.dalle import DALLE
        from dalle_pytorch_tpu.training import (
            TrainState, make_optimizer, make_dalle_train_step,
        )

        model = DALLE(
            dim=32, depth=2, num_image_tokens=16, image_fmap_size=4,
            num_text_tokens=26, text_seq_len=6, heads=2, dim_head=8,
            executor="scan", fused_ce=True,
        )
        text = jnp.zeros((4, 6), jnp.int32)
        img = jnp.zeros((4, 16), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), text, img)["params"]
        mesh = make_mesh(dp=2, fsdp=2, tp=2)
        shardings = partition_params(params, mesh)
        flat = {
            "/".join(str(k.key) for k in path): s
            for path, s in jax.tree_util.tree_flatten_with_path(shardings)[0]
        }
        qkv = next(v for k, v in flat.items()
                   if "layers/attn/to_qkv/kernel" in k)
        assert qkv.spec == P(None, "fsdp", "tp")
        ff_up = next(v for k, v in flat.items()
                     if "layers/ff/Dense_0/kernel" in k)
        assert ff_up.spec == P(None, "fsdp", "tp")
        ff_down = next(v for k, v in flat.items()
                       if "layers/ff/Dense_1/kernel" in k)
        assert ff_down.spec == P(None, "tp", "fsdp")
        scales = next(v for k, v in flat.items() if "attn_scale_stack" in k)
        assert scales.spec == P()

        # one sharded train step end to end
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, s), params, shardings
        )
        state = TrainState.create(
            apply_fn=model.apply, params=params, tx=make_optimizer(1e-3),
        )
        from dalle_pytorch_tpu.parallel.mesh import batch_sharding
        from jax.sharding import NamedSharding

        bsh = batch_sharding(mesh)
        batch = {
            "text": jax.device_put(text, bsh),
            "image_tokens": jax.device_put(img, bsh),
        }
        step = jax.jit(make_dalle_train_step(model))
        with mesh:
            state2, metrics = step(state, batch, jax.random.PRNGKey(1))
        assert np.isfinite(float(metrics["loss"]))


class TestRingAttention:
    def test_matches_dense_causal(self):
        mesh = make_mesh(dp=1, sp=8)
        b, h, n, d = 2, 2, 32, 8
        rng = jax.random.PRNGKey(0)
        q, k, v = jax.random.normal(rng, (3, b, h, n, d))

        out_ring = ring_attention_sharded(mesh, q, k, v, causal=True)

        causal = jnp.tril(jnp.ones((n, n), bool))[None, None]
        out_dense = dense_attention(q, k, v, mask=causal)
        np.testing.assert_allclose(
            np.asarray(out_ring), np.asarray(out_dense), rtol=2e-4, atol=2e-5
        )

    def test_noncausal_matches_dense(self):
        mesh = make_mesh(dp=2, sp=4)
        q, k, v = jax.random.normal(jax.random.PRNGKey(1), (3, 2, 2, 16, 8))
        out_ring = ring_attention_sharded(mesh, q, k, v, causal=False)
        out_dense = dense_attention(q, k, v, mask=None)
        np.testing.assert_allclose(
            np.asarray(out_ring), np.asarray(out_dense), rtol=2e-4, atol=2e-5
        )


class TestShardedTrainStep:
    def test_sharded_step_matches_unsharded(self):
        """dp2 x fsdp2 x tp2 sharded step == single-device step, bitwise-ish.

        This is the real replacement for the reference's DummyBackend test
        seam: the same step function, sharded vs not, must agree.
        """
        from dalle_pytorch_tpu.models.dalle import DALLE
        from dalle_pytorch_tpu.training import TrainState, make_optimizer, make_dalle_train_step

        model = DALLE(
            dim=32, depth=2, num_image_tokens=16, image_fmap_size=4,
            num_text_tokens=26, text_seq_len=6, heads=2, dim_head=8,
        )
        text = jax.random.randint(jax.random.PRNGKey(0), (8, 6), 1, 26)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 16)
        batch = {"text": text, "image_tokens": tokens}
        params = model.init(jax.random.PRNGKey(2), text, tokens)["params"]
        tx = make_optimizer(1e-3, clip_grad_norm=0.5)
        state = TrainState.create(apply_fn=model.apply, params=params, tx=tx)
        step = make_dalle_train_step(model)
        rng = jax.random.PRNGKey(3)

        ref_state, ref_metrics = jax.jit(step)(state, batch, rng)

        mesh = make_mesh(dp=2, fsdp=2, tp=2)
        state_sh = state_shardings(state, mesh)
        bs = batch_sharding(mesh)
        batch_sh = {k: jax.device_put(v, bs) for k, v in batch.items()}
        sharded_state = jax.device_put(state, state_sh)
        sharded_step = jax.jit(
            step, in_shardings=(state_sh, {k: bs for k in batch}, None),
            out_shardings=(state_sh, None),
        )
        new_state, metrics = sharded_step(sharded_state, batch_sh, rng)

        np.testing.assert_allclose(
            float(metrics["loss"]), float(ref_metrics["loss"]), rtol=1e-5
        )
        for a, b in zip(
            jax.tree.leaves(ref_state.params), jax.tree.leaves(new_state.params)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            )


class TestRingInModel:
    """attn_impl="ring": sequence-parallel DALLE must match the dense model
    bit-for-bit in function value and gradients (long-context training path,
    beyond the reference's sparsity-only sequence scaling, SURVEY.md §5.7)."""

    def _models(self, mesh):
        from dalle_pytorch_tpu.models.dalle import DALLE

        kw = dict(
            dim=32, depth=2, heads=2, dim_head=16, num_image_tokens=32,
            image_fmap_size=4, num_text_tokens=30, text_seq_len=8,
            shift_tokens=True, rotary_emb=True,
        )
        dense = DALLE(attn_impl="dense", **kw)
        ring = DALLE(attn_impl="ring", sp_mesh=mesh, **kw)
        return dense, ring

    @pytest.mark.slow  # ~50 s: grads through the 8-way ring compile the
    # largest program in the suite (tier-1 budget); the cheaper ring
    # tests above keep the fast-tier parity signal
    def test_forward_and_grads_match_dense(self):
        mesh = make_mesh(dp=1, sp=8)
        dense, ring = self._models(mesh)
        text = jnp.asarray(
            np.random.RandomState(0).randint(1, 30, size=(2, 8)), jnp.int32
        )
        toks = jnp.asarray(
            np.random.RandomState(1).randint(0, 32, size=(2, 16)), jnp.int32
        )
        params = dense.init(jax.random.PRNGKey(0), text, toks)

        def loss(v, m):
            return m.apply(v, text, toks, return_loss=True)[0]

        l_dense = loss(params, dense)
        l_ring = loss(params, ring)
        np.testing.assert_allclose(
            float(l_dense), float(l_ring), rtol=2e-5
        )
        g_dense = jax.grad(loss)(params, dense)
        g_ring = jax.grad(loss)(params, ring)
        for a, b in zip(
            jax.tree_util.tree_leaves(g_dense), jax.tree_util.tree_leaves(g_ring)
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)

    def test_ring_requires_mesh(self):
        from dalle_pytorch_tpu.models.dalle import DALLE

        model = DALLE(
            dim=32, depth=1, heads=2, dim_head=16, num_image_tokens=32,
            image_fmap_size=4, num_text_tokens=30, text_seq_len=8,
            attn_impl="ring",
        )
        text = jnp.ones((1, 8), jnp.int32)
        toks = jnp.zeros((1, 16), jnp.int32)
        with pytest.raises(AssertionError, match="sp_mesh"):
            model.init(jax.random.PRNGKey(0), text, toks)


@pytest.mark.slow
class TestLongContextRing:
    """Long-context claim with substance: ring attention at seq 4096
    (4x the flagship's 1280) sharded over all 8 virtual devices, parity
    vs the dense oracle AND through a DALLE gradient step."""

    def test_seq4096_parity(self):
        mesh = make_mesh(dp=1, sp=8)
        b, h, n, d = 1, 2, 4096, 32
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(kk, (b, h, n, d)) * 0.5 for kk in ks)
        out_ring = ring_attention_sharded(mesh, q, k, v, causal=True)
        causal = jnp.tril(jnp.ones((n, n), bool))[None, None]
        out_dense = dense_attention(q, k, v, mask=causal)
        np.testing.assert_allclose(
            np.asarray(out_ring), np.asarray(out_dense), rtol=2e-3, atol=2e-4
        )

    def test_long_seq_train_step_grads_finite(self):
        from dalle_pytorch_tpu.models.dalle import DALLE
        from dalle_pytorch_tpu.training import (
            TrainState, make_optimizer, make_dalle_train_step,
        )

        mesh = make_mesh(dp=1, sp=8)
        # text 1024 + 32x32 image grid = seq 2048 over 8 sp shards
        model = DALLE(
            dim=64, depth=2, heads=4, dim_head=16, num_image_tokens=64,
            image_fmap_size=32, num_text_tokens=64, text_seq_len=1024,
            shift_tokens=True, rotary_emb=True,
            attn_impl="ring", sp_mesh=mesh,
        )
        text = jnp.ones((1, 1024), jnp.int32)
        tokens = jnp.zeros((1, 1024), jnp.int32)
        params = jax.jit(model.init)(jax.random.PRNGKey(0), text, tokens)["params"]
        state = TrainState.create(
            apply_fn=model.apply, params=params, tx=make_optimizer(1e-3)
        )
        step = jax.jit(make_dalle_train_step(model))
        state, metrics = step(
            state, {"text": text, "image_tokens": tokens}, jax.random.PRNGKey(1)
        )
        assert np.isfinite(float(metrics["loss"]))
