"""Pure-XLA VQGAN converter (models/vae_io.py `_VQGraph`) vs. a torch
golden model.

The reference drives taming-transformers VQGANs through torch
(`/root/reference/dalle_pytorch/vae.py:160-229`); our framework converts
the checkpoint into XLA-evaluated NHWC graphs. Since taming itself is not
installed, the test reconstructs the same architecture in torch (CPU) with
taming's exact state-dict naming, saves a synthetic checkpoint, and checks
encode indices + decode images agree between torch and XLA.
"""

import math
from pathlib import Path

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as nn
import torch.nn.functional as F

import yaml


# ---------------------------------------------------------------- torch golden

def swish(x):
    return x * torch.sigmoid(x)


class TResnet(nn.Module):
    def __init__(self, cin, cout):
        super().__init__()
        self.norm1 = nn.GroupNorm(32, cin, eps=1e-6)
        self.conv1 = nn.Conv2d(cin, cout, 3, padding=1)
        self.norm2 = nn.GroupNorm(32, cout, eps=1e-6)
        self.conv2 = nn.Conv2d(cout, cout, 3, padding=1)
        if cin != cout:
            self.nin_shortcut = nn.Conv2d(cin, cout, 1)

    def forward(self, x):
        h = self.conv1(swish(self.norm1(x)))
        h = self.conv2(swish(self.norm2(h)))
        if hasattr(self, "nin_shortcut"):
            x = self.nin_shortcut(x)
        return x + h


class TAttn(nn.Module):
    def __init__(self, c):
        super().__init__()
        self.norm = nn.GroupNorm(32, c, eps=1e-6)
        self.q = nn.Conv2d(c, c, 1)
        self.k = nn.Conv2d(c, c, 1)
        self.v = nn.Conv2d(c, c, 1)
        self.proj_out = nn.Conv2d(c, c, 1)

    def forward(self, x):
        b, c, hh, ww = x.shape
        h = self.norm(x)
        q = self.q(h).reshape(b, c, hh * ww).permute(0, 2, 1)
        k = self.k(h).reshape(b, c, hh * ww)
        v = self.v(h).reshape(b, c, hh * ww)
        attn = torch.softmax(torch.bmm(q, k) * (c ** -0.5), dim=-1)
        out = torch.bmm(v, attn.permute(0, 2, 1)).reshape(b, c, hh, ww)
        return x + self.proj_out(out)


class TDown(nn.Module):
    def __init__(self, c):
        super().__init__()
        self.conv = nn.Conv2d(c, c, 3, stride=2, padding=0)

    def forward(self, x):
        return self.conv(F.pad(x, (0, 1, 0, 1)))


class TUp(nn.Module):
    def __init__(self, c):
        super().__init__()
        self.conv = nn.Conv2d(c, c, 3, padding=1)

    def forward(self, x):
        return self.conv(F.interpolate(x, scale_factor=2.0, mode="nearest"))


DD = dict(
    resolution=16,
    in_channels=3,
    out_ch=3,
    ch=32,
    ch_mult=[1, 2],
    num_res_blocks=1,
    attn_resolutions=[8],
    z_channels=8,
)
N_EMBED, EMBED_DIM = 16, 8


class TVQGAN(nn.Module):
    """taming-layout VQModel with exactly matching state-dict keys."""

    def __init__(self, dd=None, n_embed=None, embed_dim=None):
        super().__init__()
        dd = dd or DD
        self.dd = dd
        self.n_embed = N_EMBED if n_embed is None else n_embed
        self.embed_dim = EMBED_DIM if embed_dim is None else embed_dim
        ch, mult = dd["ch"], dd["ch_mult"]
        chans = [ch * m for m in mult]

        enc = nn.Module()
        enc.conv_in = nn.Conv2d(3, ch, 3, padding=1)
        enc.down = nn.ModuleList()
        cin, res = ch, dd["resolution"]
        for i, cout in enumerate(chans):
            level = nn.Module()
            level.block = nn.ModuleList(
                [TResnet(cin if j == 0 else cout, cout)
                 for j in range(dd["num_res_blocks"])]
            )
            level.attn = nn.ModuleList(
                [TAttn(cout) for _ in range(dd["num_res_blocks"])]
                if res in dd["attn_resolutions"] else []
            )
            if i != len(chans) - 1:
                level.downsample = TDown(cout)
                res //= 2
            enc.down.append(level)
            cin = cout
        enc.mid = nn.Module()
        enc.mid.block_1 = TResnet(cin, cin)
        enc.mid.attn_1 = TAttn(cin)
        enc.mid.block_2 = TResnet(cin, cin)
        enc.norm_out = nn.GroupNorm(32, cin, eps=1e-6)
        enc.conv_out = nn.Conv2d(cin, dd["z_channels"], 3, padding=1)
        self.encoder = enc

        self.quant_conv = nn.Conv2d(dd["z_channels"], self.embed_dim, 1)
        quantize = nn.Module()
        quantize.embedding = nn.Embedding(self.n_embed, self.embed_dim)
        self.quantize = quantize
        self.post_quant_conv = nn.Conv2d(self.embed_dim, dd["z_channels"], 1)

        dec = nn.Module()
        dec.conv_in = nn.Conv2d(dd["z_channels"], chans[-1], 3, padding=1)
        dec.mid = nn.Module()
        dec.mid.block_1 = TResnet(chans[-1], chans[-1])
        dec.mid.attn_1 = TAttn(chans[-1])
        dec.mid.block_2 = TResnet(chans[-1], chans[-1])
        dec.up = nn.ModuleList()
        cin = chans[-1]
        res = dd["resolution"] // 2 ** (len(chans) - 1)
        ups = []
        for i in reversed(range(len(chans))):
            cout = chans[i]
            level = nn.Module()
            level.block = nn.ModuleList(
                [TResnet(cin if j == 0 else cout, cout)
                 for j in range(dd["num_res_blocks"] + 1)]
            )
            level.attn = nn.ModuleList(
                [TAttn(cout)] * 0 if res not in dd["attn_resolutions"]
                else [TAttn(cout) for _ in range(dd["num_res_blocks"] + 1)]
            )
            if i != 0:
                level.upsample = TUp(cout)
                res *= 2
            ups.insert(0, level)
            cin = cout
        for level in ups:
            dec.up.append(level)
        dec.norm_out = nn.GroupNorm(32, chans[0], eps=1e-6)
        dec.conv_out = nn.Conv2d(chans[0], 3, 3, padding=1)
        self.decoder = dec

    # ------------------------------------------------------------- paths

    def encode_indices(self, x):
        dd = self.dd
        h = self.encoder.conv_in(x)
        res = dd["resolution"]
        for i, level in enumerate(self.encoder.down):
            for j, blk in enumerate(level.block):
                h = blk(h)
                if len(level.attn):
                    h = level.attn[j](h)
            if hasattr(level, "downsample"):
                h = level.downsample(h)
                res //= 2
        h = self.encoder.mid.block_1(h)
        h = self.encoder.mid.attn_1(h)
        h = self.encoder.mid.block_2(h)
        h = self.encoder.conv_out(swish(self.encoder.norm_out(h)))
        z = self.quant_conv(h)
        b, c, hh, ww = z.shape
        flat = z.permute(0, 2, 3, 1).reshape(-1, c)
        emb = self.quantize.embedding.weight
        d = (
            flat.pow(2).sum(1, keepdim=True)
            - 2 * flat @ emb.t()
            + emb.pow(2).sum(1)[None]
        )
        return torch.argmin(d, dim=1).reshape(b, hh * ww)

    def decode_indices(self, indices):
        b, n = indices.shape
        hw = int(math.isqrt(n))
        z = self.quantize.embedding(indices).reshape(b, hw, hw, self.embed_dim)
        z = z.permute(0, 3, 1, 2)
        h = self.decoder.conv_in(self.post_quant_conv(z))
        h = self.decoder.mid.block_1(h)
        h = self.decoder.mid.attn_1(h)
        h = self.decoder.mid.block_2(h)
        for i in reversed(range(len(self.decoder.up))):
            level = self.decoder.up[i]
            for j, blk in enumerate(level.block):
                h = blk(h)
                if len(level.attn):
                    h = level.attn[j](h)
            if hasattr(level, "upsample"):
                h = level.upsample(h)
        h = self.decoder.conv_out(swish(self.decoder.norm_out(h)))
        return (h.clamp(-1, 1) + 1) * 0.5


# ------------------------------------------------------------------ fixtures


def make_taming_ckpt(d, seed=0):
    """Write a toy-geometry taming checkpoint + config into dir `d`;
    returns (torch model, ckpt path, config path). Shared with the CLI
    e2e taming flow (tests/test_e2e.py)."""
    torch.manual_seed(seed)
    model = TVQGAN().eval()
    torch.save({"state_dict": model.state_dict()}, d / "model.ckpt")
    config = {
        "model": {
            "target": "taming.models.vqgan.VQModel",
            "params": {"ddconfig": DD, "n_embed": N_EMBED, "embed_dim": EMBED_DIM},
        }
    }
    (d / "config.yaml").write_text(yaml.safe_dump(config))
    return model, d / "model.ckpt", d / "config.yaml"


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    d = tmp_path_factory.mktemp("vqgan")
    model, _, _ = make_taming_ckpt(d)
    return model, d


class TestVQGanVAE:
    def test_geometry(self, ckpt):
        from dalle_pytorch_tpu.models.vae_io import VQGanVAE

        _, d = ckpt
        vae = VQGanVAE(str(d / "model.ckpt"), str(d / "config.yaml"))
        assert vae.image_size == 16
        assert vae.num_layers == 1  # f = 2**(len(ch_mult)-1) = 2
        assert vae.num_tokens == N_EMBED
        assert not vae.is_gumbel

    def test_encode_matches_torch(self, ckpt):
        from dalle_pytorch_tpu.models.vae_io import VQGanVAE

        model, d = ckpt
        vae = VQGanVAE(str(d / "model.ckpt"), str(d / "config.yaml"))
        rng = np.random.RandomState(1)
        imgs = rng.rand(2, 16, 16, 3).astype(np.float32)  # NHWC in [0,1]
        ours = np.asarray(vae.get_codebook_indices(imgs))
        with torch.no_grad():
            theirs = model.encode_indices(
                torch.from_numpy(imgs).permute(0, 3, 1, 2) * 2 - 1
            ).numpy()
        assert ours.shape == theirs.shape == (2, 64)
        match = (ours == theirs).mean()
        assert match > 0.95, f"index agreement only {match}"  # float tol at argmin

    def test_decode_matches_torch(self, ckpt):
        from dalle_pytorch_tpu.models.vae_io import VQGanVAE

        model, d = ckpt
        vae = VQGanVAE(str(d / "model.ckpt"), str(d / "config.yaml"))
        rng = np.random.RandomState(2)
        indices = rng.randint(0, N_EMBED, size=(2, 64)).astype(np.int32)
        ours = np.asarray(vae.decode(indices))
        with torch.no_grad():
            theirs = (
                model.decode_indices(torch.from_numpy(indices).long())
                .permute(0, 2, 3, 1)
                .numpy()
            )
        assert ours.shape == theirs.shape == (2, 16, 16, 3)
        np.testing.assert_allclose(ours, theirs, atol=2e-4)

    def test_roundtrip_shapes_for_dalle(self, ckpt):
        from dalle_pytorch_tpu.models.vae_io import VQGanVAE

        _, d = ckpt
        vae = VQGanVAE(str(d / "model.ckpt"), str(d / "config.yaml"))
        imgs = np.zeros((1, 16, 16, 3), np.float32)
        toks = vae.get_codebook_indices(imgs)
        out = vae.decode(toks)
        fmap = vae.image_size // (2 ** vae.num_layers)
        assert toks.shape == (1, fmap * fmap)
        assert out.shape == (1, 16, 16, 3)
        assert np.asarray(out).min() >= 0 and np.asarray(out).max() <= 1

# ------------------------------------------------- released geometry (f/16)


REPO_CONFIG = (
    Path(__file__).parent.parent / "configs" / "vqgan_imagenet_f16_16384.yaml"
)


@pytest.mark.slow
class TestReleasedGeometry:
    """Structural golden at the published ImageNet f/16 16384-code geometry.

    The toy-geometry tests above prove the conversion math; this pins the
    importer to the exact released configuration (ch 128, ch_mult
    [1,1,2,2,4], 2 res blocks, attn at 16, z/embed 256, 16384 codes) using
    the committed `configs/vqgan_imagenet_f16_16384.yaml` — the config the
    real heibox checkpoint ships with — so any naming/structural mismatch
    our importer has against a real state dict fails here, not at load
    time on a user's machine. Real *weights* still cannot be validated in
    this egress-less environment (documented limitation, BASELINE.md);
    spatial extent is reduced to 64px (structure and state-dict keys are
    resolution-independent; attention placement follows the config's
    declared 256px schedule identically in both implementations).
    """

    @pytest.fixture(scope="class")
    def released(self, tmp_path_factory):
        config = yaml.safe_load(REPO_CONFIG.read_text())
        params = config["model"]["params"]
        torch.manual_seed(0)
        model = TVQGAN(
            dd=params["ddconfig"], n_embed=params["n_embed"],
            embed_dim=params["embed_dim"],
        ).eval()
        d = tmp_path_factory.mktemp("vqgan_f16")
        torch.save({"state_dict": model.state_dict()}, d / "model.ckpt")
        return model, d

    def test_geometry_from_committed_config(self, released):
        from dalle_pytorch_tpu.models.vae_io import VQGanVAE

        _, d = released
        vae = VQGanVAE(str(d / "model.ckpt"), str(REPO_CONFIG))
        assert vae.image_size == 256
        assert vae.num_layers == 4  # f/16
        assert vae.num_tokens == 16384
        assert not vae.is_gumbel
        assert vae.codebook.shape == (16384, 256)

    def test_released_state_dict_parity(self, released):
        from dalle_pytorch_tpu.models.vae_io import VQGanVAE

        model, d = released
        vae = VQGanVAE(str(d / "model.ckpt"), str(REPO_CONFIG))
        rng = np.random.RandomState(3)
        imgs = rng.rand(1, 64, 64, 3).astype(np.float32)
        ours = np.asarray(vae.get_codebook_indices(imgs))
        with torch.no_grad():
            theirs = model.encode_indices(
                torch.from_numpy(imgs).permute(0, 3, 1, 2) * 2 - 1
            ).numpy()
        assert ours.shape == theirs.shape == (1, 16)  # 64px / f16 = 4x4
        match = (ours == theirs).mean()
        assert match > 0.9, f"index agreement only {match}"

        indices = rng.randint(0, 16384, size=(1, 16)).astype(np.int32)
        dec_ours = np.asarray(vae.decode(indices))
        with torch.no_grad():
            dec_theirs = (
                model.decode_indices(torch.from_numpy(indices).long())
                .permute(0, 2, 3, 1).numpy()
            )
        assert dec_ours.shape == dec_theirs.shape == (1, 64, 64, 3)
        np.testing.assert_allclose(dec_ours, dec_theirs, atol=2e-3)
