import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dalle_pytorch_tpu.models.dvae import DiscreteVAE


def make_vae(**kw):
    defaults = dict(
        image_size=32, num_tokens=32, codebook_dim=16, num_layers=2, hidden_dim=8
    )
    defaults.update(kw)
    return DiscreteVAE(**defaults)


@pytest.fixture
def img():
    return jax.random.uniform(jax.random.PRNGKey(0), (2, 32, 32, 3))


class TestDiscreteVAE:
    def test_forward_recon_shape(self, img):
        vae = make_vae()
        variables = vae.init(
            {"params": jax.random.PRNGKey(0), "gumbel": jax.random.PRNGKey(1)}, img
        )
        out = vae.apply(variables, img, rngs={"gumbel": jax.random.PRNGKey(2)})
        assert out.shape == img.shape

    @pytest.mark.parametrize(
        "kw",
        [
            {},
            {"num_resnet_blocks": 1},
            {"straight_through": True},
            {"straight_through": True, "reinmax": True},
            {"smooth_l1_loss": True, "kl_div_loss_weight": 0.1},
        ],
    )
    def test_loss_and_grads_finite(self, img, kw):
        vae = make_vae(**kw)
        variables = vae.init(
            {"params": jax.random.PRNGKey(0), "gumbel": jax.random.PRNGKey(1)}, img
        )

        def loss_fn(params):
            return vae.apply(
                {"params": params},
                img,
                return_loss=True,
                rngs={"gumbel": jax.random.PRNGKey(2)},
            )

        loss, grads = jax.value_and_grad(loss_fn)(variables["params"])
        assert np.isfinite(float(loss))
        leaves = jax.tree.leaves(grads)
        assert all(np.all(np.isfinite(np.asarray(g))) for g in leaves)
        total = sum(float(jnp.abs(g).sum()) for g in leaves)
        assert total > 0

    def test_codebook_indices_and_decode_roundtrip(self, img):
        vae = make_vae()
        variables = vae.init(
            {"params": jax.random.PRNGKey(0), "gumbel": jax.random.PRNGKey(1)}, img
        )
        idx = vae.apply(variables, img, method=DiscreteVAE.get_codebook_indices)
        fmap = 32 // 4
        assert idx.shape == (2, fmap * fmap)
        assert idx.dtype == jnp.int32
        assert (np.asarray(idx) >= 0).all() and (np.asarray(idx) < 32).all()

        recon = vae.apply(variables, idx, method=DiscreteVAE.decode)
        assert recon.shape == img.shape

    def test_temp_argument(self, img):
        vae = make_vae()
        variables = vae.init(
            {"params": jax.random.PRNGKey(0), "gumbel": jax.random.PRNGKey(1)}, img
        )
        l1 = vae.apply(
            variables, img, return_loss=True, temp=5.0,
            rngs={"gumbel": jax.random.PRNGKey(2)},
        )
        l2 = vae.apply(
            variables, img, return_loss=True, temp=0.01,
            rngs={"gumbel": jax.random.PRNGKey(2)},
        )
        assert float(l1) != float(l2)

    def test_kl_matches_manual(self, img):
        """KL(q || uniform) with batchmean reduction, reference `:258-263`."""
        vae = make_vae(kl_div_loss_weight=1.0)
        variables = vae.init(
            {"params": jax.random.PRNGKey(0), "gumbel": jax.random.PRNGKey(1)}, img
        )
        logits = vae.apply(variables, img, return_logits=True)
        logits = np.asarray(logits, dtype=np.float64).reshape(2, -1, 32)
        q = np.exp(logits - logits.max(-1, keepdims=True))
        q /= q.sum(-1, keepdims=True)
        manual_kl = (q * (np.log(q) - np.log(1 / 32))).sum() / 2

        loss_with = vae.apply(
            variables, img, return_loss=True, rngs={"gumbel": jax.random.PRNGKey(2)}
        )
        vae0 = make_vae(kl_div_loss_weight=0.0)
        loss_without = vae0.apply(
            variables, img, return_loss=True, rngs={"gumbel": jax.random.PRNGKey(2)}
        )
        np.testing.assert_allclose(
            float(loss_with) - float(loss_without), manual_kl, rtol=1e-4
        )
