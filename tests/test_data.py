import io
import json
import os
import tarfile
from pathlib import Path

import numpy as np
import pytest

from dalle_pytorch_tpu.data.tokenizer import (
    ByteTokenizer,
    SimpleTokenizer,
    get_tokenizer,
)
from dalle_pytorch_tpu.data.rainbow import RainbowDataset, COLORS, SHAPES
from dalle_pytorch_tpu.data.loader import (
    TextImageDataset,
    ImageFolderDataset,
    MnistDataset,
    random_resized_crop,
)
from dalle_pytorch_tpu.data.webdataset import TarImageTextDataset, expand_shards


class TestByteTokenizer:
    def test_roundtrip(self):
        tok = ByteTokenizer()
        ids = tok.tokenize(["small orange circle", "big blue square"], 32)
        assert ids.shape == (2, 32)
        assert ids.dtype == np.int32
        assert (ids >= 0).all()
        assert tok.decode(ids[0]) == "small orange circle"

    def test_overflow_raises_unless_truncate(self):
        tok = ByteTokenizer()
        with pytest.raises(RuntimeError, match="too long"):
            tok.tokenize("a" * 100, 8)
        out = tok.tokenize("a" * 100, 8, truncate_text=True)
        assert out.shape == (1, 8)

    def test_zero_reserved_for_padding(self):
        tok = ByteTokenizer()
        ids = tok.tokenize("hi", 8)[0]
        assert ids[0] != 0 and ids[1] != 0 and (ids[2:] == 0).all()


class TestSimpleTokenizer:
    @pytest.fixture
    def bpe_file(self, tmp_path):
        # tiny CLIP-format merges file: header line + merges
        merges = ["#version: test", "h e", "l l", "he ll", "hell o</w>", "o k</w>"]
        p = tmp_path / "merges.txt"
        p.write_text("\n".join(merges))
        return p

    def test_encode_decode_roundtrip(self, bpe_file):
        tok = SimpleTokenizer(bpe_file)
        ids = tok.encode("hello ok")
        assert len(ids) > 0
        assert tok.decode(ids) == "hello ok"

    def test_merges_reduce_token_count(self, bpe_file):
        tok = SimpleTokenizer(bpe_file)
        # 'hello' fully merges via the chain -> single token
        assert len(tok.encode("hello")) == 1

    def test_vocab_layout(self, bpe_file):
        tok = SimpleTokenizer(bpe_file)
        assert tok.vocab_size == 512 + 5 + 2

    def test_get_tokenizer_dispatch(self, bpe_file):
        # no flags -> the shipped CLIP-scale 32k default vocab (8k is the
        # fallback when the 32k model is absent; no silent ByteTokenizer
        # degradation either way)
        from dalle_pytorch_tpu.data.tokenizer import NativeBPETokenizer

        default = get_tokenizer()
        assert isinstance(default, NativeBPETokenizer)
        assert default.vocab_size == 32768
        ids = default.tokenize("small red circle", context_length=8)
        assert default.decode(ids[0]) == "small red circle"
        assert isinstance(get_tokenizer(bpe_path=str(bpe_file)), SimpleTokenizer)

    def test_byte_fallback_warns(self, monkeypatch, tmp_path):
        """A missing default vocab must degrade LOUDLY, not silently."""
        import dalle_pytorch_tpu.data.tokenizer as tok

        # drop the process-wide probe cache (monkeypatch restores the real
        # decision afterwards, so later tests see the shipped vocab again)
        monkeypatch.setattr(tok, "_default_decision", None)
        monkeypatch.setattr(tok, "_warned_default_probe", False)
        monkeypatch.setattr(
            tok, "NativeBPETokenizer",
            type("Broken", (), {"__init__": lambda self, p: (_ for _ in ()).throw(OSError("no toolchain"))}),
        )
        with pytest.warns(UserWarning, match="ByteTokenizer"):
            assert isinstance(get_tokenizer(), ByteTokenizer)

    def test_byte_fallback_warns_once_per_process(self, monkeypatch):
        """The `default_bpe_*.model unusable` warning fires once: repeated
        default-tokenizer construction (trainer + generate CLI + serving
        engine in one process) reuses the cached probe decision silently."""
        import warnings as _warnings

        import dalle_pytorch_tpu.data.tokenizer as tok

        real = tok.NativeBPETokenizer
        monkeypatch.setattr(tok, "_default_decision", None)
        monkeypatch.setattr(tok, "_warned_default_probe", False)
        broken = type("Broken", (), {"__init__": lambda self, p: (_ for _ in ()).throw(OSError("no toolchain"))})
        monkeypatch.setattr(tok, "NativeBPETokenizer", broken)
        with pytest.warns(UserWarning):
            assert isinstance(get_tokenizer(), ByteTokenizer)
        with _warnings.catch_warnings():
            _warnings.simplefilter("error")  # a second warning would raise
            assert isinstance(get_tokenizer(), ByteTokenizer)
        # the fallback is a re-probe, not a latch: once the vocabulary
        # becomes usable the default tokenizer recovers mid-process
        monkeypatch.setattr(tok, "NativeBPETokenizer", real)
        recovered = get_tokenizer()
        assert not isinstance(recovered, ByteTokenizer)


class TestRainbow:
    def test_deterministic(self):
        d1 = RainbowDataset(num_samples=16, seed=3)
        d2 = RainbowDataset(num_samples=16, seed=3)
        np.testing.assert_array_equal(d1.image(5), d2.image(5))
        assert d1.caption(5) == d2.caption(5)

    def test_images_valid(self):
        ds = RainbowDataset(num_samples=8, image_size=32)
        for i in range(8):
            img = ds.image(i)
            assert img.shape == (32, 32, 3)
            assert img.min() >= 0 and img.max() <= 1
            assert img.max() > 0.25  # shape actually drawn (textures dim to 0.3)
            words = ds.caption(i).split()
            assert any(w in COLORS for w in words)
            assert any(w in SHAPES for w in words)

    def test_batches_sharded(self):
        ds = RainbowDataset(num_samples=32)
        tok = ByteTokenizer()
        b0 = list(ds.batches(4, tok, 24, shard=(0, 2)))
        b1 = list(ds.batches(4, tok, 24, shard=(1, 2)))
        assert len(b0) == len(b1) == 4
        assert b0[0]["images"].shape == (4, 32, 32, 3)
        assert b0[0]["text"].shape == (4, 24)
        assert not np.array_equal(b0[0]["images"], b1[0]["images"])


@pytest.fixture
def image_folder(tmp_path):
    from PIL import Image

    for cls, color in [("red_things", (255, 0, 0)), ("blue_things", (0, 0, 255))]:
        d = tmp_path / "train" / cls
        d.mkdir(parents=True)
        for i in range(3):
            Image.new("RGB", (40, 50), color).save(d / f"im{i}.png")
    # one paired-caption image
    cap = tmp_path / "train" / "red_things" / "special.png"
    Image.new("RGB", (40, 40), (255, 255, 0)).save(cap)
    cap.with_suffix(".txt").write_text("a special yellow image")
    return tmp_path / "train"


class TestFolderDataset:
    def test_captions_from_dirs_and_txt(self, image_folder):
        ds = ImageFolderDataset(str(image_folder))
        caps = {ds.get(i)[0] for i in range(len(ds))}
        assert "red things" in caps and "blue things" in caps
        assert "a special yellow image" in caps

    def test_class_name_json(self, image_folder, tmp_path):
        mapping = tmp_path / "map.json"
        mapping.write_text(json.dumps({"red_things": "crimson objects"}))
        ds = ImageFolderDataset(str(image_folder), class_name_json=str(mapping))
        caps = {ds.get(i)[0] for i in range(len(ds))}
        assert "crimson objects" in caps

    def test_imagenet_wnid_dirs_caption_out_of_the_box(self, tmp_path):
        # wnid-named class dirs resolve through the shipped
        # data/imagenet_classes.json with no --class_name_json flag
        # (reference vendors the same mapping, `loader.py:43-54`)
        from PIL import Image

        for wnid in ("n01440764", "n01443537"):
            d = tmp_path / wnid
            d.mkdir(parents=True)
            Image.new("RGB", (8, 8), (0, 128, 0)).save(d / "x.png")
        ds = ImageFolderDataset(str(tmp_path))
        caps = {ds.get(i)[0] for i in range(len(ds))}
        assert caps == {"tench", "goldfish"}

    def test_unknown_wnid_falls_back_to_dir_name(self, tmp_path):
        from PIL import Image

        d = tmp_path / "n99999999"
        d.mkdir(parents=True)
        Image.new("RGB", (8, 8), (0, 0, 0)).save(d / "x.png")
        ds = ImageFolderDataset(str(tmp_path))
        assert ds.get(0)[0] == "n99999999"

    def test_pipeline_batches(self, image_folder):
        ds = TextImageDataset(
            str(image_folder), text_len=16, image_size=32,
            truncate_captions=True,
        )
        batches = list(ds.batches(2, shuffle_seed=0))
        assert len(batches) == 3
        assert batches[0]["images"].shape == (2, 32, 32, 3)
        assert batches[0]["images"].dtype == np.float32
        assert batches[0]["text"].shape == (2, 16)

    def test_corrupt_image_fallback(self, image_folder):
        bad = image_folder / "red_things" / "corrupt.png"
        bad.write_bytes(b"not an image")
        ds = TextImageDataset(str(image_folder), text_len=8, image_size=16,
                              truncate_captions=True)
        # consuming every sample must not raise
        n = sum(b["text"].shape[0] for b in ds.batches(1, drop_last=False))
        assert n == len(ds)


class TestMnist:
    @pytest.fixture
    def mnist_dir(self, tmp_path):
        import struct

        imgs = np.random.RandomState(0).randint(0, 255, (4, 28, 28), np.uint8)
        lbls = np.asarray([0, 5, 9, 3], np.uint8)
        with open(tmp_path / "train-images-idx3-ubyte", "wb") as f:
            f.write(struct.pack(">IIII", 2051, 4, 28, 28))
            f.write(imgs.tobytes())
        with open(tmp_path / "train-labels-idx1-ubyte", "wb") as f:
            f.write(struct.pack(">II", 2049, 4))
            f.write(lbls.tobytes())
        return tmp_path

    def test_idx_loading(self, mnist_dir):
        ds = MnistDataset(str(mnist_dir), train=True)
        assert len(ds) == 4
        cap, img = ds.get(1)
        assert cap == "five"
        assert img.shape == (28, 28, 3)


class TestWebdataset:
    @pytest.fixture
    def tar_shards(self, tmp_path):
        from PIL import Image

        for s in range(2):
            with tarfile.open(tmp_path / f"shard-{s:04d}.tar", "w") as tar:
                for i in range(3):
                    key = f"sample{s}{i}"
                    buf = io.BytesIO()
                    Image.new("RGB", (32, 32), (s * 100, i * 50, 0)).save(
                        buf, format="JPEG"
                    )
                    data = buf.getvalue()
                    info = tarfile.TarInfo(f"{key}.jpg")
                    info.size = len(data)
                    tar.addfile(info, io.BytesIO(data))
                    txt = f"caption {s} {i}".encode()
                    info = tarfile.TarInfo(f"{key}.txt")
                    info.size = len(txt)
                    tar.addfile(info, io.BytesIO(txt))
        return tmp_path

    def test_brace_expansion(self):
        shards = expand_shards("shard-{0000..0003}.tar")
        assert shards == [f"shard-{i:04d}.tar" for i in range(4)]

    def test_iterates_pairs(self, tar_shards):
        ds = TarImageTextDataset(str(tar_shards), text_len=16, image_size=16)
        batches = list(ds.batches(3))
        assert len(batches) == 2
        assert batches[0]["images"].shape == (3, 16, 16, 3)
        assert batches[0]["text"].shape == (3, 16)

    def test_shard_split(self, tar_shards):
        ds = TarImageTextDataset(str(tar_shards), text_len=8, image_size=16)
        s0 = list(ds.samples(shard=(0, 2)))
        s1 = list(ds.samples(shard=(1, 2)))
        assert len(s0) == 3 and len(s1) == 3
        assert {c for c, _ in s0}.isdisjoint({c for c, _ in s1})

    def test_shuffle_seed_reshuffles_epochs(self, tar_shards):
        ds = TarImageTextDataset(
            str(tar_shards), text_len=8, image_size=16, shuffle_buffer=4
        )
        base = [c for c, _ in ds.samples()]
        e0 = [c for c, _ in ds.samples(shuffle_seed=0)]
        e0_again = [c for c, _ in ds.samples(shuffle_seed=0)]
        e1 = [c for c, _ in ds.samples(shuffle_seed=1)]
        assert sorted(e0) == sorted(base)  # a permutation, nothing dropped
        assert e0 == e0_again  # deterministic per seed
        assert e0 != e1 or e0 != base  # epochs actually reshuffle

    def test_missing_caption_filtered(self, tmp_path):
        from PIL import Image

        with tarfile.open(tmp_path / "solo.tar", "w") as tar:
            buf = io.BytesIO()
            Image.new("RGB", (8, 8)).save(buf, format="JPEG")
            data = buf.getvalue()
            info = tarfile.TarInfo("orphan.jpg")
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
        ds = TarImageTextDataset(str(tmp_path / "solo.tar"))
        assert list(ds.samples()) == []


class TestCrop:
    def test_random_resized_crop_shape_and_range(self):
        rng = np.random.RandomState(0)
        img = np.random.randint(0, 255, (50, 70, 3), np.uint8)
        out = random_resized_crop(img, 32, rng)
        assert out.shape == (32, 32, 3)
        assert 0.0 <= out.min() and out.max() <= 1.0


class TestTokenDataset:
    """Offline token precompute (precompute_tokens.py + TokenDataset) — the
    offline counterpart of the in-forward frozen-VAE encode
    (`dalle_pytorch.py:619-627`)."""

    def test_roundtrip(self, tmp_path):
        import subprocess, sys, os
        from pathlib import Path

        repo = Path(__file__).resolve().parents[1]
        env = {**os.environ, "PYTHONPATH": str(repo),
               "DALLE_TPU_FORCE_PLATFORM": "cpu"}

        # tiny dVAE checkpoint
        import jax, jax.numpy as jnp
        from dalle_pytorch_tpu.models.dvae import DiscreteVAE
        from dalle_pytorch_tpu.training.pipeline import save_vae_checkpoint

        vae = DiscreteVAE(image_size=16, num_layers=2, num_tokens=32,
                          codebook_dim=16, hidden_dim=16)
        params = vae.init(
            {"params": jax.random.PRNGKey(0), "gumbel": jax.random.PRNGKey(1)},
            jnp.zeros((1, 16, 16, 3)),
        )["params"]
        save_vae_checkpoint(str(tmp_path / "vae.npz"), vae, params)

        out = subprocess.run(
            [sys.executable, str(repo / "precompute_tokens.py"),
             "--image_text_folder", "rainbow:20",
             "--vae_path", str(tmp_path / "vae.npz"),
             "--batch_size", "8", "--output", str(tmp_path / "tok.npz")],
            capture_output=True, text=True, timeout=600, env=env, cwd=tmp_path,
        )
        assert out.returncode == 0, out.stdout + out.stderr

        from dalle_pytorch_tpu.data.loader import TokenDataset
        from dalle_pytorch_tpu.data.tokenizer import ByteTokenizer

        ds = TokenDataset(tmp_path / "tok.npz", ByteTokenizer(), text_len=16)
        assert len(ds) == 20  # drop_last=False keeps the ragged tail
        assert ds.num_tokens == 32 and ds.image_size == 16
        batches = list(ds.batches(8, shuffle_seed=0))
        assert len(batches) == 2  # 20 // 8 full batches
        b = batches[0]
        assert b["text"].shape == (8, 16)
        assert b["image_tokens"].shape == (8, 16)  # 4x4 fmap
        assert b["image_tokens"].dtype == np.int32
        # captions roundtrip through the tokenizer
        text = ByteTokenizer().decode(b["text"][0])
        # text_len=16 may truncate the shape word; size words survive
        from dalle_pytorch_tpu.data.rainbow import SIZES

        assert any(text.startswith(w) for w in SIZES)
