import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dalle_pytorch_tpu.models.dvae import DiscreteVAE
from dalle_pytorch_tpu.models.dalle import DALLE
from dalle_pytorch_tpu.training import (
    TrainState,
    make_optimizer,
    make_vae_train_step,
    make_dalle_train_step,
    set_learning_rate,
    get_learning_rate,
    ReduceLROnPlateau,
    ExponentialDecay,
)


def small_dalle():
    return DALLE(
        dim=32, depth=1, num_image_tokens=16, image_fmap_size=4,
        num_text_tokens=26, text_seq_len=6, heads=2, dim_head=8,
    )


def dalle_state(model, batch):
    params = model.init(
        jax.random.PRNGKey(0), batch["text"], batch["image_tokens"]
    )["params"]
    return TrainState.create(
        apply_fn=model.apply, params=params, tx=make_optimizer(1e-3, 0.5)
    )


@pytest.fixture
def batch():
    return {
        "text": jax.random.randint(jax.random.PRNGKey(0), (4, 6), 1, 26),
        "image_tokens": jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 16),
    }


class TestVaeStep:
    def test_loss_decreases(self):
        vae = DiscreteVAE(
            image_size=16, num_tokens=16, codebook_dim=16, num_layers=1,
            hidden_dim=16, straight_through=False,
        )
        img = jax.random.uniform(jax.random.PRNGKey(0), (4, 16, 16, 3))
        params = vae.init(
            {"params": jax.random.PRNGKey(0), "gumbel": jax.random.PRNGKey(1)}, img
        )["params"]
        state = TrainState.create(
            apply_fn=vae.apply, params=params, tx=make_optimizer(3e-3)
        )
        step = jax.jit(make_vae_train_step(vae))
        rng = jax.random.PRNGKey(2)
        first = last = None
        for i in range(30):
            rng, r = jax.random.split(rng)
            state, metrics = step(state, img, r, jnp.float32(0.9))
            if first is None:
                first = float(metrics["loss"])
            last = float(metrics["loss"])
        assert last < first

    def test_grad_accum_equivalence(self):
        vae = DiscreteVAE(
            image_size=16, num_tokens=8, codebook_dim=8, num_layers=1,
            hidden_dim=8, straight_through=False, temperature=1.0,
        )
        img = jax.random.uniform(jax.random.PRNGKey(0), (4, 16, 16, 3))
        params = vae.init(
            {"params": jax.random.PRNGKey(0), "gumbel": jax.random.PRNGKey(1)}, img
        )["params"]

        # identical halves => accumulated grads == single-batch grads
        img2 = jnp.concatenate([img[:2], img[:2]])
        state = TrainState.create(
            apply_fn=vae.apply, params=params, tx=make_optimizer(1e-3)
        )
        rng = jax.random.PRNGKey(5)
        s1, m1 = jax.jit(make_vae_train_step(vae, grad_accum=2))(
            state, img2, rng, jnp.float32(1.0)
        )
        # gumbel rngs differ between microbatches, so compare only finiteness
        assert np.isfinite(float(m1["loss"]))


class TestDalleStep:
    @pytest.mark.parametrize(
        "mode", ["forward_only", "forward_forward", "forward_reverse_partial", "reverse_only"]
    )
    def test_modes(self, batch, mode):
        model = small_dalle()
        state = dalle_state(model, batch)
        step = jax.jit(make_dalle_train_step(model, mode=mode))
        new_state, metrics = step(state, batch, jax.random.PRNGKey(0))
        assert np.isfinite(float(metrics["loss"]))
        if mode != "forward_only":
            assert "accuracy" in metrics
        if mode == "forward_forward":
            np.testing.assert_allclose(
                float(metrics["loss"]),
                float(metrics["forward_loss"]) + float(metrics["inverse_loss"]),
                rtol=1e-5,
            )
        assert int(new_state.step) == 1

    def test_in_step_vae_encode(self):
        """Frozen-VAE encode fused into the train step (ref `:619-627`)."""
        vae = DiscreteVAE(
            image_size=16, num_tokens=16, codebook_dim=8, num_layers=2, hidden_dim=8
        )
        img = jax.random.uniform(jax.random.PRNGKey(0), (4, 16, 16, 3))
        vae_params = vae.init(
            {"params": jax.random.PRNGKey(0), "gumbel": jax.random.PRNGKey(1)}, img
        )["params"]
        model = small_dalle()
        text = jax.random.randint(jax.random.PRNGKey(0), (4, 6), 1, 26)
        tok_probe = vae.apply(
            {"params": vae_params}, img, method=DiscreteVAE.get_codebook_indices
        )
        params = model.init(jax.random.PRNGKey(2), text, tok_probe)["params"]
        state = TrainState.create(
            apply_fn=model.apply, params=params, tx=make_optimizer(1e-3)
        )
        step = jax.jit(make_dalle_train_step(model, vae=vae))
        new_state, metrics = step(
            state, {"text": text, "images": img}, jax.random.PRNGKey(3),
            vae_params=vae_params,
        )
        assert np.isfinite(float(metrics["loss"]))

    @pytest.mark.parametrize("grad_accum,n_steps", [(1, 3), (2, 2)])
    def test_multi_step_matches_sequential(self, batch, grad_accum, n_steps):
        """One make_multi_step dispatch == n sequential step dispatches,
        bit-compatible params and per-key RNG stream (the trainer's
        fold_in(rng, global_step) keys are passed stacked). grad_accum=2
        covers the nested-scan combination the bench's OOM ladder
        produces on hardware."""
        from dalle_pytorch_tpu.training import make_multi_step, stack_batches

        model = small_dalle()
        state = dalle_state(model, batch)
        step = make_dalle_train_step(model, grad_accum=grad_accum)
        rng = jax.random.PRNGKey(7)
        keys = jnp.stack([jax.random.fold_in(rng, i) for i in range(n_steps)])

        seq_state = state
        losses = []
        jstep = jax.jit(step)
        for i in range(n_steps):
            seq_state, m = jstep(seq_state, batch, keys[i])
            losses.append(float(m["loss"]))

        batches = stack_batches([batch] * n_steps)
        multi = jax.jit(make_multi_step(step, n_steps))
        multi_state, mm = multi(state, batches, keys)

        assert int(multi_state.step) == n_steps
        np.testing.assert_allclose(
            float(mm["loss"]), np.mean(losses), rtol=1e-5
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5
            ),
            multi_state.params, seq_state.params,
        )

    def test_grad_accum_matches_full_batch(self, batch):
        model = small_dalle()
        state = dalle_state(model, batch)
        rng = jax.random.PRNGKey(0)
        _, m_full = jax.jit(make_dalle_train_step(model))(state, batch, rng)
        _, m_acc = jax.jit(make_dalle_train_step(model, grad_accum=2))(
            state, batch, rng
        )
        np.testing.assert_allclose(
            float(m_full["loss"]), float(m_acc["loss"]), rtol=1e-4
        )


class TestThroughputMeter:
    def test_stride_never_hits_exact_multiple(self, monkeypatch):
        """steps_per_dispatch strides (3,6,8,11,...) never land on a
        multiple of 10; the meter must still initialize and fire on
        interval crossings, scaling by the true step delta."""
        from dalle_pytorch_tpu.training.metrics import ThroughputMeter

        t = [100.0]
        monkeypatch.setattr(
            "dalle_pytorch_tpu.training.metrics.time",
            type("T", (), {"time": staticmethod(lambda: t[0])}),
        )
        meter = ThroughputMeter(interval=10)
        assert meter.update(3, batch_size=8) is None  # initializes here
        t[0] += 1.0
        assert meter.update(6, 8) is None
        t[0] += 1.0
        rate = meter.update(11, 8)  # crosses 10
        # 8 samples/step * (11-3) steps over 2.0s
        assert rate == pytest.approx(8 * 8 / 2.0)
        t[0] += 4.0
        assert meter.update(14, 8) is None
        assert meter.update(21, 8) == pytest.approx(8 * 10 / 4.0)

    def test_stride_one_matches_classic_cadence(self, monkeypatch):
        from dalle_pytorch_tpu.training.metrics import ThroughputMeter

        t = [0.0]
        monkeypatch.setattr(
            "dalle_pytorch_tpu.training.metrics.time",
            type("T", (), {"time": staticmethod(lambda: t[0])}),
        )
        meter = ThroughputMeter(interval=10)
        fired = []
        for step in range(1, 31):
            t[0] += 0.5
            r = meter.update(step, 4)
            if r is not None:
                fired.append((step, r))
        assert [s for s, _ in fired] == [10, 20, 30]
        # 9 steps over 4.5s for the first window, then exactly 10/5.0
        assert fired[0][1] == pytest.approx(4 * 9 / 4.5)
        assert fired[1][1] == pytest.approx(4 * 10 / 5.0)


class TestProfilerHook:
    def test_stride_skips_exact_step(self, monkeypatch, tmp_path):
        """steps_per_dispatch can step OVER profile_step; the hook must
        trace the first dispatch at/after it and only then stop training
        (previously it stopped without ever tracing)."""
        from dalle_pytorch_tpu.training.metrics import ProfilerHook

        calls = []
        monkeypatch.setattr(
            "dalle_pytorch_tpu.training.metrics.jax.profiler",
            type("P", (), {
                "start_trace": staticmethod(lambda d: calls.append(("start", d))),
                "stop_trace": staticmethod(lambda: calls.append(("stop",))),
            }),
        )
        hook = ProfilerHook(True, profile_step=200, out_dir=str(tmp_path / "p"))
        # stride-3 window sequence around 200: 198 -> 201 -> 204
        hook.before_step(198)
        assert not calls and hook.after_step(201) is False
        hook.before_step(201)
        assert calls == [("start", str(tmp_path / "p"))]
        assert hook.after_step(204) is True  # traced, now stop
        assert calls[-1] == ("stop",)
        hook.before_step(204)  # must not restart
        assert len(calls) == 2


class TestLRControl:
    def test_set_get_lr(self, batch):
        model = small_dalle()
        state = dalle_state(model, batch)
        assert get_learning_rate(state) == pytest.approx(1e-3)
        state = set_learning_rate(state, 5e-4)
        assert get_learning_rate(state) == pytest.approx(5e-4)
        # the new lr is actually used by the next update
        step = jax.jit(make_dalle_train_step(model))
        new_state, _ = step(state, batch, jax.random.PRNGKey(0))
        assert get_learning_rate(new_state) == pytest.approx(5e-4)

    def test_plateau_reduces_after_patience(self):
        sched = ReduceLROnPlateau(factor=0.5, patience=2, cooldown=1, min_lr=1e-6)
        lr = 1.0
        lr = sched.step(1.0, lr)  # best
        for _ in range(3):
            lr = sched.step(2.0, lr)  # bad x3 > patience
        assert lr == pytest.approx(0.5)
        lr2 = sched.step(2.0, lr)  # cooldown swallows one bad epoch
        assert lr2 == pytest.approx(0.5)

    def test_exponential(self):
        sched = ExponentialDecay(gamma=0.5)
        assert sched.step(0.0, 1.0) == pytest.approx(0.5)


class TestFullStateResume:
    def test_resume_matches_uninterrupted_run(self, batch, tmp_path):
        """train(2N) == train(N) -> save -> load -> train(N): the loss
        trajectory must be identical, proving Adam moments + injected lr
        + step counter survive the checkpoint round trip (the reference's
        opt/scheduler reload, `/root/reference/train_dalle.py:330-338`)."""
        from dalle_pytorch_tpu.training.config import TrainConfig
        from dalle_pytorch_tpu.training.pipeline import (
            save_dalle_checkpoint,
            load_dalle_checkpoint,
            restore_opt_state,
        )

        model = small_dalle()
        step = jax.jit(make_dalle_train_step(model))

        def run(state, start, n):
            losses = []
            for i in range(start, start + n):
                state, metrics = step(state, batch, jax.random.PRNGKey(100 + i))
                losses.append(float(metrics["loss"]))
            return state, losses

        # uninterrupted: 4 steps
        state_a, losses_a = run(dalle_state(model, batch), 0, 4)

        # interrupted: 2 steps, checkpoint, reload, 2 more
        state_b, losses_b1 = run(dalle_state(model, batch), 0, 2)
        ckpt = tmp_path / "dalle.npz"
        save_dalle_checkpoint(
            str(ckpt), TrainConfig(), jax.device_get(state_b.params), None,
            epoch=0, vae_class_name="DiscreteVAE",
            opt_state=jax.device_get(state_b.opt_state),
            train_meta={"global_step": 2},
        )
        _, params, _, meta, opt_leaves = load_dalle_checkpoint(str(ckpt))
        fresh = TrainState.create(
            apply_fn=model.apply, params=params, tx=make_optimizer(1e-3, 0.5)
        )
        resumed = fresh.replace(
            opt_state=restore_opt_state(fresh.opt_state, opt_leaves),
            step=int(meta["train"]["global_step"]),
        )
        _, losses_b2 = run(resumed, 2, 2)

        np.testing.assert_allclose(losses_a, losses_b1 + losses_b2, rtol=1e-5)

    def test_restore_opt_state_mismatch_falls_back(self, batch):
        from dalle_pytorch_tpu.training.pipeline import restore_opt_state

        model = small_dalle()
        state = dalle_state(model, batch)
        leaves = [np.zeros((2, 2))] * 3  # wrong length/shapes
        restored = restore_opt_state(state.opt_state, leaves)
        assert jax.tree_util.tree_structure(restored) == jax.tree_util.tree_structure(
            state.opt_state
        )
