"""Golden learning-signal integration test (SURVEY.md §4d).

Mirrors the reference's de-facto integration bar — the rainbow notebook's
exact image-token-sequence accuracy (`examples/rainbow_dalle.ipynb` cells
43-44: 1.0 train at convergence) — at a scale small enough for CI: overfit
16 samples and require near-perfect exact-match accuracy plus a genuinely
trained (non-collapsed) dVAE.
"""

import os
import re
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
ENV = {**os.environ, "PYTHONPATH": f"{REPO}:{os.environ.get('PYTHONPATH', '')}"}


class TestRainbowConvergence:
    @pytest.mark.slow  # ~200 s of real training: a quarter of the fast
    # tier's whole time budget for one test — it belongs with the other
    # long-running integration tests (same tier as the serve-CLI e2e)
    def test_overfit_reaches_exact_accuracy(self, tmp_path):
        result = subprocess.run(
            [
                sys.executable, str(REPO / "examples" / "rainbow_dalle.py"),
                "--num-samples", "16", "--train-frac", "1.0",
                "--image-size", "16", "--batch-size", "16",
                "--vae-steps", "250", "--dalle-steps", "250",
                "--eval-samples", "16", "--out-dir", str(tmp_path), "--cpu",
            ],
            capture_output=True, text=True, timeout=1200, cwd=tmp_path, env=ENV,
        )
        assert result.returncode == 0, (
            f"example failed:\n{result.stdout[-2000:]}\n{result.stderr[-2000:]}"
        )
        out = result.stdout

        m = re.search(r"hard-recon MSE: ([\d.]+); codebook usage: (\d+)/", out)
        assert m, f"no recon line in:\n{out}"
        mse, usage = float(m.group(1)), int(m.group(2))
        assert mse < 0.05, f"dVAE failed to reconstruct (MSE {mse})"
        assert usage >= 2, f"dVAE codebook collapsed ({usage} codes)"

        m = re.search(r"train: exact ([\d.]+), per-token ([\d.]+)", out)
        assert m, f"no accuracy line in:\n{out}"
        exact, per_tok = float(m.group(1)), float(m.group(2))
        assert per_tok > 0.95, f"per-token accuracy only {per_tok}"
        assert exact >= 0.9, f"exact-sequence accuracy only {exact}"

        assert (tmp_path / "generated.png").exists()
