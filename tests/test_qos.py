"""QoS serving under overload: priority admission, preemption, recovery.

The load-bearing contracts, in order of consequence:

  * PREEMPTION IS LATENCY, NEVER CORRECTNESS — a request suspended at a
    chunk boundary and resumed later returns tokens BIT-IDENTICAL to the
    un-preempted run, because decode RNG is (seed, image-position)-keyed
    and the re-admitted row restarts at position 0 (the same determinism
    decode-composition invariance pins in tests/test_continuous.py).
  * the weighted-fair scheduler BOUNDS starvation — a saturating
    low-class flood cannot push the high/normal classes' admission share
    below their weight ratio, and the low class itself is never starved
    outright.
  * RECOVERY LEAKS NOTHING — a dispatch failure mid-wave (injected
    deterministically via `serving/faults.py`) rebuilds engine state,
    leaves the block pool / prefix cache / slot allocator consistent
    (`PagedKVManager.leak_check`), and the suspended requests' bounded
    retry still produces bit-identical tokens.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dalle_pytorch_tpu.models.dalle import DALLE
from dalle_pytorch_tpu.obs.tracing import Tracer
from dalle_pytorch_tpu.serving.batcher import (
    ContinuousBatcher,
    QueueFullError,
    RequestCancelled,
    RequestTimeout,
)
from dalle_pytorch_tpu.serving.engine import (
    ContinuousEngine,
    PagedContinuousEngine,
    SampleSpec,
)
from dalle_pytorch_tpu.serving.faults import FaultInjector, InjectedFault
from dalle_pytorch_tpu.serving.paging import PagedKVManager
from dalle_pytorch_tpu.serving.qos import (
    ShedError,
    TenantQuotaError,
    WeightedFairQueue,
    priority_class,
)
from dalle_pytorch_tpu.serving.server import ServingServer
from dalle_pytorch_tpu.training.metrics import MetricsRegistry

from test_continuous import FakeContinuousEngine

TEXT_SEQ = 8
FMAP = 4
IMG_SEQ = FMAP * FMAP


# ------------------------------------------------------ weighted-fair queue


class _R:
    """Minimal request double for scheduler unit tests."""

    def __init__(self, name, priority="normal", tenant="", rows=1):
        self.name = name
        self.klass = priority_class(priority)
        self.tenant = tenant
        self.pending_rows = rows
        self.enqueued_at = time.monotonic()

    def __repr__(self):
        return f"_R({self.name})"


class TestWeightedFairQueue:
    def test_single_class_single_tenant_is_fifo(self):
        q = WeightedFairQueue()
        reqs = [_R(i) for i in range(5)]
        for r in reqs:
            q.push(r)
        assert [q.pop().name for _ in range(5)] == [0, 1, 2, 3, 4]

    def test_class_shares_follow_weights(self):
        """Backlogged high vs low: admissions split ~8:1 (the default
        weights), so low is throttled but NEVER starved — the stride
        scheduler's bound, pinned as 'at most 9 pops between low pops'."""
        q = WeightedFairQueue()
        for i in range(100):
            q.push(_R(f"h{i}", "high"))
            q.push(_R(f"l{i}", "low"))
        popped = [q.pop().name for _ in range(90)]
        lows = [i for i, n in enumerate(popped) if n.startswith("l")]
        assert 8 <= len(lows) <= 12, popped
        gaps = np.diff([-1] + lows)
        assert gaps.max() <= 9, "low class starved past the weight bound"

    def test_tenant_fairness_within_class(self):
        """One tenant flooding a class cannot starve another tenant in
        the same class: service alternates while both are backlogged."""
        q = WeightedFairQueue()
        for i in range(20):
            q.push(_R(f"a{i}", "low", tenant="a"))
        for i in range(3):
            q.push(_R(f"b{i}", "low", tenant="b"))
        popped = [q.pop().name for _ in range(6)]
        assert popped[0][0] == "a"  # a was first in, ties break stably
        # b's three requests all surface within the first six pops
        assert sum(1 for n in popped if n.startswith("b")) == 3

    def test_push_front_resumes_next_in_its_queue(self):
        q = WeightedFairQueue()
        a, b, c = _R("a"), _R("b"), _R("c")
        q.push(a)
        q.push(b)
        q.push_front(c)
        assert q.pop() is c

    def test_uncharged_pop_keeps_shares(self):
        q = WeightedFairQueue()
        q.push(_R("x", "low"))
        before = list(q._class_served)
        q.pop(charge=False)  # cancelled/expired: consumed nothing
        assert q._class_served == before

    def test_idle_class_banks_no_credit(self):
        """Reactivation clamp: a class that sat idle while another was
        served re-enters at the CURRENT minimum ratio — a low burst after
        a long high-only period gets its fair share, not a priority
        inversion worth the whole idle span."""
        q = WeightedFairQueue()
        for i in range(100):
            q.push(_R(f"h{i}", "high"))
        for _ in range(50):  # high-only service: high banks ratio 6.25
            q.pop()
        for i in range(10):  # low reactivates from empty
            q.push(_R(f"l{i}", "low"))
        popped = [q.pop().name for _ in range(18)]
        lows = sum(1 for n in popped if n.startswith("l"))
        assert lows <= 3, (
            f"stale credit let low run ahead of high: {popped}"
        )
        assert popped[0].startswith("h"), "tie must break to the better class"

    def test_rows_accounting(self):
        q = WeightedFairQueue()
        q.push(_R("a", "high", tenant="t", rows=2))
        q.push(_R("b", "low", tenant="t", rows=3))
        q.push(_R("c", "normal", rows=1))
        assert q.rows == 6
        assert q.tenant_rows("t") == 5
        assert q.class_depths() == {"high": 2, "normal": 1, "low": 3}
        assert q.rows_at_or_better(priority_class("high")) == 2
        assert q.rows_at_or_better(priority_class("normal")) == 3
        assert q.rows_at_or_better(priority_class("low")) == 6
        assert q.oldest_enqueued_at() is not None
        q.pop()
        q.pop()
        q.pop()
        assert q.rows == 0 and q.tenant_rows("t") == 0


# --------------------------------------------------- fake-engine QoS policy


class TestTenantWeights:
    """Per-tenant weighted shares (ROADMAP §5 follow-on): fairness within
    a class is proportional to configured weights, not equal."""

    def test_weights_split_service_proportionally(self):
        q = WeightedFairQueue(tenant_weights={"a": 4.0, "b": 1.0})
        for i in range(30):
            q.push(_R(f"a{i}", tenant="a"))
            q.push(_R(f"b{i}", tenant="b"))
        served = {"a": 0, "b": 0}
        for _ in range(25):
            served[q.pop().tenant] += 1
        # stride scheduling over rows_served/weight: a backlogged 4:1
        # pair splits admissions exactly 4:1
        assert served == {"a": 20, "b": 5}

    def test_unlisted_tenants_weigh_one(self):
        q = WeightedFairQueue(tenant_weights={"vip": 2.0})
        for i in range(20):
            q.push(_R(f"v{i}", tenant="vip"))
            q.push(_R(f"p{i}", tenant="pleb"))
        served = {"vip": 0, "pleb": 0}
        for _ in range(12):
            served[q.pop().tenant] += 1
        assert served == {"vip": 8, "pleb": 4}

    def test_idle_weighted_tenant_banks_no_credit(self):
        """The reactivation clamp scales by weight: a weight-4 tenant
        that sat idle re-enters at the current minimum RATIO (not raw
        rows), so it gets its 4:1 share from now on — not a catch-up
        burst for the idle period."""
        q = WeightedFairQueue(tenant_weights={"a": 4.0})
        for i in range(30):
            q.push(_R(f"b{i}", tenant="b"))
        for _ in range(20):
            q.pop()
        for i in range(30):
            q.push(_R(f"a{i}", tenant="a"))
        wins = {"a": 0, "b": 0}
        for _ in range(10):
            wins[q.pop().tenant] += 1
        assert wins == {"a": 8, "b": 2}

    def test_nonpositive_weight_rejected(self):
        with pytest.raises(AssertionError):
            WeightedFairQueue(tenant_weights={"a": 0.0})


class StepEngine(FakeContinuousEngine):
    """FakeContinuousEngine whose chunk boundary advances only when the
    test releases a permit — deterministic stepping for policy tests."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.step_sem = threading.Semaphore(0)

    def step_chunk(self):
        self.chunk_entered.set()
        assert self.step_sem.acquire(timeout=10), "no permit released"
        return super().step_chunk()


def _step(eng, n=1):
    """Release n chunk boundaries; returns once the worker is parked at
    the NEXT boundary entry (all admission/retire/reap/preempt work of
    the released boundaries is then complete)."""
    for _ in range(n):
        eng.chunk_entered.clear()
        eng.step_sem.release()
        assert eng.chunk_entered.wait(10)


def _until(eng, cond, max_steps=64):
    """Step boundaries until `cond()` holds (worker must be parked at a
    chunk entry, i.e. after a chunk_entered wait) — absorbs the race
    between test submissions and the worker's admission waves."""
    for _ in range(max_steps):
        if cond():
            return
        _step(eng)
    assert cond(), "condition never reached within the step budget"


def _finish(eng, reqs, timeout=20.0):
    """Drain: keep releasing boundaries until every request resolved.
    Permit-release + poll rather than `_step`: after the LAST retirement
    the worker parks idle in cond.wait and never re-enters a chunk, so
    waiting on chunk entry would hang exactly at the finish line."""
    deadline = time.monotonic() + timeout
    while not all(r.future.done() for r in reqs):
        assert time.monotonic() < deadline, "requests never finished"
        eng.step_sem.release()
        time.sleep(0.002)


def spec(seed, text=None):
    ids = np.zeros(TEXT_SEQ, np.int32) if text is None else text
    return SampleSpec(ids, seed=seed)


class TestPriorityPolicy:
    def test_high_overtakes_queued_low(self):
        """Slots full of low, queue holds more low, then a high arrives:
        the high's first token lands before every QUEUED low's."""
        eng = FakeContinuousEngine(chunk=2)
        b = ContinuousBatcher(eng, registry=eng.registry)
        running = [b.submit([spec(i)], priority="low") for i in range(4)]
        queued = [b.submit([spec(10 + i)], priority="low") for i in range(4)]
        high = b.submit([spec(99)], priority="high")
        for r in running + queued + [high]:
            r.future.result(timeout=10)
        assert high.first_token_at is not None
        assert all(
            high.first_token_at <= q.first_token_at for q in queued
        ), "queued low-class requests beat the high-class arrival"
        b.shutdown()

    def test_low_flood_cannot_starve_normal(self):
        """Starvation bound via trace timestamps: under a saturating
        low-class flood from one tenant, a normal-class request's queue
        time stays below the flood's slowest request."""
        eng = FakeContinuousEngine(chunk=4)
        b = ContinuousBatcher(eng, registry=eng.registry)
        tr = Tracer()
        flood = [
            b.submit(
                [spec(i)], priority="low", tenant="flooder",
                trace=tr.start_trace(),
            )
            for i in range(16)
        ]
        normal = b.submit(
            [spec(50)], priority="normal", trace=tr.start_trace()
        )
        for r in flood + [normal]:
            r.future.result(timeout=10)
            r.trace.finish()
        normal_queue = normal.trace.stage_seconds().get("queue", 0.0)
        flood_queues = [
            r.trace.stage_seconds().get("queue", 0.0) for r in flood
        ]
        assert normal_queue <= max(flood_queues), (
            "normal class waited longer than the whole low flood"
        )
        b.shutdown()

    def test_preempts_youngest_low_for_high(self):
        eng = StepEngine(chunk=1)  # 8 boundaries per image: slow decode
        b = ContinuousBatcher(eng, registry=eng.registry)
        tr = Tracer()
        lows = [
            b.submit([spec(i)], priority="low", trace=tr.start_trace())
            for i in range(4)
        ]
        assert eng.chunk_entered.wait(10)  # worker parked at a boundary
        _until(eng, lambda: b.allocator.n_active == 4)  # all four admitted
        high = b.submit([spec(9)], priority="high")
        _step(eng, 2)  # boundary 1: preempt fires; boundary 2: high admits
        assert lows[3].preemptions == 1, "victim must be the youngest low"
        assert all(lows[i].preemptions == 0 for i in range(3))
        fam = eng.registry.get("dalle_serving_preemptions_total")
        assert dict(fam.items())["priority"].value == 1
        # run everything to completion: resumed low re-prefills and ends
        _finish(eng, lows + [high])
        for r in lows + [high]:
            toks, _ = r.future.result(timeout=10)
        assert high.first_token_at <= lows[3].first_token_at or (
            lows[3].first_token_at is not None
        )
        fam = eng.registry.get("dalle_serving_resumptions_total")
        assert dict(fam.items())["priority"].value == 1
        # the preempted span landed in the victim's trace
        lows[3].trace.finish()
        assert "preempted" in lows[3].trace.stage_seconds()
        b.shutdown()

    def test_reserve_slots_hold_room_for_high(self):
        eng = StepEngine(chunk=1)
        b = ContinuousBatcher(eng, registry=eng.registry, reserve_slots=1)
        lows = [b.submit([spec(i)], priority="low") for i in range(4)]
        assert eng.chunk_entered.wait(10)
        _until(eng, lambda: b.allocator.n_active == 3)
        # only 3 of 4 slots go to the low class; one stays reserved
        _step(eng, 2)
        assert b.allocator.n_active == 3
        high = b.submit([spec(9)], priority="high")
        _until(eng, lambda: b.allocator.n_active == 4)  # reserve used
        _finish(eng, lows + [high])
        for r in lows + [high]:
            r.future.result(timeout=10)
        b.shutdown()

    def test_reserve_makes_wide_low_request_unadmittable_at_submit(self):
        """A non-high request wider than max_batch minus the reserve can
        NEVER admit — it must be rejected at submit, not queued to
        head-of-line-block its class forever."""
        eng = StepEngine(chunk=1)
        b = ContinuousBatcher(eng, registry=eng.registry, reserve_slots=1)
        with pytest.raises(QueueFullError, match="exceeds max batch"):
            b.submit([spec(i) for i in range(4)], priority="low")
        # the high class may still use the full slot set
        high = b.submit([spec(i) for i in range(4)], priority="high")
        _finish(eng, [high])
        high.future.result(timeout=10)
        b.shutdown()

    def test_preemption_churn_free_despite_stale_low_credit(self):
        """The finding-3 livelock setup: high banks heavy scheduler
        credit first, then a preempted low is re-queued — the clamp must
        keep the blocked high as the scheduler's pick, so the victim is
        preempted ONCE, not re-admitted and re-evicted every boundary."""
        eng = StepEngine(chunk=1)
        b = ContinuousBatcher(eng, registry=eng.registry)
        # bank high-class service credit
        warm = [b.submit([spec(i)], priority="high") for i in range(12)]
        _finish(eng, warm)
        lows = [b.submit([spec(50 + i)], priority="low") for i in range(4)]
        assert eng.chunk_entered.wait(10)
        _until(eng, lambda: b.allocator.n_active == 4)
        high = b.submit([spec(99)], priority="high")
        _until(eng, lambda: high.first_token_at is not None, max_steps=16)
        _finish(eng, lows + [high])
        assert sum(r.preemptions for r in lows) == 1, (
            "preempt/re-admit churn: victim evicted more than once"
        )
        b.shutdown()

    def test_cancel_mid_decode_releases_slot(self):
        eng = StepEngine(chunk=1)
        b = ContinuousBatcher(eng, registry=eng.registry)
        req = b.submit([spec(0)])
        assert eng.chunk_entered.wait(10)
        _until(eng, lambda: b.allocator.n_active == 1)  # admitted, decoding
        req.cancel()
        _finish(eng, [req])  # reaped at the next chunk boundary
        with pytest.raises(RequestCancelled):
            req.future.result(timeout=10)
        assert b.allocator.n_active == 0
        assert eng.registry.get("dalle_serving_cancelled_total").value == 1
        b.shutdown()

    def test_timeout_mid_decode_releases_slot(self):
        eng = StepEngine(chunk=1)
        b = ContinuousBatcher(eng, registry=eng.registry)
        req = b.submit([spec(0)], timeout_s=0.3)
        assert eng.chunk_entered.wait(10)
        _until(eng, lambda: b.allocator.n_active == 1)
        time.sleep(0.35)  # deadline passes while the row decodes
        _finish(eng, [req])
        with pytest.raises(RequestTimeout):
            req.future.result(timeout=10)
        assert b.allocator.n_active == 0
        assert eng.registry.get("dalle_serving_timeouts_total").value == 1
        b.shutdown()


class FailNthChunkEngine(FakeContinuousEngine):
    def __init__(self, fail_calls, **kw):
        super().__init__(**kw)
        self.fail_calls = set(fail_calls)
        self.chunk_calls = 0

    def step_chunk(self):
        self.chunk_calls += 1
        if self.chunk_calls in self.fail_calls:
            raise RuntimeError(f"injected chunk failure #{self.chunk_calls}")
        return super().step_chunk()


class TestDispatchRetry:
    def test_transient_failure_retries_to_completion(self):
        eng = FailNthChunkEngine({1}, chunk=4)
        b = ContinuousBatcher(eng, registry=eng.registry)
        req = b.submit([spec(7)])
        toks, _ = req.future.result(timeout=10)
        assert int(toks[0, 0]) == 7
        assert req.dispatch_retries == 1
        assert (
            eng.registry.get("dalle_serving_dispatch_retries_total").value
            == 1
        )
        fam = eng.registry.get("dalle_serving_resumptions_total")
        assert dict(fam.items())["dispatch_retry"].value == 1
        b.shutdown()

    def test_retry_budget_is_one(self):
        """A persistently failing engine costs each request exactly two
        dispatch attempts (original + the one bounded retry)."""
        eng = FakeContinuousEngine(fail_chunks=True)
        b = ContinuousBatcher(eng, registry=eng.registry)
        req = b.submit([spec(0)])
        with pytest.raises(RuntimeError, match="XLA fell over"):
            req.future.result(timeout=10)
        assert req.dispatch_retries == 1
        b.shutdown()


class TestShedQuotaRetryAfter:
    def _loaded_batcher(self, **kw):
        """Batcher with 4 rows decoding (worker parked in a chunk) so
        submissions stay queued."""
        eng = StepEngine(chunk=1)
        b = ContinuousBatcher(eng, registry=eng.registry, **kw)
        # distinct tenants so the background fill can't trip a per-tenant
        # quota while racing the worker's admission waves
        running = [
            b.submit([spec(i)], priority="low", tenant=f"bg{i}")
            for i in range(4)
        ]
        assert eng.chunk_entered.wait(10)
        _until(eng, lambda: b.allocator.n_active == 4)
        return eng, b, running

    def test_tenant_quota_429(self):
        eng, b, running = self._loaded_batcher(tenant_quota_rows=2)
        b.submit([spec(10)], tenant="t")
        b.submit([spec(11)], tenant="t")
        with pytest.raises(TenantQuotaError) as e:
            b.submit([spec(12)], tenant="t")
        assert e.value.retry_after_s >= 1.0
        b.submit([spec(13)], tenant="other")  # other tenants unaffected
        fam = eng.registry.get("dalle_serving_shed_total")
        assert dict(fam.items())["quota"].value == 1
        self._drain(eng, b, running)

    def test_deadline_shed_503(self):
        eng, b, running = self._loaded_batcher(deadline_shed=True)
        b._chunk_ema = 0.5  # measured basis: 8 chunks/image -> 4s/image
        with pytest.raises(ShedError) as e:
            b.submit([spec(10)], timeout_s=2.0)  # unmeetable
        assert e.value.reason == "deadline"
        assert 1.0 <= e.value.retry_after_s <= 60.0
        b.submit([spec(11)], timeout_s=120.0)  # meetable: admitted
        fam = eng.registry.get("dalle_serving_shed_total")
        assert dict(fam.items())["deadline"].value == 1
        self._drain(eng, b, running)

    def test_shed_disabled_admits(self):
        eng, b, running = self._loaded_batcher(deadline_shed=False)
        b._chunk_ema = 0.5
        b.submit([spec(10)], timeout_s=2.0)  # no shed model: queued
        self._drain(eng, b, running)

    def test_queue_full_retry_after_and_class_horizon(self):
        eng, b, running = self._loaded_batcher(max_queue_rows=4)
        b._chunk_ema = 0.1
        for i in range(4):
            b.submit([spec(20 + i)], priority="low")
        with pytest.raises(QueueFullError) as e:
            b.submit([spec(30)], priority="low")
        assert e.value.retry_after_s >= 1.0
        # the class horizon: high sees past the low flood's queue rows
        b.submit([spec(31)], priority="high")
        self._drain(eng, b, running)

    def _drain(self, eng, b, running):
        _finish(eng, running)
        b.shutdown(drain=False)


class TestSLOBurnAware:
    """Preemption-aware SLO burn (ROADMAP §5 follow-on): the batcher's
    `slo_burn` hook (wired to SLOTracker.max_burn by ServingServer)
    tightens admission and changes the preemption victim policy while
    the error budget burns."""

    def test_burn_tightens_deadline_shed_deterministically(self):
        eng = FakeContinuousEngine(chunk=4)
        b = ContinuousBatcher(eng, registry=eng.registry)
        burn = {"v": 0.0}
        b.slo_burn = lambda: burn["v"]
        # settle one request so the worker idles, then pin the cost
        # model: image time = 2 chunks x 1.0s EMA = 2.0s, empty backlog
        b.submit([spec(1)], timeout_s=30.0).future.result(timeout=10)
        b._chunk_ema = 1.0
        # burn <= 1: est completion 2.0s fits a 4s timeout -> admit is
        # exactly the burn-blind behavior
        burn["v"] = 0.5
        b._chunk_ema = 1.0
        b.submit([spec(2)], timeout_s=4.0).future.result(timeout=10)
        # burn 4x: admission budget tightens to 4s/4 = 1s < 2s -> shed,
        # attributed to the burn (the request WOULD fit its raw timeout)
        burn["v"] = 4.0
        b._chunk_ema = 1.0
        with pytest.raises(ShedError) as e:
            b.submit([spec(3)], timeout_s=4.0)
        assert e.value.reason == "slo_burn"
        assert e.value.retry_after_s >= 1.0
        fam = eng.registry.get("dalle_serving_shed_total")
        assert dict(fam.items())["slo_burn"].value == 1
        # a deadline-impossible request stays reason=deadline even while
        # burning (the burn did not cause that rejection)
        with pytest.raises(ShedError) as e:
            b.submit([spec(4)], timeout_s=1.0)
        assert e.value.reason == "deadline"
        # a broken burn source must not break admission
        b.slo_burn = lambda: (_ for _ in ()).throw(RuntimeError("boom"))
        b._chunk_ema = 1.0
        b.submit([spec(5)], timeout_s=4.0).future.result(timeout=10)
        b.shutdown()

    def test_burn_prefers_cheapest_redo_victim(self):
        """Victim selection under burn: evict the lower-class request
        with the LEAST decode progress (cheapest redo) instead of the
        youngest. Setup makes the two policies disagree: an OLDER
        single-row request has less total progress than a YOUNGER
        two-row one."""
        eng = StepEngine(chunk=2)
        eng.image_seq_len = 32  # long decode: nothing completes mid-test
        b = ContinuousBatcher(eng, registry=eng.registry)
        b.slo_burn = lambda: 2.0
        old = b.submit([spec(1)], priority="low")
        assert eng.chunk_entered.wait(10)
        _until(eng, lambda: b.allocator.n_active == 1)
        young = b.submit([spec(2), spec(3)], priority="low")
        _until(eng, lambda: b.allocator.n_active == 3)
        _step(eng, 3)
        # precondition: the policies disagree — the older request's one
        # row has less summed progress than the younger's two rows
        def progress(req):
            return sum(
                int(eng.pos[s])
                for s, (r, _) in b._inflight.items() if r is req
            )

        assert progress(old) < progress(young), (
            f"setup broken: old={progress(old)} young={progress(young)}"
        )
        assert old.admitted_seq < young.admitted_seq
        high = b.submit([spec(9), spec(10)], priority="high")
        _step(eng, 2)  # boundary 1: preempt; boundary 2: high admits
        assert old.preemptions == 1, (
            "burning: the cheapest-redo victim (least progress) must go"
        )
        assert young.preemptions == 0
        _finish(eng, [old, young, high])
        for r in (old, young, high):
            r.future.result(timeout=10)
        b.shutdown()


# ------------------------------------------- real engines: bit-identity


@pytest.fixture(scope="module")
def toy():
    model = DALLE(
        dim=32, depth=2, heads=2, dim_head=8,
        num_image_tokens=32, image_fmap_size=FMAP,
        num_text_tokens=64, text_seq_len=TEXT_SEQ,
        shift_tokens=True, rotary_emb=True,
    )
    text = jnp.zeros((1, TEXT_SEQ), jnp.int32)
    toks = jnp.zeros((1, IMG_SEQ), jnp.int32)
    params = jax.jit(model.init)(jax.random.PRNGKey(42), text, toks)
    return model, params


def _prompt(fill):
    ids = np.zeros(TEXT_SEQ, np.int32)
    ids[:4] = fill
    return ids


def _make_engine(toy, paged, prefix_entries=8):
    model, params = toy
    cls = PagedContinuousEngine if paged else ContinuousEngine
    kw = dict(page_size=8, prefix_entries=prefix_entries) if paged else {}
    return cls(
        model=model, variables=params, max_batch=2, chunk_tokens=2,
        prefill_batch=2, registry=MetricsRegistry(), **kw,
    )


def _wait_first_token(req, timeout=30.0):
    deadline = time.monotonic() + timeout
    while req.first_token_at is None:
        assert time.monotonic() < deadline, "request never produced a token"
        time.sleep(0.002)


class TestPreemptResumeBitIdentity:
    @pytest.mark.parametrize("paged", [False, True], ids=["slot", "paged"])
    def test_preempted_run_matches_unpreempted(self, toy, paged):
        """The acceptance pin: fill both slots with low, let them decode,
        then submit a high — the youngest low is preempted (slot released
        mid-decode) and later resumed from scratch; its final tokens must
        equal the un-preempted reference run bit for bit, and the
        preemption snapshot must be a prefix of them."""
        eng = _make_engine(toy, paged)
        b = ContinuousBatcher(eng, registry=eng.registry)
        victim_spec = spec(1234, _prompt((5, 6, 7, 8)))
        # reference: the same spec served without interference
        ref_toks, _ = b.submit([victim_spec]).future.result(timeout=120)

        other = b.submit([spec(5, _prompt((1, 1, 2, 2)))], priority="low")
        victim = b.submit([victim_spec], priority="low")
        _wait_first_token(victim)  # decoding, tokens exist
        high = b.submit([spec(9, _prompt((3, 3, 4, 4)))], priority="high")
        h_toks, _ = high.future.result(timeout=120)
        v_toks, _ = victim.future.result(timeout=120)
        other.future.result(timeout=120)

        assert victim.preemptions == 1, "high had no free slot: must preempt"
        assert high.preemptions == 0
        np.testing.assert_array_equal(v_toks, ref_toks)
        snap = victim.preempt_snapshots[0]
        assert len(snap) >= 1
        np.testing.assert_array_equal(v_toks[0][: len(snap)], snap)
        fam = eng.registry.get("dalle_serving_resumptions_total")
        assert dict(fam.items())["priority"].value == 1
        if paged:
            # the resume admitted through the prefix cache (near-zero
            # re-prefill — the PR 6 wiring this layer exists to use)
            assert victim.prefix_hit is True
            assert eng.kv.leak_check() == []
        b.shutdown()


# ------------------------------------------- real engines: fault injection


class TestFaultInjectedRecovery:
    def test_midwave_prefill_failure_leaves_pool_consistent(self, toy):
        """Injected failure on the first prefill wave: the donated-state
        rebuild resets pool/cache/tables, the batcher's bounded retry
        re-admits both requests, tokens still match the reference, and
        the page pool audits clean with admissions still working.
        Prefix caching is disabled so the reference runs don't register
        the prompts — a repeat admission must run a REAL prefill wave
        for the injected prefill fault to have a dispatch to hit."""
        eng = _make_engine(toy, paged=True, prefix_entries=0)
        b = ContinuousBatcher(eng, registry=eng.registry)
        specs = [spec(11, _prompt((9, 9, 1, 1))), spec(22, _prompt((9, 9, 2, 2)))]
        refs = [
            b.submit([s]).future.result(timeout=120)[0] for s in specs
        ]
        eng.faults = FaultInjector().fail_nth("prefill", 1)
        reqs = [b.submit([s], priority="low") for s in specs]
        outs = [r.future.result(timeout=120)[0] for r in reqs]
        for out, ref in zip(outs, refs):
            np.testing.assert_array_equal(out, ref)
        assert eng.faults.fired and eng.faults.fired[0]["program"] == "prefill"
        assert (
            eng.registry.get("dalle_serving_dispatch_retries_total").value
            == len([r for r in reqs if r.dispatch_retries])
        )
        assert eng.kv.leak_check() == [], "failed wave leaked pages/refs"
        # the pool still admits after the rebuild
        again = b.submit([spec(33, _prompt((7, 7, 7, 7)))])
        again.future.result(timeout=120)
        assert eng.kv.leak_check() == []
        b.shutdown()

    def test_chunk_failure_midflight_recovers_bit_identical(self, toy):
        eng = _make_engine(toy, paged=True)
        b = ContinuousBatcher(eng, registry=eng.registry)
        s = spec(77, _prompt((2, 4, 6, 8)))
        ref, _ = b.submit([s]).future.result(timeout=120)
        eng.faults = FaultInjector().fail_nth("chunk", 2)
        req = b.submit([s])
        out, _ = req.future.result(timeout=120)
        np.testing.assert_array_equal(out, ref)
        assert req.dispatch_retries == 1
        assert eng.kv.leak_check() == []
        b.shutdown()

    def test_exhausted_retry_fails_clean(self, toy):
        eng = _make_engine(toy, paged=True)
        b = ContinuousBatcher(eng, registry=eng.registry)
        eng.faults = FaultInjector().fail_nth("prefill", 1).fail_nth(
            "prefill", 2
        )
        req = b.submit([spec(5, _prompt((1, 2, 3, 4)))])
        with pytest.raises(InjectedFault):
            req.future.result(timeout=120)
        assert req.dispatch_retries == 1
        assert eng.kv.leak_check() == []
        # rules exhausted: the engine serves again
        ok = b.submit([spec(6, _prompt((4, 3, 2, 1)))])
        ok.future.result(timeout=120)
        assert eng.kv.leak_check() == []
        b.shutdown()

    def test_stall_rule_delays_but_completes(self, toy):
        eng = _make_engine(toy, paged=False)
        b = ContinuousBatcher(eng, registry=eng.registry)
        eng.faults = FaultInjector().stall_nth("chunk", 1, seconds=0.05)
        req = b.submit([spec(3, _prompt((6, 6, 6, 6)))])
        req.future.result(timeout=120)
        assert eng.faults.fired[0]["kind"] == "stall"
        b.shutdown()


class TestLeakCheck:
    def _kv(self):
        return PagedKVManager(
            n_rows=2, page_size=4, max_positions=17, text_positions=9,
            n_pages=16, max_entries=4,
        )

    def test_clean_lifecycle_audits_clean(self):
        kv = self._kv()
        ids = np.arange(TEXT_SEQ, dtype=np.int32)
        assert kv.leak_check() == []
        kv.admit_miss(0, ids, register=False)
        kv.ensure(0, 3)
        assert kv.leak_check() == []
        kv.release(0)
        assert kv.leak_check() == []

    def test_detects_refcount_drift(self):
        kv = self._kv()
        kv.admit_miss(0, np.arange(TEXT_SEQ, dtype=np.int32), register=False)
        kv.pool._ref[int(kv.table[0, 0])] += 1  # simulated leak
        assert any("refcount" in p for p in kv.leak_check())

    def test_detects_reservation_drift(self):
        kv = self._kv()
        kv.admit_miss(0, np.arange(TEXT_SEQ, dtype=np.int32), register=False)
        kv._debt[0] += 1
        assert any("pages_per_row" in p for p in kv.leak_check())


# ------------------------------------------------------------- HTTP layer


def _post(port, body, timeout=30):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


class TestHTTPQoS:
    def test_priority_tenant_and_qos_surfaces(self, toy):
        from dalle_pytorch_tpu.data.tokenizer import ByteTokenizer

        eng = _make_engine(toy, paged=False)
        eng.tokenizer = ByteTokenizer()
        server = ServingServer(eng, port=0, request_timeout_s=60).start()
        try:
            port = server.port
            status, payload = _post(
                port,
                {"prompt": "red", "priority": "high", "tenant": "acme",
                 "seed": 3},
            )
            assert status == 200 and len(payload["tokens"][0]) == IMG_SEQ

            with pytest.raises(urllib.error.HTTPError) as e:
                _post(port, {"prompt": "red", "priority": "urgent"})
            assert e.value.code == 400

            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10
            ) as resp:
                health = json.loads(resp.read())
            qos = health["qos"]
            assert qos["queue_by_class"] == {
                "high": 0, "normal": 0, "low": 0
            }
            assert qos["preempt_enabled"] is True
            assert "preemptions" in qos and "shed" in qos

            # the metric families render with their reason labels
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ) as resp:
                text = resp.read().decode()
            assert "dalle_serving_queue_depth_rows_by_class" in text
            assert "dalle_serving_dispatch_retries_total" in text
        finally:
            server.shutdown()

    def test_quota_429_with_retry_after(self, toy):
        from dalle_pytorch_tpu.data.tokenizer import ByteTokenizer

        eng = _make_engine(toy, paged=False)
        eng.tokenizer = ByteTokenizer()
        # quota 0: every tenanted submission is over quota — the cheapest
        # deterministic way to drive the 429 path over real HTTP
        server = ServingServer(
            eng, port=0, request_timeout_s=60, tenant_quota_rows=0
        ).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(server.port, {"prompt": "red", "tenant": "flooder"})
            assert e.value.code == 429
            retry = e.value.headers.get("Retry-After")
            assert retry is not None and int(retry) >= 1
        finally:
            server.shutdown()


# ------------------------------------------------------- bench line schema


@pytest.mark.slow
def test_priority_mix_bench_schema():
    """`bench_serving --priority_mix` emits one JSON line with the
    per-class/QoS schema downstream tooling parses."""
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "SERVE_DIM": "32", "SERVE_DEPTH": "2", "SERVE_FMAP": "4",
        "SERVE_TEXT_SEQ": "8", "SERVE_BATCH_SHAPES": "1,2",
        "SERVE_OPEN_SECONDS": "2", "SERVE_CHUNK_TOKENS": "4",
        "SERVE_PRIORITY_TIMEOUT": "20",
    }
    out = subprocess.run(
        [sys.executable, "bench_serving.py", "--mode", "open-loop",
         "--priority_mix", "0.3"],
        cwd=Path(__file__).resolve().parents[1],
        env=env, capture_output=True, text=True, timeout=540,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    line = json.loads(out.stdout.strip().splitlines()[-1])
    assert line["metric"] == "serving_priority_mix"
    for key in (
        "classes", "preemptions", "resumptions", "shed",
        "ttft_unloaded_p50_ms", "ttft_unloaded_p95_ms", "rate_rps",
        "saturation_rps", "overload_factor", "dispatch_retries",
        "priority_mix", "kv_layout", "value",
    ):
        assert key in line, f"missing {key}"
    assert set(line["classes"]) <= {"high", "low"}
    for stats in line["classes"].values():
        for k in (
            "offered", "completed", "shed", "rejected", "errors",
            "ttft_p50_ms", "ttft_p95_ms",
        ):
            assert k in stats
