"""Scan-executor parity: `executor="scan"` must be math-identical to the
default unrolled executor on its supported configs, with checkpoint
interop both ways (`scan_params_to_unrolled` / `unrolled_params_to_scan`).

The scan executor exists for compile time (one layer body in the HLO
instead of `depth` copies); these tests pin that it changes NOTHING else.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dalle_pytorch_tpu.models.transformer import (
    Transformer,
    scan_params_to_unrolled,
    unrolled_params_to_scan,
)
from dalle_pytorch_tpu.models.dalle import DALLE, generate_images_cached

FMAP = 3
SEQ = 4 + FMAP * FMAP  # text_len (incl bos) 4, image 9
DIM, DEPTH = 32, 3


def pair(**kw):
    base = dict(
        dim=DIM, depth=DEPTH, seq_len=SEQ, heads=2, dim_head=8,
        image_fmap_size=FMAP, rotary_emb=True, shift_tokens=True,
    )
    base.update(kw)
    return (
        Transformer(executor="unrolled", **base),
        Transformer(executor="scan", **base),
    )


def x_input():
    return jax.random.normal(jax.random.PRNGKey(0), (2, SEQ, DIM))


class TestScanParity:
    @pytest.mark.parametrize(
        "kw",
        [
            {},
            {"sandwich_norm": True},
            {"stable": True},
            {"rotary_emb": False, "shift_tokens": False},
            {"reversible": True},  # remat-in-scan
            {"reversible": True,
             "remat_policy": "dots_with_no_batch_dims_saveable"},
        ],
    )
    def test_output_matches_unrolled(self, kw):
        unr, scn = pair(**kw)
        x = x_input()
        vu = unr.init(jax.random.PRNGKey(1), x)
        vs = {"params": unrolled_params_to_scan(vu["params"], DEPTH)}
        out_u = unr.apply(vu, x)
        out_s = scn.apply(vs, x)
        np.testing.assert_allclose(
            np.asarray(out_u), np.asarray(out_s), rtol=2e-5, atol=2e-5
        )

    def test_reverse_model_matches(self):
        unr, scn = pair()
        x = x_input()
        vu = unr.init(jax.random.PRNGKey(1), x)
        vs = {"params": unrolled_params_to_scan(vu["params"], DEPTH)}
        out_u = unr.apply(vu, x, reverse_model=True)
        out_s = scn.apply(vs, x, reverse_model=True)
        np.testing.assert_allclose(
            np.asarray(out_u), np.asarray(out_s), rtol=2e-5, atol=2e-5
        )
        # and reverse != forward (sanity that the flag acted)
        assert not np.allclose(np.asarray(out_s), np.asarray(scn.apply(vs, x)))

    def test_grad_matches_unrolled(self):
        unr, scn = pair(reversible=True)
        x = x_input()
        vu = unr.init(jax.random.PRNGKey(1), x)

        def loss_u(p):
            return unr.apply({"params": p}, x).astype(jnp.float32).sum()

        def loss_s(p):
            return scn.apply({"params": p}, x).astype(jnp.float32).sum()

        gu = jax.grad(loss_u)(vu["params"])
        gs = jax.grad(loss_s)(unrolled_params_to_scan(vu["params"], DEPTH))
        # compare on the unrolled layout
        gs_unrolled = scan_params_to_unrolled(gs, DEPTH)
        flat_u = jax.tree_util.tree_leaves_with_path(gu)
        flat_s = dict(
            (jax.tree_util.keystr(k), v)
            for k, v in jax.tree_util.tree_leaves_with_path(gs_unrolled)
        )
        assert len(flat_u) == len(flat_s)
        for k, v in flat_u:
            np.testing.assert_allclose(
                np.asarray(v), np.asarray(flat_s[jax.tree_util.keystr(k)]),
                rtol=1e-4, atol=1e-4,
            )

    def test_conversion_round_trip(self):
        _, scn = pair()
        x = x_input()
        vs = scn.init(jax.random.PRNGKey(1), x)
        back = unrolled_params_to_scan(
            scan_params_to_unrolled(vs["params"], DEPTH), DEPTH
        )
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            vs["params"], back,
        )

    @pytest.mark.parametrize(
        "kw, msg",
        [
            ({"attn_types": ("axial_row",), "attn_impl": "flash"}, "masked"),
            ({"shared_attn_ids": (0, 0, 0)}, "sharing"),
            ({"reversible": True, "reversible_impl": "revnet"}, "revnet"),
        ],
    )
    def test_unsupported_configs_raise(self, kw, msg):
        _, scn = pair(**{k: v for k, v in kw.items()})
        with pytest.raises(ValueError, match=msg):
            scn.init(jax.random.PRNGKey(1), x_input())

    @pytest.mark.parametrize(
        "attn_types",
        [
            ("axial_row",),
            ("full", "axial_row", "axial_col", "conv_like"),
            ("sparse",),
        ],
    )
    def test_attn_type_cycling_matches_unrolled(self, attn_types):
        # masked attn types run as dense + depth-stacked scanned pattern
        # masks; every cycled layout must be bit-comparable with the
        # unrolled executor's per-layer static masks
        unr, scn = pair(attn_types=attn_types)
        x = x_input()
        vu = unr.init(jax.random.PRNGKey(1), x)
        vs = {"params": unrolled_params_to_scan(vu["params"], DEPTH)}
        out_u = unr.apply(vu, x)
        out_s = scn.apply(vs, x)
        np.testing.assert_allclose(
            np.asarray(out_u), np.asarray(out_s), rtol=2e-5, atol=2e-5
        )

    def test_attn_type_cycling_grads_match(self):
        unr, scn = pair(attn_types=("full", "axial_row"), reversible=True)
        x = x_input()
        vu = unr.init(jax.random.PRNGKey(1), x)

        def loss_u(p):
            return unr.apply({"params": p}, x).astype(jnp.float32).sum()

        def loss_s(p):
            return scn.apply({"params": p}, x).astype(jnp.float32).sum()

        gu = jax.grad(loss_u)(vu["params"])
        gs = scan_params_to_unrolled(
            jax.grad(loss_s)(unrolled_params_to_scan(vu["params"], DEPTH)),
            DEPTH,
        )
        flat_s = dict(
            (jax.tree_util.keystr(k), v)
            for k, v in jax.tree_util.tree_leaves_with_path(gs)
        )
        for k, v in jax.tree_util.tree_leaves_with_path(gu):
            np.testing.assert_allclose(
                np.asarray(v), np.asarray(flat_s[jax.tree_util.keystr(k)]),
                rtol=1e-4, atol=1e-4,
            )


class TestScanCLIP:
    """CLIP's two non-causal encoders under the scan executor (incl. the
    text encoder's dynamic key-padding mask through nn.broadcast)."""

    def test_loss_parity(self):
        from dalle_pytorch_tpu.models.clip import CLIP

        kw = dict(
            dim_text=32, dim_image=32, dim_latent=16, num_text_tokens=50,
            text_enc_depth=2, text_seq_len=8, text_heads=2,
            visual_enc_depth=2, visual_heads=2, visual_image_size=16,
            visual_patch_size=8,
        )
        cu, cs = CLIP(executor="unrolled", **kw), CLIP(executor="scan", **kw)
        text = jnp.array([[3, 5, 2, 0, 0, 0, 0, 0], [7, 1, 4, 9, 0, 0, 0, 0]])
        mask = text > 0
        imgs = jax.random.uniform(jax.random.PRNGKey(0), (2, 16, 16, 3))
        vs = cs.init(jax.random.PRNGKey(1), text, imgs, text_mask=mask,
                     return_loss=True)
        loss_s = cs.apply(vs, text, imgs, text_mask=mask, return_loss=True)

        pu = dict(vs["params"])
        for name, depth in (("text_transformer", 2), ("visual_transformer", 2)):
            pu[name] = scan_params_to_unrolled(vs["params"][name], depth)
        loss_u = cu.apply({"params": pu}, text, imgs, text_mask=mask,
                          return_loss=True)
        np.testing.assert_allclose(float(loss_s), float(loss_u), rtol=1e-5)


class TestScanDALLE:
    """End-to-end through the DALLE wrapper: scan-trained params must
    produce the same loss as unrolled, and the converted checkpoint must
    drive the unrolled cached decode."""

    def _model(self, executor):
        return DALLE(
            dim=DIM, depth=DEPTH, heads=2, dim_head=8,
            num_image_tokens=16, image_fmap_size=FMAP,
            num_text_tokens=30, text_seq_len=4,
            shift_tokens=True, rotary_emb=True, executor=executor,
        )

    def test_loss_parity_and_cached_decode(self):
        mu, ms = self._model("unrolled"), self._model("scan")
        text = jnp.array([[3, 5, 2, 0], [7, 1, 0, 0]], jnp.int32)
        img = jnp.arange(2 * FMAP * FMAP, dtype=jnp.int32).reshape(2, -1) % 16
        vs = ms.init(jax.random.PRNGKey(0), text, img)
        loss_s, _ = ms.apply(vs, text, img, return_loss=True)

        pu = dict(vs["params"])
        pu["transformer"] = scan_params_to_unrolled(
            vs["params"]["transformer"], DEPTH
        )
        loss_u, _ = mu.apply({"params": pu}, text, img, return_loss=True)
        np.testing.assert_allclose(float(loss_s), float(loss_u), rtol=1e-5)

        # converted checkpoint drives the unrolled KV-cached sampler
        imgs = generate_images_cached(
            mu, {"params": pu}, jax.random.PRNGKey(2), text[:1]
        )
        assert imgs.shape == (1, FMAP * FMAP)

    def test_native_cached_decode_matches_unrolled(self):
        """The scan executor's OWN KV-cached decode (depth-stacked cache
        scanned in and out) must produce the same tokens as the unrolled
        cached sampler on the converted checkpoint — no conversion needed."""
        mu, ms = self._model("unrolled"), self._model("scan")
        text = jnp.array([[3, 5, 2, 0]], jnp.int32)
        img = jnp.arange(FMAP * FMAP, dtype=jnp.int32)[None] % 16
        vs = ms.init(jax.random.PRNGKey(0), text, img)
        near_greedy = dict(temperature=1e-4, filter_thres=0.999)
        toks_scan = generate_images_cached(
            ms, vs, jax.random.PRNGKey(2), text, **near_greedy
        )
        pu = dict(vs["params"])
        pu["transformer"] = scan_params_to_unrolled(
            vs["params"]["transformer"], DEPTH
        )
        toks_unrolled = generate_images_cached(
            mu, {"params": pu}, jax.random.PRNGKey(2), text, **near_greedy
        )
        np.testing.assert_array_equal(
            np.asarray(toks_scan), np.asarray(toks_unrolled)
        )
        # and the scan model's uncached full-reforward sampler agrees
        from dalle_pytorch_tpu.models.dalle import generate_images

        toks_full = generate_images(
            ms, vs, jax.random.PRNGKey(2), text, **near_greedy
        )
        np.testing.assert_array_equal(
            np.asarray(toks_scan), np.asarray(toks_full)
        )

    def test_cached_decode_with_pattern_masks_matches_unrolled(self):
        """Scan-native cached decode WITH the attn-type cycle: the traced
        per-layer pattern masks row-slice at the decode position exactly
        like the unrolled executor's static masks, so both cached
        samplers emit identical tokens from the same (converted)
        checkpoint — generate.py needs no layout conversion for masked
        scan checkpoints."""
        attn_types = ("full", "axial_row", "axial_col", "conv_like")
        kw = dict(
            dim=DIM, depth=DEPTH, heads=2, dim_head=8,
            num_image_tokens=16, image_fmap_size=FMAP,
            num_text_tokens=30, text_seq_len=4,
            shift_tokens=True, rotary_emb=True, attn_types=attn_types,
        )
        ms = DALLE(executor="scan", **kw)
        text = jnp.array([[3, 5, 2, 0]], jnp.int32)
        img = jnp.arange(FMAP * FMAP, dtype=jnp.int32)[None] % 16
        vs = ms.init(jax.random.PRNGKey(0), text, img)
        toks_scan = generate_images_cached(
            ms, vs, jax.random.PRNGKey(2), text,
            temperature=1e-4, filter_thres=0.999,
        )

        mu = DALLE(executor="unrolled", **kw)
        pu = dict(vs["params"])
        pu["transformer"] = scan_params_to_unrolled(
            vs["params"]["transformer"], DEPTH
        )
        toks_unrolled = generate_images_cached(
            mu, {"params": pu}, jax.random.PRNGKey(2), text,
            temperature=1e-4, filter_thres=0.999,
        )
        np.testing.assert_array_equal(
            np.asarray(toks_scan), np.asarray(toks_unrolled)
        )
