"""End-to-end: config system, checkpoint formats, and the full CLI flow
(train_vae -> train_dalle -> generate) on the synthetic rainbow dataset —
the moral equivalent of the reference's rainbow notebook integration test
(`/root/reference/examples/rainbow_dalle.ipynb`, SURVEY.md §4)."""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dalle_pytorch_tpu.training.config import load_config, TrainConfig
from dalle_pytorch_tpu.training.checkpoint import (
    save_params_npz,
    load_params_npz,
    CheckpointManager,
)

REPO = Path(__file__).resolve().parent.parent


class TestConfig:
    def test_defaults(self):
        cfg = load_config()
        assert cfg.mode == "forward_only"
        assert cfg.model.dim == 512

    def test_overrides_and_types(self):
        cfg = load_config(
            overrides=["model.depth=4", "learning_rate=1e-3", "lr_decay=true"]
        )
        assert cfg.model.depth == 4 and isinstance(cfg.model.depth, int)
        assert cfg.learning_rate == pytest.approx(1e-3)
        assert cfg.lr_decay is True

    def test_exp_presets(self):
        assert load_config(overrides=["exp=ff"]).mode == "forward_forward"
        assert load_config(overrides=["exp=r"]).mode == "forward_reverse_partial"
        assert load_config(overrides=["exp=ro"]).mode == "reverse_only"

    def test_unknown_key_raises(self):
        with pytest.raises(KeyError):
            load_config(overrides=["bogus_key=1"])

    def test_yaml_roundtrip(self, tmp_path):
        import yaml

        p = tmp_path / "cfg.yaml"
        p.write_text(yaml.safe_dump({"batch_size": 16, "model": {"depth": 3}}))
        cfg = load_config(str(p), overrides=["model.heads=4"])
        assert cfg.batch_size == 16 and cfg.model.depth == 3 and cfg.model.heads == 4


class TestCheckpointFormats:
    def test_npz_roundtrip(self, tmp_path):
        tree = {"a": {"kernel": np.ones((3, 4)), "bias": np.zeros(4)}, "b": np.arange(5)}
        path = tmp_path / "ck.npz"
        save_params_npz(str(path), tree, metadata={"epoch": 3})
        loaded, meta = load_params_npz(str(path))
        assert meta["epoch"] == 3
        np.testing.assert_array_equal(loaded["a"]["kernel"], tree["a"]["kernel"])
        np.testing.assert_array_equal(loaded["b"], tree["b"])

    def test_orbax_manager_rotation_and_resume(self, tmp_path):
        from dalle_pytorch_tpu.training import TrainState, make_optimizer

        params = {"w": jnp.ones((4, 4))}
        state = TrainState.create(
            apply_fn=lambda *a: None, params=params, tx=make_optimizer(1e-3)
        )
        mgr = CheckpointManager(str(tmp_path / "ck"), keep_n=2)
        for step in (1, 2, 3):
            mgr.save(
                step,
                state.replace(step=step),
                metadata={"epoch": step},
            )
        mgr.wait()
        assert mgr.latest_step() == 3
        restored, meta, step = mgr.restore(state)
        assert step == 3 and meta["epoch"] == 3
        assert int(restored.step) == 3
        # rotation: keep_n=2 -> step 1 gone
        steps = sorted(int(p.name) for p in (tmp_path / "ck").iterdir() if p.name.isdigit())
        assert steps == [2, 3]
        mgr.close()


def _assert_same_npz(a: dict, b: dict, name: str):
    """Same keys, float entries allclose (2e-4: the separately-compiled
    scan vs per-step programs fuse differently — same tolerance as
    test_steps_per_dispatch_resume_parity), metadata exactly equal."""
    assert a.keys() == b.keys(), f"{name} checkpoint keys differ"
    for k in a:
        if a[k].dtype.kind in "fc":
            np.testing.assert_allclose(
                a[k], b[k], atol=2e-4,
                err_msg=f"{name} param {k} diverged between spd settings",
            )
        else:  # hparams metadata etc.
            assert np.array_equal(a[k], b[k]), f"{name} entry {k} differs"


def run_cli(script, *cli_args, cwd):
    env = dict(os.environ)
    env["DALLE_TPU_FORCE_PLATFORM"] = "cpu"
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    result = subprocess.run(
        [sys.executable, str(REPO / script), *cli_args],
        cwd=cwd, env=env, capture_output=True, text=True, timeout=900,
    )
    assert result.returncode == 0, (
        f"{script} failed:\nSTDOUT:{result.stdout[-3000:]}\n"
        f"STDERR:{result.stderr[-3000:]}"
    )
    return result.stdout


@pytest.mark.slow
class TestCliEndToEnd:

    def test_full_flow(self, tmp_path):
        common = [
            "--set", "vae.image_size=16", "--set", "vae.num_layers=2",
            "--set", "vae.num_tokens=32", "--set", "vae.codebook_dim=16",
            "--set", "vae.hidden_dim=16", "--set", "debug=true",
        ]
        # 1. train dVAE on rainbow
        out = run_cli(
            "train_vae.py", "--image_folder", "rainbow:64", "--epochs", "1",
            "--batch_size", "8", "--output", str(tmp_path / "vae.npz"),
            *common, cwd=tmp_path,
        )
        assert (tmp_path / "vae.npz").exists()
        assert "64 images for training" in out

        # 2. train DALLE (forward_forward exercises the inverse objective).
        # NOTE: deliberately does NOT repeat the vae.* overrides — the
        # checkpoint must carry the actual VAE hparams from vae.npz
        # (regression: generate once rebuilt the VAE from stale cfg.vae).
        out = run_cli(
            "train_dalle.py", "--image_text_folder", "rainbow:64",
            "--vae_path", str(tmp_path / "vae.npz"),
            "--epochs", "1", "--batch_size", "8", "--exp", "ff",
            "--set", "model.dim=64", "--set", "model.depth=2",
            "--set", "model.heads=2", "--set", "model.dim_head=16",
            "--set", "model.text_seq_len=32", "--set", "model.rotary_emb=true",
            "--set", "model.shift_tokens=true", "--set", "save_every_n_steps=5",
            "--set", "log_images_freq=0", "--set", "bf16=false",
            "--set", "debug=true", cwd=tmp_path,
        )
        ckpt = tmp_path / "checkpoints" / "dalle.npz"
        assert ckpt.exists()

        # 3. resume for one more epoch from the checkpoint
        run_cli(
            "train_dalle.py", "--image_text_folder", "rainbow:64",
            "--dalle_path", str(ckpt), "--epochs", "2", "--batch_size", "8",
            cwd=tmp_path,
        )

        # 4. generate images from two prompts
        run_cli(
            "generate.py", "--dalle_path", str(ckpt),
            "--text", "small red circle|large blue square",
            "--num_images", "2", "--batch_size", "2",
            "--outputs_dir", str(tmp_path / "outputs"), cwd=tmp_path,
        )
        grids = list((tmp_path / "outputs").rglob("grid.png"))
        assert len(grids) == 2
        pngs = list((tmp_path / "outputs").rglob("[0-9].png"))
        assert len(pngs) == 4

    def test_clip_flow(self, tmp_path):
        """train_clip.py CLI -> clip.npz -> generate.py --clip_path rerank
        (the reference's CLIP reranking loop,
        `/root/reference/dalle_pytorch/dalle_pytorch.py:569-571`)."""
        vae_path = _tiny_vae_ckpt(tmp_path)
        run_cli(
            "train_dalle.py", "--image_text_folder", "rainbow:32",
            "--vae_path", str(vae_path),
            "--epochs", "1", "--batch_size", "8",
            "--set", "model.dim=64", "--set", "model.depth=1",
            "--set", "model.heads=2", "--set", "model.dim_head=16",
            "--set", "model.text_seq_len=32", "--set", "bf16=false",
            "--set", "log_images_freq=0",
            "--set", "debug=true", cwd=tmp_path,
        )
        run_cli(
            "train_clip.py", "--image_text_folder", "rainbow:32",
            "--epochs", "1", "--batch_size", "8",
            "--image_size", "16", "--patch_size", "8",
            "--text_seq_len", "32", "--dim", "32", "--dim_latent", "16",
            "--depth", "1", "--heads", "2",
            # windowed dispatch: 4 batches -> one [2,...] window x2
            "--steps_per_dispatch", "2",
            "--output", str(tmp_path / "clip.npz"), "--debug", cwd=tmp_path,
        )
        assert (tmp_path / "clip.npz").exists()
        out = run_cli(
            "generate.py", "--dalle_path",
            str(tmp_path / "checkpoints" / "dalle.npz"),
            "--clip_path", str(tmp_path / "clip.npz"),
            "--text", "small red circle", "--num_images", "2",
            "--batch_size", "2",
            "--outputs_dir", str(tmp_path / "outputs"), cwd=tmp_path,
        )
        # the rerank branch actually ran (a silently-skipped --clip_path
        # would still produce PNGs, so file existence alone proves nothing)
        assert "clip scores (best first):" in out
        pngs = list((tmp_path / "outputs").rglob("[0-9].png"))
        assert len(pngs) == 2
        assert list((tmp_path / "outputs").rglob("grid.png"))

    def test_taming_vqgan_flow(self, tmp_path):
        """train_dalle.py --taming (host-side VQGAN encode, reference
        `train_dalle.py:139-186` precedence) -> generate.py rebuilding the
        VQGAN from the checkpoint's stored config paths."""
        from test_vqgan import make_taming_ckpt

        _, vq_ckpt, vq_yaml = make_taming_ckpt(tmp_path)
        run_cli(
            "train_dalle.py", "--image_text_folder", "rainbow:32",
            "--taming", "--epochs", "1", "--batch_size", "8",
            "--set", f"vqgan_model_path={vq_ckpt}",
            "--set", f"vqgan_config_path={vq_yaml}",
            "--set", "model.dim=64", "--set", "model.depth=1",
            "--set", "model.heads=2", "--set", "model.dim_head=16",
            "--set", "model.text_seq_len=16", "--set", "bf16=false",
            "--set", "truncate_captions=true", "--set", "log_images_freq=0",
            "--set", "debug=true", cwd=tmp_path,
        )
        ckpt = tmp_path / "checkpoints" / "dalle.npz"
        assert ckpt.exists()
        run_cli(
            "generate.py", "--dalle_path", str(ckpt),
            "--text", "small red circle", "--num_images", "1",
            "--batch_size", "1",
            "--outputs_dir", str(tmp_path / "outputs"), cwd=tmp_path,
        )
        assert list((tmp_path / "outputs").rglob("grid.png"))

    def test_wds_training(self, tmp_path):
        """train_dalle.py straight from tar shards (the reference's --wds
        path, `/root/reference/train_dalle.py:257-278,309-313`) — guards
        the trainer/dataset contract (batches signature, length-less
        streaming), not just the dataset class."""
        import io
        import tarfile

        from PIL import Image

        rng = np.random.RandomState(0)
        shard_dir = tmp_path / "shards"
        shard_dir.mkdir()
        idx = 0
        for s in range(2):
            with tarfile.open(shard_dir / f"shard-{s:04d}.tar", "w") as tar:
                for _ in range(8):
                    img = Image.fromarray(
                        rng.randint(0, 255, (20, 20, 3)).astype(np.uint8)
                    )
                    buf = io.BytesIO()
                    img.save(buf, format="JPEG")
                    data = buf.getvalue()
                    info = tarfile.TarInfo(f"{idx:05d}.jpg")
                    info.size = len(data)
                    tar.addfile(info, io.BytesIO(data))
                    cap = f"tiny caption number {idx}".encode()
                    info = tarfile.TarInfo(f"{idx:05d}.txt")
                    info.size = len(cap)
                    tar.addfile(info, io.BytesIO(cap))
                    idx += 1

        # random-init tiny dVAE checkpoint (no training needed for the
        # trainer-contract test)
        from dalle_pytorch_tpu.models.dvae import DiscreteVAE
        from dalle_pytorch_tpu.training.pipeline import save_vae_checkpoint

        vae = DiscreteVAE(
            image_size=16, num_tokens=32, codebook_dim=16,
            num_layers=2, hidden_dim=16,
        )
        vae_params = vae.init(
            {"params": jax.random.PRNGKey(0), "gumbel": jax.random.PRNGKey(1)},
            jnp.zeros((1, 16, 16, 3)),
        )["params"]
        save_vae_checkpoint(str(tmp_path / "vae.npz"), vae, vae_params)

        out = run_cli(
            "train_dalle.py", "--image_text_folder", str(shard_dir),
            "--epochs", "1", "--batch_size", "8",
            "--vae_path", str(tmp_path / "vae.npz"),
            "--set", "wds=jpg,txt",
            "--set", "model.dim=64", "--set", "model.depth=1",
            "--set", "model.heads=2", "--set", "model.dim_head=16",
            "--set", "model.text_seq_len=16", "--set", "bf16=false",
            "--set", "truncate_captions=true",
            "--set", "log_images_freq=0", "--set", "debug=true",
            cwd=tmp_path,
        )
        assert "streaming dataset for training" in out


def _tiny_vae_ckpt(tmp_path):
    """Random-init 16px dVAE checkpoint (fmap 4 -> 16 image tokens)."""
    from dalle_pytorch_tpu.models.dvae import DiscreteVAE
    from dalle_pytorch_tpu.training.pipeline import save_vae_checkpoint

    vae = DiscreteVAE(
        image_size=16, num_tokens=32, codebook_dim=16,
        num_layers=2, hidden_dim=16,
    )
    vae_params = vae.init(
        {"params": jax.random.PRNGKey(0), "gumbel": jax.random.PRNGKey(1)},
        jnp.zeros((1, 16, 16, 3)),
    )["params"]
    path = tmp_path / "vae.npz"
    save_vae_checkpoint(str(path), vae, vae_params)
    return path


class TestAttnImplWiring:
    """model.attn_impl and mesh.sp must be reachable from the trainer CLI
    (round-2 verdict weak #3: they existed only in tests/bench/dryrun)."""

    def test_config_resolution(self):
        """dalle_from_config resolves attn_impl x mesh.sp combinations."""
        from dalle_pytorch_tpu.parallel.mesh import make_mesh
        from dalle_pytorch_tpu.training.pipeline import dalle_from_config

        mesh2 = make_mesh(dp=-1, sp=2)
        cfg = load_config(overrides=["model.attn_impl=auto"])
        m = dalle_from_config(cfg, 32, 4, 100, sp_mesh=mesh2)
        assert m.attn_impl == "ring" and m.sp_mesh is mesh2

        # sp=1: the axis is inert, attn_impl passes through, no mesh threaded
        mesh1 = make_mesh(dp=-1, sp=1)
        cfg = load_config(overrides=["model.attn_impl=flash"])
        m = dalle_from_config(cfg, 32, 4, 100, sp_mesh=mesh1)
        assert m.attn_impl == "flash" and m.sp_mesh is None

        # explicit non-ring impl with sp>1 is a config error, not a silent
        # downgrade
        with pytest.raises(ValueError, match="ring"):
            dalle_from_config(cfg, 32, 4, 100, sp_mesh=mesh2)

        cfg = load_config(
            overrides=["model.attn_impl=ring", "model.stable_softmax=true"]
        )
        with pytest.raises(ValueError, match="stable_softmax"):
            dalle_from_config(cfg, 32, 4, 100, sp_mesh=mesh2)

        # scan executor: resolves through, but not with sequence parallelism
        cfg = load_config(overrides=["model.executor=scan"])
        m = dalle_from_config(cfg, 32, 4, 100, sp_mesh=mesh1)
        assert m.executor == "scan"
        with pytest.raises(ValueError, match="scan"):
            dalle_from_config(cfg, 32, 4, 100, sp_mesh=mesh2)
        cfg = load_config(overrides=["model.executor=bogus"])
        with pytest.raises(ValueError, match="executor"):
            dalle_from_config(cfg, 32, 4, 100)


@pytest.mark.slow
class TestAttnImplCli:
    def test_train_with_flash_attn(self, tmp_path):
        """2 steps of train_dalle.py with --set model.attn_impl=flash
        (Pallas kernel, interpret mode on CPU)."""
        vae_path = _tiny_vae_ckpt(tmp_path)
        run_cli(
            "train_dalle.py", "--image_text_folder", "rainbow:16",
            "--vae_path", str(vae_path),
            "--epochs", "1", "--batch_size", "8",
            "--set", "model.attn_impl=flash",
            "--set", "model.dim=64", "--set", "model.depth=1",
            "--set", "model.heads=2", "--set", "model.dim_head=16",
            "--set", "model.text_seq_len=16", "--set", "bf16=false",
            "--set", "log_images_freq=0", "--set", "debug=true",
            cwd=tmp_path,
        )
        assert (tmp_path / "checkpoints" / "dalle.npz").exists()

    def test_vae_train_with_steps_per_dispatch(self, tmp_path):
        """train_vae.py with steps_per_dispatch=3: 4 batches/epoch -> one
        full [3,...] window + a 1-batch tail; gumbel temp rides as a
        per-dispatch constant."""
        run_cli(
            "train_vae.py", "--image_folder", "rainbow:32", "--epochs", "1",
            "--batch_size", "8", "--output", str(tmp_path / "vae_spd.npz"),
            "--set", "steps_per_dispatch=3",
            "--set", "vae.image_size=16", "--set", "vae.num_layers=2",
            "--set", "vae.num_tokens=32", "--set", "vae.codebook_dim=16",
            "--set", "vae.hidden_dim=16", "--set", "debug=true",
            cwd=tmp_path,
        )
        assert (tmp_path / "vae_spd.npz").exists()

    def test_train_with_steps_per_dispatch(self, tmp_path):
        """steps_per_dispatch=3 over rainbow:64 at batch 8 -> 8 batches/
        epoch = two full [3,...] windows + a 2-batch tail through the
        single-step program; checkpoint completes and the step count is
        exact (16 steps over 2 epochs)."""
        vae_path = _tiny_vae_ckpt(tmp_path)
        out = run_cli(
            "train_dalle.py", "--image_text_folder", "rainbow:64",
            "--vae_path", str(vae_path),
            "--epochs", "2", "--batch_size", "8",
            "--set", "steps_per_dispatch=3",
            "--set", "model.dim=64", "--set", "model.depth=1",
            "--set", "model.heads=2", "--set", "model.dim_head=16",
            "--set", "model.text_seq_len=16", "--set", "bf16=false",
            "--set", "save_every_n_steps=5",
            "--set", "log_images_freq=0", "--set", "debug=true",
            cwd=tmp_path,
        )
        assert (tmp_path / "checkpoints" / "dalle.npz").exists()
        # the 10-step logging cadence fires on crossings (steps 12 and 16+)
        assert "loss - " in out
        # save cadence (5) crossed inside a window -> Orbax step written
        from dalle_pytorch_tpu.training.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path / "checkpoints" / "dalle_ckpt"))
        assert mgr.latest_step(), "no Orbax step checkpoints written"
        mgr.close()

    def test_steps_per_dispatch_resume_parity(self, tmp_path):
        """Three-way CLI parity at 16 total steps (8 batches/epoch x 2):

          A. steps_per_dispatch=3, uninterrupted
          B. steps_per_dispatch=3, stopped after epoch 0, then --resume
             (Orbax mid-epoch checkpoint at step 6 + tail replay)
          C. steps_per_dispatch=1 classic loop

        All three must land on the same final parameters: C==A proves the
        windowed driver changes no math (fold_in key stream intact); B==A
        proves preemption-resume replays windows aligned to the original
        batch stream."""
        vae_path = _tiny_vae_ckpt(tmp_path)

        def train(out, epochs, spd, resume=False):
            run_cli(
                "train_dalle.py", "--image_text_folder", "rainbow:64",
                "--vae_path", str(vae_path),
                *(["--resume"] if resume else []),
                "--epochs", str(epochs), "--batch_size", "8",
                "--set", f"steps_per_dispatch={spd}",
                "--set", "model.dim=64", "--set", "model.depth=1",
                "--set", "model.heads=2", "--set", "model.dim_head=16",
                "--set", "model.text_seq_len=16", "--set", "bf16=false",
                "--set", "save_every_n_steps=5",
                "--set", f"output_dir={out}",
                "--set", "log_images_freq=0", "--set", "debug=true",
                cwd=tmp_path,
            )
            ckpt = tmp_path / out / "dalle.npz"
            assert ckpt.exists()
            from dalle_pytorch_tpu.training.pipeline import load_dalle_checkpoint

            _, params, _, _, _ = load_dalle_checkpoint(str(ckpt))
            return params

        params_a = train("run_a", 2, 3)
        train("run_b", 1, 3)
        params_b = train("run_b", 2, 3, resume=True)
        params_c = train("run_c", 2, 1)

        def close(x, y):
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), atol=2e-4
                ),
                x, y,
            )

        close(params_b, params_a)
        close(params_c, params_a)

    def test_train_with_scan_executor_and_generate(self, tmp_path):
        """2 steps with --set model.executor=scan (depth-stacked nn.scan
        params) AND the sparse attn-type cycle, then generate.py from that
        checkpoint: the scan executor's native KV-cached decode runs
        directly on the stacked params — pattern masks row-sliced at the
        decode position, no layout conversion."""
        vae_path = _tiny_vae_ckpt(tmp_path)
        run_cli(
            "train_dalle.py", "--image_text_folder", "rainbow:16",
            "--vae_path", str(vae_path),
            "--epochs", "1", "--batch_size", "8",
            "--set", "model.executor=scan",
            "--set", "model.attn_types=full,axial_row",
            "--set", "model.dim=64", "--set", "model.depth=2",
            "--set", "model.heads=2", "--set", "model.dim_head=16",
            "--set", "model.text_seq_len=16", "--set", "bf16=false",
            "--set", "log_images_freq=0", "--set", "debug=true",
            cwd=tmp_path,
        )
        ckpt = tmp_path / "checkpoints" / "dalle.npz"
        assert ckpt.exists()
        run_cli(
            "generate.py", "--dalle_path", str(ckpt),
            "--text", "small blue square", "--num_images", "2",
            "--batch_size", "2",
            "--outputs_dir", str(tmp_path / "scan_out"), cwd=tmp_path,
        )
        assert list((tmp_path / "scan_out").rglob("grid.png"))

    def test_train_with_sequence_parallel_ring(self, tmp_path):
        """2 steps of train_dalle.py with mesh.sp=2 on the 8-virtual-device
        CPU mesh: ring attention inside the real trainer loop (seq 32
        shards 16/16 across the sp axis)."""
        vae_path = _tiny_vae_ckpt(tmp_path)
        out = run_cli(
            "train_dalle.py", "--image_text_folder", "rainbow:16",
            "--vae_path", str(vae_path),
            "--epochs", "1", "--batch_size", "8",
            "--set", "mesh.dp=4", "--set", "mesh.sp=2",
            # explicit ring (not auto): the checkpoint then carries
            # attn_impl="ring", exercising generate.py's downgrade
            "--set", "model.attn_impl=ring",
            "--set", "model.dim=64", "--set", "model.depth=1",
            "--set", "model.heads=2", "--set", "model.dim_head=16",
            "--set", "model.text_seq_len=16", "--set", "bf16=false",
            "--set", "log_images_freq=0", "--set", "debug=true",
            cwd=tmp_path,
        )
        ckpt = tmp_path / "checkpoints" / "dalle.npz"
        assert ckpt.exists()

        # generation from the ring-trained checkpoint: decode must
        # downgrade ring->auto (KV-cached decode never runs ring)
        run_cli(
            "generate.py", "--dalle_path", str(ckpt),
            "--text", "small red circle", "--num_images", "2",
            "--batch_size", "2",
            "--outputs_dir", str(tmp_path / "ring_out"), cwd=tmp_path,
        )
        assert list((tmp_path / "ring_out").rglob("grid.png"))

    def test_vae_and_clip_spd_invariance(self, tmp_path):
        """ADVICE r4: train_vae/train_clip now derive RNG via
        fold_in(global_step) (shared window_keys helper), so an 11-step
        run (3 full spd=3 windows + a 2-step tail) must produce the SAME
        final checkpoint as the per-step run — window size is purely an
        execution detail."""
        outs = {}
        for spd in (1, 3):
            out = tmp_path / f"vae_spd{spd}.npz"
            run_cli(
                "train_vae.py", "--image_folder", "rainbow:88", "--epochs",
                "1", "--batch_size", "8", "--output", str(out),
                "--set", f"steps_per_dispatch={spd}",
                "--set", "vae.image_size=16", "--set", "vae.num_layers=2",
                "--set", "vae.num_tokens=32", "--set", "vae.codebook_dim=16",
                "--set", "vae.hidden_dim=16", "--set", "debug=true",
                cwd=tmp_path,
            )
            outs[spd] = dict(np.load(out))
        _assert_same_npz(outs[1], outs[3], "vae")

        clips = {}
        for spd in (1, 3):
            out = tmp_path / f"clip_spd{spd}.npz"
            run_cli(
                "train_clip.py", "--image_text_folder", "rainbow:88",
                "--epochs", "1", "--batch_size", "8",
                "--output", str(out), "--steps_per_dispatch", str(spd),
                "--image_size", "16", "--patch_size", "8", "--dim", "32",
                "--dim_latent", "16", "--depth", "1", "--heads", "2",
                "--text_seq_len", "64", "--debug",
                cwd=tmp_path,
            )
            clips[spd] = dict(np.load(out))
        _assert_same_npz(clips[1], clips[3], "clip")

    def test_train_with_pipeline_parallel(self, tmp_path):
        """mesh.pp=2 in the real trainer loop on the 8-virtual-device CPU
        mesh: the GPipe trunk (2 stages x 2 layers, 2 microbatches)
        trains end-to-end AND the logged loss stream is identical to a
        pp=1 run — the pipelined trunk is numerically the plain trunk."""
        vae_path = _tiny_vae_ckpt(tmp_path)
        losses = {}
        for pp in (1, 2):
            out = run_cli(
                "train_dalle.py", "--image_text_folder", "rainbow:96",
                "--vae_path", str(vae_path),
                "--epochs", "1", "--batch_size", "8",
                # pp=1 leg: dp=-1 absorbs the 8 CPU devices (same global
                # batch, grads psum'd -> identical math to the pp run)
                "--set", f"mesh.pp={pp}", "--set", "mesh.pp_micro=2",
                "--set", "model.executor=scan",
                "--set", "model.dim=64", "--set", "model.depth=4",
                "--set", "model.heads=2", "--set", "model.dim_head=16",
                "--set", "model.text_seq_len=16", "--set", "bf16=false",
                "--set", "log_images_freq=0", "--set", "debug=true",
                "--set", f"output_dir={tmp_path / f'pp{pp}'}",
                cwd=tmp_path,
            )
            lines = [l for l in out.splitlines() if " loss - " in l]
            assert lines, f"no loss line in pp={pp} output:\n{out[-1500:]}"
            losses[pp] = lines
            assert (tmp_path / f"pp{pp}" / "dalle.npz").exists()
        assert losses[1] == losses[2], (
            f"pp=2 loss stream diverged from pp=1:\n{losses}"
        )

        # invalid configs fail loudly, not silently
        env = {**os.environ, "DALLE_TPU_FORCE_PLATFORM": "cpu"}
        env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        bad = subprocess.run(
            [sys.executable, str(REPO / "train_dalle.py"),
             "--image_text_folder", "rainbow:16",
             "--vae_path", str(vae_path), "--batch_size", "8",
             "--set", "mesh.pp=2", "--set", "model.executor=unrolled"],
            cwd=tmp_path, env=env, capture_output=True, text=True, timeout=300,
        )
        assert bad.returncode != 0
        assert "executor=scan" in bad.stderr
