"""Test configuration: force CPU with 8 virtual devices BEFORE jax imports.

This is the moral equivalent of the reference's DummyBackend test seam
(`/root/reference/dalle_pytorch/distributed_backends/dummy_backend.py`) —
except our fake 8-device mesh actually exercises the real sharding and
collective code paths.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment's sitecustomize registers the TPU tunnel backend and forces
# jax_platforms="axon,cpu" at interpreter start; the env var alone is too late.
# Tests must run on the virtual 8-device CPU mesh, so override the config.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")
