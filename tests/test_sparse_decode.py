"""Block-sparse flash decode: sparse-vs-dense oracle parity corpus.

Two pins, per the PR 19 contract:

  * an ALL-ONES bitmap is BIT-IDENTICAL to the non-sparse kernel — same
    tile boundaries, same predicates, same accumulation order. This is
    the serving stack's parity anchor: dense-causal policy ("causal",
    the default) keeps every bit-identity contract the decode path ever
    made, on the slotted, paged, and sharded kernels, fp32 and int8.
  * an arbitrary bitmap matches the dense MASKED oracle — dense cached
    attention under (tile-expanded bitmap AND causal-over-prefix). That
    is the kernel's mathematical spec: live tiles are read whole and the
    causal mask trims inside them (the policy's tile reduction is
    conservative, so exact-pattern dense is a quality comparison — the
    bench reports it — not a parity pin). The axial-row case runs the
    REAL layout reduction (`_build_static_mask` + `mask_to_block_bitmap`)
    end to end.

Kernel tests run in Pallas interpret mode on CPU; engine-level cycles
(full program compiles) ride the slow tier except the slotted anchor.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dalle_pytorch_tpu.models.attention import _kv_quantize
from dalle_pytorch_tpu.ops.attention_core import dense_attention
from dalle_pytorch_tpu.ops.masks import mask_to_block_bitmap
from dalle_pytorch_tpu.ops.pallas_decode import (
    block_sparse_flash_decode_attention,
    block_sparse_paged_flash_decode_attention,
    flash_decode_attention,
    paged_decode_attention,
    paged_flash_decode_attention,
    sharded_flash_decode_attention,
    sharded_paged_decode_attention,
)


def _qkv(b, h, n, s, d, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, h, n, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    return q, k, v


def _sparse_oracle(q, k, v, lengths, bitmap, block_k):
    """Dense cached attention under the kernel's spec mask: position t of
    row b is visible iff its tile is live AND t is causally in range."""
    n = q.shape[2]
    s = k.shape[2]
    tiles = jnp.asarray(bitmap)[:, jnp.arange(s) // block_k]  # [B, S]
    causal = (
        jnp.arange(s)[None, None, :]
        <= (lengths[:, None, None] - n + jnp.arange(n)[None, :, None])
    )
    mask = (tiles != 0)[:, None, :] & causal
    return dense_attention(q, k, v, mask=mask[:, None])


def _rand_bitmap(b, nk, seed, live_frac=0.5):
    """Random bitmap with tile 0 always live (the policy's always-live
    text prefix: a row with zero live tiles has no softmax support)."""
    rng = np.random.RandomState(seed)
    bm = (rng.rand(b, nk) < live_frac).astype(np.int32)
    bm[:, 0] = 1
    return jnp.asarray(bm)


# ------------------------------------------------------------ slotted kernel


@pytest.mark.parametrize("block_k", [8, 16])
def test_all_ones_bit_identical_to_plain_flash(block_k):
    """The serving parity anchor: all-ones bitmap == flash_decode_attention
    bit for bit, per-row lengths included."""
    b, h, s, d = 4, 2, 37, 16
    q, k, v = _qkv(b, h, 1, s, d)
    lengths = jnp.asarray([1, 9, 20, s], jnp.int32)
    nk = -(-s // block_k)
    ones = jnp.ones((b, nk), jnp.int32)
    sparse = block_sparse_flash_decode_attention(
        q, k, v, lengths, ones, block_k=block_k
    )
    plain = flash_decode_attention(q, k, v, lengths, block_k=block_k)
    np.testing.assert_array_equal(np.asarray(sparse), np.asarray(plain))


def test_all_ones_bit_identical_int8():
    """Same anchor on the quantized cache: the scale sidecar rides the
    same index maps, so all-ones stays bit-identical there too."""
    b, h, s, d = 2, 2, 24, 8
    q, k, v = _qkv(b, h, 1, s, d, seed=1)
    kq, ks = _kv_quantize(k)
    vq, vs = _kv_quantize(v)
    lengths = jnp.asarray([5, 24], jnp.int32)
    ones = jnp.ones((b, 3), jnp.int32)
    sparse = block_sparse_flash_decode_attention(
        q, kq, vq, lengths, ones, block_k=8, k_scale=ks, v_scale=vs
    )
    plain = flash_decode_attention(
        q, kq, vq, lengths, block_k=8, k_scale=ks, v_scale=vs
    )
    np.testing.assert_array_equal(np.asarray(sparse), np.asarray(plain))


@pytest.mark.parametrize("n", [1, 4], ids=["decode", "chunk"])
def test_random_bitmap_matches_masked_oracle(n):
    """Arbitrary bitmaps across chunk sizes and per-row lengths match the
    tile-expanded dense oracle to fp32 tolerance."""
    b, h, s, d, block_k = 3, 2, 40, 8, 8
    q, k, v = _qkv(b, h, n, s, d, seed=2)
    lengths = jnp.asarray([n + 3, 17, s], jnp.int32)
    bm = _rand_bitmap(b, s // block_k, seed=3)
    out = block_sparse_flash_decode_attention(
        q, k, v, lengths, bm, block_k=block_k
    )
    np.testing.assert_allclose(
        out, _sparse_oracle(q, k, v, lengths, bm, block_k),
        atol=2e-5, rtol=1e-5,
    )


def test_axial_layout_bitmap_matches_masked_oracle():
    """The REAL policy reduction end to end: an axial_row static layout
    reduced by mask_to_block_bitmap (text prefix always live) drives the
    kernel; output matches the dense oracle under the reduced mask."""
    from dalle_pytorch_tpu.models.transformer import _build_static_mask

    fmap, text_seq, block_k = 4, 7, 8
    total = text_seq + fmap * fmap  # 23
    max_len = total + 1  # 24
    text_len = text_seq + 1
    mask = np.asarray(_build_static_mask("axial_row", total, fmap, 0))
    mask = np.pad(
        mask, ((0, max_len - total), (0, max_len - total)),
        constant_values=True,
    )[:max_len, :max_len]
    rows = mask_to_block_bitmap(
        mask, block_k, n_blocks=max_len // block_k, always_live=text_len
    )
    # three slots decoding at different image positions
    img_pos = np.asarray([0, 5, 15])
    bm = jnp.asarray(rows[text_len + img_pos].astype(np.int32))
    lengths = jnp.asarray(text_len + img_pos + 1, jnp.int32)
    b, h, d = 3, 2, 8
    q, k, v = _qkv(b, h, 1, max_len, d, seed=4)
    out = block_sparse_flash_decode_attention(
        q, k, v, lengths, bm, block_k=block_k
    )
    np.testing.assert_allclose(
        out, _sparse_oracle(q, k, v, lengths, bm, block_k),
        atol=2e-5, rtol=1e-5,
    )
    assert not np.asarray(bm).all(), "layout should have dead tiles"


def test_dead_tiles_never_read():
    """Poison K/V inside dead tiles with huge finite garbage: the output
    must be unchanged — dead tiles are skipped, not merely down-weighted
    (unmasked, 1e4-magnitude logits would dominate every softmax)."""
    b, h, s, d, block_k = 2, 2, 32, 8, 8
    q, k, v = _qkv(b, h, 1, s, d, seed=5)
    lengths = jnp.asarray([s, s], jnp.int32)
    bm = jnp.asarray([[1, 0, 1, 0], [1, 1, 0, 0]], jnp.int32)
    clean = block_sparse_flash_decode_attention(
        q, k, v, lengths, bm, block_k=block_k
    )
    dead = (np.asarray(bm)[:, np.arange(s) // block_k] == 0)  # [B, S]
    poison = jnp.asarray(
        np.where(dead[:, None, :, None], 1e4, 0.0), jnp.float32
    )
    poisoned = block_sparse_flash_decode_attention(
        q, k + poison, v + poison, lengths, bm, block_k=block_k
    )
    np.testing.assert_array_equal(np.asarray(clean), np.asarray(poisoned))


def test_bitmap_is_traced_data_under_jit():
    """One compiled program serves DIFFERENT bitmaps — the policy is data,
    not structure (the zero-recompile contract at kernel level)."""
    b, h, s, d, block_k = 2, 2, 16, 8, 8
    q, k, v = _qkv(b, h, 1, s, d, seed=6)
    lengths = jnp.asarray([s, s], jnp.int32)
    with jax.log_compiles(False):
        fn = jax.jit(
            lambda bm: block_sparse_flash_decode_attention(
                q, k, v, lengths, bm, block_k=block_k
            )
        )
        bm1 = jnp.asarray([[1, 1], [1, 1]], jnp.int32)
        bm2 = jnp.asarray([[1, 0], [1, 1]], jnp.int32)
        out1 = fn(bm1)
        compiled_once = fn._cache_size()
        out2 = fn(bm2)
        assert fn._cache_size() == compiled_once
    np.testing.assert_array_equal(
        np.asarray(out1),
        np.asarray(flash_decode_attention(q, k, v, lengths, block_k=block_k)),
    )
    np.testing.assert_allclose(
        out2, _sparse_oracle(q, k, v, lengths, bm2, block_k),
        atol=2e-5, rtol=1e-5,
    )


# -------------------------------------------------------------- paged kernels


def _paged(k, v, page_size, seed=7):
    """Scatter contiguous K/V into a shuffled page pool + table."""
    b, h, s, d = k.shape
    n_pages = s // page_size
    rng = np.random.RandomState(seed)
    perm = rng.permutation(b * n_pages)
    pool_k = np.zeros((b * n_pages, h, page_size, d), np.float32)
    pool_v = np.zeros_like(pool_k)
    table = np.zeros((b, n_pages), np.int32)
    for bi in range(b):
        for j in range(n_pages):
            phys = perm[bi * n_pages + j]
            table[bi, j] = phys
            sl = np.s_[bi, :, j * page_size : (j + 1) * page_size]
            pool_k[phys] = np.asarray(k)[sl]
            pool_v[phys] = np.asarray(v)[sl]
    return jnp.asarray(pool_k), jnp.asarray(pool_v), jnp.asarray(table)


def test_paged_all_ones_bit_identical_both_impls():
    """Page-granularity all-ones == the non-sparse paged kernel (true
    paged impl), and the gather impl == the slotted sparse kernel — the
    paged-vs-slotted parity contract survives sparsity."""
    b, h, s, d, page = 2, 2, 32, 8, 8
    q, k, v = _qkv(b, h, 1, s, d, seed=8)
    kp, vp, table = _paged(k, v, page)
    lengths = jnp.asarray([9, 26], jnp.int32)
    ones = jnp.ones((b, s // page), jnp.int32)
    sparse_kernel = block_sparse_paged_flash_decode_attention(
        q, kp, vp, lengths, table, ones
    )
    plain_kernel = paged_flash_decode_attention(q, kp, vp, lengths, table)
    np.testing.assert_array_equal(
        np.asarray(sparse_kernel), np.asarray(plain_kernel)
    )
    gather = paged_decode_attention(
        q, kp, vp, lengths, table, s, impl="gather",
        block_bitmap=ones, sparse_block=page,
    )
    slotted = block_sparse_flash_decode_attention(
        q, k, v, lengths, ones, block_k=page
    )
    np.testing.assert_array_equal(np.asarray(gather), np.asarray(slotted))


def test_paged_sparse_matches_oracle_both_impls():
    """A patterned bitmap on the paged cache: both impls match the
    tile-expanded oracle; the gather impl stays bit-identical to the
    slotted sparse kernel; a dead page's physical slot can hold garbage."""
    b, h, s, d, page = 2, 2, 32, 8, 8
    q, k, v = _qkv(b, h, 1, s, d, seed=9)
    kp, vp, table = _paged(k, v, page)
    lengths = jnp.asarray([s, s], jnp.int32)
    bm = jnp.asarray([[1, 0, 1, 1], [1, 1, 0, 1]], jnp.int32)
    oracle = _sparse_oracle(q, k, v, lengths, bm, page)
    for impl in ("gather", "kernel"):
        out = paged_decode_attention(
            q, kp, vp, lengths, table, s, impl=impl,
            block_bitmap=bm, sparse_block=page,
        )
        np.testing.assert_allclose(out, oracle, atol=2e-5, rtol=1e-5)
    slotted = block_sparse_flash_decode_attention(
        q, k, v, lengths, bm, block_k=page
    )
    gather = paged_decode_attention(
        q, kp, vp, lengths, table, s, impl="gather",
        block_bitmap=bm, sparse_block=page,
    )
    np.testing.assert_array_equal(np.asarray(gather), np.asarray(slotted))


def test_paged_sparse_int8_scale_pages_skip_with_their_page():
    """int8 pool: all-ones stays bit-identical to the non-sparse paged
    quantized kernel; a patterned bitmap matches the dequantized oracle."""
    b, h, s, d, page = 2, 2, 32, 8, 8
    q, k, v = _qkv(b, h, 1, s, d, seed=10)
    kq, ks = _kv_quantize(k)
    vq, vs = _kv_quantize(v)
    kp, vp, table = _paged(kq.astype(jnp.float32), vq.astype(jnp.float32), page)
    ksp, vsp, _ = _paged(ks[..., None], vs[..., None], page, seed=7)
    kp, vp = kp.astype(jnp.int8), vp.astype(jnp.int8)
    ksp, vsp = ksp[..., 0], vsp[..., 0]
    lengths = jnp.asarray([s, s], jnp.int32)
    ones = jnp.ones((b, s // page), jnp.int32)
    sparse = block_sparse_paged_flash_decode_attention(
        q, kp, vp, lengths, table, ones, k_scale=ksp, v_scale=vsp
    )
    plain = paged_flash_decode_attention(
        q, kp, vp, lengths, table, k_scale=ksp, v_scale=vsp
    )
    np.testing.assert_array_equal(np.asarray(sparse), np.asarray(plain))
    bm = jnp.asarray([[1, 1, 0, 1], [1, 0, 1, 1]], jnp.int32)
    out = block_sparse_paged_flash_decode_attention(
        q, kp, vp, lengths, table, bm, k_scale=ksp, v_scale=vsp
    )
    kdq = jnp.asarray(kq, jnp.float32) * ks[..., None]
    vdq = jnp.asarray(vq, jnp.float32) * vs[..., None]
    np.testing.assert_allclose(
        out, _sparse_oracle(q, kdq, vdq, lengths, bm, page),
        atol=2e-5, rtol=1e-5,
    )


# ----------------------------------------------------------- sharded kernels


def test_sharded_sparse_bit_identical_to_unsharded():
    """Head-sharded sparse decode == unsharded sparse decode bit for bit
    (the bitmap replicates; heads are independent)."""
    from dalle_pytorch_tpu.serving.sharded import build_serving_mesh

    mesh = build_serving_mesh({"tp": 2})
    b, h, s, d, block_k = 2, 4, 32, 8, 8
    q, k, v = _qkv(b, h, 1, s, d, seed=11)
    lengths = jnp.asarray([13, s], jnp.int32)
    bm = _rand_bitmap(b, s // block_k, seed=12)
    sharded = sharded_flash_decode_attention(
        mesh, q, k, v, lengths, block_bitmap=bm, sparse_block=block_k
    )
    local = block_sparse_flash_decode_attention(
        q, k, v, lengths, bm, block_k=block_k
    )
    np.testing.assert_array_equal(np.asarray(sharded), np.asarray(local))


def test_sharded_paged_sparse_bit_identical_to_unsharded():
    from dalle_pytorch_tpu.serving.sharded import build_serving_mesh

    mesh = build_serving_mesh({"tp": 2})
    b, h, s, d, page = 2, 4, 32, 8, 8
    q, k, v = _qkv(b, h, 1, s, d, seed=13)
    kp, vp, table = _paged(k, v, page)
    lengths = jnp.asarray([9, s], jnp.int32)
    bm = jnp.asarray([[1, 1, 0, 1], [1, 0, 1, 1]], jnp.int32)
    sharded = sharded_paged_decode_attention(
        mesh, q, kp, vp, lengths, table, s, impl="gather",
        block_bitmap=bm, sparse_block=page,
    )
    local = paged_decode_attention(
        q, kp, vp, lengths, table, s, impl="gather",
        block_bitmap=bm, sparse_block=page,
    )
    np.testing.assert_array_equal(np.asarray(sharded), np.asarray(local))


# ------------------------------------------------------------ engine cycles
#
# Full serve cycles: policy mode on every engine. The slotted anchor runs
# in tier 1; paged and sharded cycles compile whole serving programs and
# ride the slow tier.

TEXT_SEQ = 8
FMAP = 4
IMG_SEQ = FMAP * FMAP


def _build_model(**kw):
    from dalle_pytorch_tpu.models.dalle import DALLE

    base = dict(
        dim=32, depth=2, heads=2, dim_head=8,
        num_image_tokens=32, image_fmap_size=FMAP,
        num_text_tokens=64, text_seq_len=TEXT_SEQ,
        shift_tokens=True, rotary_emb=True, attn_impl="flash",
    )
    base.update(kw)
    model = DALLE(**base)
    text = jnp.zeros((1, TEXT_SEQ), jnp.int32)
    toks = jnp.zeros((1, IMG_SEQ), jnp.int32)
    params = jax.jit(model.init)(jax.random.PRNGKey(42), text, toks)
    return model, params


def _spec(seed):
    from dalle_pytorch_tpu.serving.engine import SampleSpec

    ids = np.zeros(TEXT_SEQ, np.int32)
    ids[:3] = (5, 6, 7)
    return SampleSpec(ids, seed=seed, temperature=1.0, top_k=0.9)


def _cycle(eng):
    eng.prefill_slots([(0, _spec(7)), (1, _spec(9))])
    for _ in range(32):
        pos, act = eng.step_chunk()
        if (pos[act] >= eng.image_seq_len).all():
            break
    else:
        raise AssertionError("decode never finished")
    out = eng.harvest([0, 1])
    eng.release([0, 1])
    return out


def _registry():
    from dalle_pytorch_tpu.training.metrics import MetricsRegistry

    return MetricsRegistry()


class TestEnginePolicyMode:
    def test_full_causal_policy_bit_identical_to_causal(self):
        """Policy mode on an unpatterned model: every bitmap is all-ones,
        so the whole serve cycle is bit-identical to the default engine —
        the parity anchor at engine level."""
        from dalle_pytorch_tpu.serving.engine import ContinuousEngine

        model, params = _build_model()
        kw = dict(model=model, variables=params, max_batch=2,
                  chunk_tokens=4, prefill_batch=2)
        causal = ContinuousEngine(registry=_registry(), **kw)
        policy = ContinuousEngine(
            registry=_registry(), decode_sparsity="policy", **kw
        )
        np.testing.assert_array_equal(_cycle(causal), _cycle(policy))

    def test_axial_policy_zero_recompile_and_counts(self):
        """Patterned model in policy mode: a warm serve cycle compiles
        ZERO programs (bitmaps are traced data) and the tile counters
        report real skips."""
        from dalle_pytorch_tpu.serving.engine import ContinuousEngine
        from dalle_pytorch_tpu.utils.compile_guard import assert_no_recompiles

        model, params = _build_model(
            attn_types=("full", "axial_row"), decode_sparse_block=4
        )
        eng = ContinuousEngine(
            model=model, variables=params, max_batch=2, chunk_tokens=4,
            prefill_batch=2, registry=_registry(), decode_sparsity="policy",
        )
        eng.warmup()
        with assert_no_recompiles():
            out = _cycle(eng)
        assert out.shape == (2, IMG_SEQ)
        detail = eng.sparsity_detail()
        assert detail["mode"] == "policy"
        assert detail["patterned_layers"] == 1
        assert detail["kv_tiles_skipped"] > 0
        assert detail["kv_tiles_read"] > 0
        read = eng.registry.get("dalle_serving_kv_tiles_read_total")
        assert int(read.value) == detail["kv_tiles_read"]

    @pytest.mark.slow
    def test_paged_policy_int8_zero_recompile(self):
        from dalle_pytorch_tpu.serving.engine import PagedContinuousEngine
        from dalle_pytorch_tpu.utils.compile_guard import assert_no_recompiles

        model, params = _build_model(
            attn_types=("full", "axial_row"), decode_sparse_block=4
        )
        eng = PagedContinuousEngine(
            model=model, variables=params, max_batch=2, chunk_tokens=4,
            prefill_batch=2, page_size=4, registry=_registry(),
            decode_sparsity="policy", kv_dtype="int8",
        )
        eng.warmup()
        with assert_no_recompiles():
            out = _cycle(eng)
        assert out.shape == (2, IMG_SEQ)
        assert eng.sparsity_detail()["kv_tiles_skipped"] > 0

    @pytest.mark.slow
    def test_sharded_full_causal_policy_parity(self):
        from dalle_pytorch_tpu.serving.engine import ContinuousEngine
        from dalle_pytorch_tpu.serving.sharded import ShardedContinuousEngine

        model, params = _build_model()
        kw = dict(model=model, variables=params, max_batch=2,
                  chunk_tokens=4, prefill_batch=2)
        ref = ContinuousEngine(registry=_registry(), **kw)
        shp = ShardedContinuousEngine(
            registry=_registry(), mesh_shape="tp=2",
            decode_sparsity="policy", **kw,
        )
        np.testing.assert_array_equal(_cycle(ref), _cycle(shp))

    @pytest.mark.slow
    def test_sharded_paged_axial_policy_zero_recompile(self):
        from dalle_pytorch_tpu.serving.sharded import (
            ShardedPagedContinuousEngine,
        )
        from dalle_pytorch_tpu.utils.compile_guard import assert_no_recompiles

        model, params = _build_model(
            attn_types=("full", "axial_row"), decode_sparse_block=4
        )
        eng = ShardedPagedContinuousEngine(
            model=model, variables=params, max_batch=2, chunk_tokens=4,
            prefill_batch=2, page_size=4, registry=_registry(),
            mesh_shape="tp=2", decode_sparsity="policy",
        )
        eng.warmup()
        with assert_no_recompiles():
            out = _cycle(eng)
        assert out.shape == (2, IMG_SEQ)
        assert eng.sparsity_detail()["kv_tiles_skipped"] > 0
