"""Prefetcher: input/compute overlap, error propagation, early close."""

import time

import pytest

from dalle_pytorch_tpu.data.prefetch import Prefetcher


def slow_producer(n, delay):
    for i in range(n):
        time.sleep(delay)
        yield i


class TestPrefetcher:
    def test_order_and_completion(self):
        out = list(Prefetcher(range(10), transform=lambda x: x * 2))
        assert out == [x * 2 for x in range(10)]

    def test_overlap(self):
        """Producer and consumer sleeps overlap: total ~= max, not sum."""
        n, delay = 8, 0.05
        pf = Prefetcher(slow_producer(n, delay), depth=2)
        t0 = time.perf_counter()
        count = 0
        for _ in pf:
            time.sleep(delay)  # consumer "compute"
            count += 1
        total = time.perf_counter() - t0
        assert count == n
        # serial would be >= 2*n*delay = 0.8s; overlapped ~ n*delay + delay
        assert total < 1.6 * n * delay, f"no overlap: {total:.3f}s"

    def test_wait_fraction_bounds(self):
        pf = Prefetcher(slow_producer(4, 0.03))
        for _ in pf:
            pass
        assert 0.0 <= pf.wait_fraction <= 1.0
        # consumer did no work, so it mostly waited
        assert pf.wait_fraction > 0.5

    def test_error_propagates(self):
        def bad():
            yield 1
            raise RuntimeError("boom")

        pf = Prefetcher(bad())
        assert next(pf) == 1
        with pytest.raises(RuntimeError, match="boom"):
            for _ in pf:
                pass

    def test_transform_error_propagates(self):
        pf = Prefetcher([1, 2], transform=lambda x: 1 // 0)
        with pytest.raises(ZeroDivisionError):
            list(pf)

    def test_close_mid_stream(self):
        pf = Prefetcher(slow_producer(100, 0.01), depth=2)
        next(pf)
        pf.close()  # must not hang or leak the thread
        assert not pf._thread.is_alive()
