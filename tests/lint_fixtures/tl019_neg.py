"""TL019 negatives: matching specs, unknowns, and cold paths."""

import jax
from jax.sharding import PartitionSpec as P

from dalle_pytorch_tpu.parallel.mesh import make_mesh, shard_map


def _impl(x):
    return x


def _k(rows):
    return rows


mesh = make_mesh()

run_tp = jax.jit(
    _impl,
    in_shardings=(P(None, "tp"),),
    out_shardings=P(None, "tp"),
)

kernel = shard_map(_k, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"))


# tracelint: hotloop
def step(batch):
    # placement matches the program's declared input: no reshard
    x = jax.device_put(batch, P(None, "tp"))
    return run_tp(x)


# tracelint: hotloop
def opaque(batch, sharding):
    # symbol vs literal: UNKNOWN, the lint stays silent
    y = jax.device_put(batch, sharding)
    return run_tp(y)


def cold(batch):
    # mismatch, but not hotloop-reachable: a one-off reshard is fine
    z = jax.device_put(batch, P("dp"))
    return run_tp(z)


# tracelint: hotloop
def unplaced(batch):
    # no recorded placement for `batch`: nothing to compare
    return kernel(batch)
