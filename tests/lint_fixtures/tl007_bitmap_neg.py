"""TL007 negative (block-sparse decode): the bitmap rides as TRACED data
— the engine derives it host-side per chunk and threads it in as an
argument (models/dalle.py:_with_block_bitmap), so inside the scan body it
is already a tracer; or it is built ONCE outside the body and closed over
as a device array. Both are the shipped pattern and must stay clean."""

import numpy as np
import jax.numpy as jnp
from jax import lax


def chunk_traced_bitmap(state, toks, block_bitmap):
    def body_traced_bitmap(carry, tok):
        rows = jnp.asarray(block_bitmap)  # traced argument, not a constant
        return carry + rows[0, 0, 0], tok

    return lax.scan(body_traced_bitmap, state, toks)


def chunk_hoisted_bitmap(state, toks):
    bitmap = jnp.asarray(np.ones((16, 8, 16), np.int32))  # once, closed over

    def body_hoisted_bitmap(carry, tok):
        return carry + bitmap[0, 0, 0], tok

    return lax.scan(body_hoisted_bitmap, state, toks)
