"""TL002 positive: device->host syncs under tracing and in hot loops."""

import jax
import numpy as np
from jax import lax


@jax.jit
def sync_in_jit(x):
    host = np.asarray(x)  # numpy inside jit: pulled off-device every call
    return host.item()  # .item() is a sync


@jax.jit
def cast_in_jit(x):
    return float(x.sum())  # float() concretizes the tracer


def scan_with_sync(xs):
    def body(carry, x):
        return carry + x, x.tolist()  # .tolist() inside a scan body

    return lax.scan(body, 0.0, xs)


class Engine:
    # tracelint: hotloop
    def step(self):
        pos = np.asarray(self._state["pos"])  # implicit sync on engine state
        jax.device_get(self._state)  # explicit sync, still needs a reason
        self._state["row"].block_until_ready()  # stall in the hot loop
        return pos
