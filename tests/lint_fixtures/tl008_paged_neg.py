"""TL008 negative fixture (paged-pool clause): head-axis pool splits,
partial-wrapped kernels with whole page axes, and non-paged callables
that are free to shard their leading axis — all silent."""

from functools import partial

from dalle_pytorch_tpu.ops.pallas_decode import (
    paged_decode_attention,
    paged_flash_decode_attention,
)
from dalle_pytorch_tpu.parallel.mesh import make_mesh
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

mesh = make_mesh(dp=2, tp=4)


def body(q, k, v):
    return q + k + v


# pools split on the HEAD axis (position 1) — the sanctioned layout
ok_head_split = shard_map(
    paged_flash_decode_attention,
    mesh=mesh,
    in_specs=(
        P(None, "tp", None),
        P(None, "tp", None, None),
        P(None, "tp", None, None),
    ),
    out_specs=P(None, "tp", None),
)

ok_partial = shard_map(
    partial(paged_decode_attention, page_size=64),
    mesh=mesh,
    in_specs=(
        P(None, "tp", None),
        P(None, "tp", None, None),
        P(None, "tp", None, None),
    ),
    out_specs=P(None, "tp", None),
)

# a non-paged callable may shard whatever leading axis it likes
ok_other_fn = shard_map(
    body,
    mesh=mesh,
    in_specs=(P("tp", None), P("tp", None), P("tp", None)),
    out_specs=P("tp", None),
)

# in_specs built elsewhere (not a literal tuple): silent by design
SPECS = (P(None, "tp", None), P(None, "tp", None, None), P(None, "tp", None, None))
ok_indirect = shard_map(
    paged_decode_attention, mesh=mesh, in_specs=SPECS, out_specs=P(None, "tp", None),
)
