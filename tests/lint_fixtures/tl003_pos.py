"""TL003 positive: donated buffers read after the donating dispatch."""

import jax


def _chunk_builder(model, key):
    def fn(state):
        return state

    return fn


_chunk_builder._donate_argnums = (0,)


def _jit_sample(builder, model, key, *args):
    return builder(model, key)(*args)


def chunk(state):
    # wrapper donating its own param via the builder dispatch idiom
    return _jit_sample(_chunk_builder, None, (), state)


step = jax.jit(lambda s: s, donate_argnums=(0,))


def read_after_wrapper_donation(state):
    new = chunk(state)  # state's buffers are donated here...
    pos = state["img_pos"]  # ...so this reads an invalidated buffer
    return new, pos


def read_after_jit_donation(state):
    out = step(state)  # direct jax.jit(donate_argnums=...) dispatch
    return out, state["row"]  # read of the donated arg


def donate_then_return(state):
    _ = chunk(state)
    return state  # returning the dead buffer is a read too
