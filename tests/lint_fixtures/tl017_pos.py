"""TL017 positives: mesh-aware jit programs without pinned out_shardings.

Never executed — parsed by tests/test_shardlint.py only.
"""

import jax


class ShardedEngine:
    def _chunk_op(self, s):
        fn = self._sharded_program(
            "chunk",
            lambda: jax.jit(  # TL017: ladder program, no out_shardings pin
                self._chunk_builder(),
                donate_argnums=(1,),
            ),
        )
        return fn(self.variables, s)

    def _release_op(self, s, mask):
        fn = self._sharded_program(
            "release",
            lambda: jax.jit(  # TL017: ladder program, no out_shardings pin
                self._release_builder(),
                donate_argnums=(0,),
            ),
        )
        return fn(s, mask)


def make_step(fn, state_shardings):
    # TL017: declares where inputs live and donates, but lets GSPMD pick
    # the output layout per dispatch
    return jax.jit(
        fn,
        donate_argnums=(0,),
        in_shardings=(state_shardings,),
    )
