"""TL019 positives: hot-path values placed under one spec, consumed under
another.

Never executed — parsed by tests/test_shardlint.py only.
"""

import jax
from jax.sharding import PartitionSpec as P

from dalle_pytorch_tpu.parallel.mesh import make_mesh, shard_map


def _impl(x):
    return x


def _k(rows):
    return rows


mesh = make_mesh()

run_tp = jax.jit(
    _impl,
    in_shardings=(P(None, "tp"),),
    out_shardings=P(None, "tp"),
)

kernel = shard_map(_k, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"))

STATE = jax.device_put(init(), P(None, "tp"))  # noqa: F821


# tracelint: hotloop
def step(batch):
    x = jax.device_put(batch, P("dp"))
    return run_tp(x)  # TL019: placed dp, program wants (None, tp)


# tracelint: hotloop
def scatter(rows):
    y = jax.device_put(rows, P(None, "tp"))
    return kernel(y)  # TL019: placed (None, tp), shard_map wants dp


def _drain():
    return kernel(STATE)  # TL019: module placement (None, tp) vs dp


# tracelint: hotloop
def hot_outer():
    while more():  # noqa: F821
        _drain()
