"""TL001 positive: Python control flow on traced parameters. Never
executed — tracelint parses it; pytest ignores non-test_ files."""

import functools

import jax
from jax import lax


@jax.jit
def branch_on_param(x):
    if x > 0:  # branching on a tracer: ConcretizationTypeError at runtime
        return x
    return -x


@jax.jit
def loop_on_param(x):
    while x.sum() < 10:  # while on a tracer
        x = x + 1
    return x


@functools.partial(jax.jit, static_argnums=(1,))
def assert_on_traced(x, n):
    assert x.mean() > 0  # assert on the TRACED arg (n is the static one)
    return x * n


def scan_caller(xs):
    def body(carry, x):
        if carry > 0:  # scan-body carry is always traced
            carry = carry + x
        return carry, carry

    return lax.scan(body, 0.0, xs)


@jax.jit
def alias_flow(x):
    y = x + 1  # y aliases a traced value...
    if y.any():  # ...so branching on it is the same hazard
        return y
    return x
