"""TL008 positive fixture: partition specs naming axes the enclosing
mesh does not define. The mesh is bound from a LITERAL axis tuple, so
the rule can resolve its vocabulary ("data", "model")."""

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("data", "model"))


def body(q, k):
    return q + k


sharded = shard_map(
    body,
    mesh=mesh,
    # "dp" belongs to the 4-axis make_mesh vocabulary, not THIS mesh
    in_specs=(P("data", "model"), P("dp", None)),
    out_specs=P("data", "tensor"),  # "tensor" is nobody's axis
)

# divisibility asserted so TL020 stays out: this fixture pins TL008 only
assert ROWS % 8 == 0  # noqa: F821

# the classic rename drift: "model" misspelled survives until trace time
sharding = NamedSharding(mesh, P("modle"))
