"""Suppression fixtures: a reasoned suppression hides the finding; a
bare one does not (and is itself a TL000 finding)."""

import jax
import numpy as np


@jax.jit
def justified(x):
    # the sync below is deliberate and explained: suppressed cleanly
    return np.asarray(x)  # tracelint: disable=TL002 -- fixture: demonstrating a reasoned suppression
