"""TL002 negative: host-side numpy on host data, and device work kept on
device under tracing."""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@jax.jit
def stays_on_device(x):
    return jnp.asarray(x) + jnp.sum(x)  # jnp, not np: stays traced


def host_prep(specs):
    # np on host-side request data is ordinary batch assembly, not a sync
    seeds = np.asarray([s.seed for s in specs], np.int32)
    return np.stack([s.ids for s in specs]), seeds


class Engine:
    # tracelint: hotloop
    def admit(self, spec):
        # np.asarray on REQUEST data (not engine state) is host-side prep
        text = np.asarray(spec.text_ids, np.int32)
        return self.dispatch(text)


def scan_caller(xs):
    def body(carry, x):
        return carry + x, carry

    return lax.scan(body, 0.0, xs)
