"""TL009 negative fixture: every safe shape — finally-protected ends,
except-handler ends, the context manager, cross-function begin/end (the
batcher's cross-thread idiom), and non-trace `.begin()` receivers."""

import contextlib


def finally_protected(trace, work):
    span = trace.begin("respond")
    try:
        work()
    finally:
        trace.end(span)


def except_plus_success_path(trace, work):
    # the serving HTTP handler's shape: error path ends with error=...,
    # success path ends in straight-line code after the try
    span = trace.begin("respond")
    try:
        payload = work()
    except Exception as exc:
        trace.end(span, error=repr(exc))
        raise
    trace.end(span)
    return payload


def context_manager(trace, work):
    with trace.span("chunk"):
        work()


def cross_function_begin(trace):
    # the batcher idiom: the queue span begins here and ends on the
    # worker thread in another function — no same-function end, silent
    return trace.begin("queue")


def not_a_tracer(cursor, work):
    txn = cursor.begin("txn")  # receiver names no trace: out of scope
    work()
    cursor.end(txn)


def nested_finally(trace, work):
    span = trace.begin("harvest")
    try:
        with contextlib.suppress(ValueError):
            work()
    finally:
        trace.end(span, slots=1)
