"""Regression fixture — PR 7's sampler-vs-/healthz race, as shipped
before the review-hardening round: the vitals sampler thread appended
stall records to a plain deque with NO lock while the /healthz handler
thread iterated it (`RuntimeError: deque mutated during iteration`).
TL014 must flag the iteration (mutations unguarded too)."""

import collections
import threading


class StallWatchdog:
    def __init__(self):
        self._recent = collections.deque(maxlen=16)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            stall = self._check()
            if stall is not None:
                self._recent.append(stall)  # sampler thread, lock-free

    def _check(self):
        return None

    def recent_stalls(self):
        # the /healthz handler thread called this mid-append
        return [dict(s) for s in self._recent]  # TL014
