"""Regression fixture — PR 9's collector read race, as shipped before
its review-hardening round: `POST /ingest` handler threads mutated the
bundle dict under the lock while `GET /traces` iterated the LIVE dict
outside it. The class has no worker thread of its own — the concurrency
is handler fan-in, declared with `# tracelint: threads` (each public
method is its own concurrent root). TL014 must flag the read."""

import threading


# tracelint: threads
class TraceCollector:
    def __init__(self):
        self._lock = threading.Lock()
        self._bundles = {}

    def ingest(self, record):
        with self._lock:
            self._bundles[record["trace_id"]] = record

    def traces(self, n=None):
        # GET /traces iterated the live dict with no lock
        out = [b for b in self._bundles.values()]  # TL014
        return out[:n] if n else out
