"""TL013 positive fixture: shared state compound-written on one thread
root and touched on another with no common lock. Three findings:

1. `_counter`: augassign on the worker thread, no lock at all, read by
   the caller-root `snapshot()`.
2. `_errors`: augassign under the lock on the worker, but `snapshot()`
   reads it lock-free — one side guarded is not guarded.
3. `_backlog`: container mutation on the worker, no lock, read (len) by
   the caller root.
"""

import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._counter = 0
        self._errors = 0
        self._backlog = []
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            self._counter += 1  # TL013: unguarded vs snapshot()'s read
            with self._lock:
                self._errors += 1  # TL013: snapshot() reads without the lock
            self._backlog.append(self._counter)  # TL013: unguarded mutation

    def snapshot(self):
        return (self._counter, self._errors, len(self._backlog))
