"""TL013 negative fixture: the same worker/caller shapes, disciplined.

* `_counter`: both sides under one lock.
* `_running`: the GIL-atomic flag idiom — plain write-only rebind in
  `stop()`, plain read in the worker loop — exempt by design.
* `_config`: written only in `__init__` (construction happens-before
  thread start), read everywhere: clean.
* `_pending`: check-then-act, but entirely under the lock.
* `_helper_total`: compound-written in a private helper whose only call
  site holds the lock — the inherited-lock pass must keep this clean.
"""

import threading


class Worker:
    def __init__(self, config):
        self._lock = threading.Lock()
        self._counter = 0
        self._running = True
        self._config = dict(config)
        self._pending = None
        self._helper_total = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while self._running:
            with self._lock:
                self._counter += 1
                self._bump()
                if self._pending is not None:
                    self._pending = None

    def _bump(self):
        # caller holds the lock (inherited-lock pass)
        self._helper_total += len(self._config)

    def request(self):
        with self._lock:
            self._pending = object()

    def stop(self):
        self._running = False

    def snapshot(self):
        with self._lock:
            return (self._counter, self._helper_total)
