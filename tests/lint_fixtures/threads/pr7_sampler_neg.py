"""Regression fixture — PR 7's shipped fix: the ring gained a lock;
appends happen under it and `recent_stalls()` snapshots under it before
iterating. Clean."""

import collections
import threading


class StallWatchdog:
    def __init__(self):
        self._lock = threading.Lock()
        self._recent = collections.deque(maxlen=16)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            stall = self._check()
            if stall is not None:
                with self._lock:
                    self._recent.append(stall)

    def _check(self):
        return None

    def recent_stalls(self):
        with self._lock:
            snap = list(self._recent)
        return [dict(s) for s in snap]
