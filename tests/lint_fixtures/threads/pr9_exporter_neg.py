"""Regression fixture — PR 9's shipped exporter fix: every counter
mutation happens under the lock, and `detail()` snapshots them under it
too. Clean."""

import collections
import threading


class TraceExporter:
    def __init__(self):
        self._lock = threading.Lock()
        self._buf = collections.deque()
        self.traces_sent = 0
        self.dropped = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            batch = None
            with self._lock:
                if self._buf:
                    batch = self._buf.popleft()
            if batch is None:
                continue
            ok = self._post(batch)
            with self._lock:
                if ok:
                    self.traces_sent += 1
                else:
                    self.dropped += 1

    def _post(self, batch):
        return batch is not None

    def export(self, trace):
        with self._lock:
            self._buf.append(trace)

    def detail(self):
        with self._lock:
            return {"sent": self.traces_sent, "dropped": self.dropped}
