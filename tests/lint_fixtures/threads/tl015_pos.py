"""TL015 positive fixture: two lock-order inversions, each reported
ONCE (one finding per cycle).

1. `Router`: `dispatch()` nests state_lock -> seed_lock, `reseed()`
   nests them the other way round.
2. `Spool`: the inversion hides one hop away — `flush()` holds `_a` and
   calls `_drain()` which acquires `_b`, while `park()` nests `_b` ->
   `_a` directly.
"""

import threading


class Router:
    def __init__(self):
        self._state_lock = threading.Lock()
        self._seed_lock = threading.Lock()
        self.seed = 0

    def dispatch(self):
        with self._state_lock:
            with self._seed_lock:  # TL015: opposite order vs reseed()
                return self.seed

    def reseed(self):
        with self._seed_lock:
            with self._state_lock:
                self.seed += 1


class Spool:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.entries = []

    def flush(self):
        with self._a:
            self._drain()  # TL015: _drain takes _b while _a is held

    def _drain(self):
        with self._b:
            self.entries.clear()

    def park(self):
        with self._b:
            with self._a:
                self.entries.append(object())
