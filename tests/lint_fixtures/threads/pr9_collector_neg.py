"""Regression fixture — PR 9's shipped collector fix: read endpoints
iterate SNAPSHOTS taken under the lock. Clean."""

import threading


# tracelint: threads
class TraceCollector:
    def __init__(self):
        self._lock = threading.Lock()
        self._bundles = {}

    def ingest(self, record):
        with self._lock:
            self._bundles[record["trace_id"]] = record

    def traces(self, n=None):
        with self._lock:
            snap = list(self._bundles.values())
        out = [b for b in snap]
        return out[:n] if n else out
