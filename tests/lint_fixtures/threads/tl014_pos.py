"""TL014 positive fixture: shared containers mutated under the lock on
the worker thread, iterated lock-free from caller-root methods. Three
findings — a comprehension, a list(...items()) snapshot call, and a
`for` loop."""

import collections
import threading


class Ring:
    def __init__(self):
        self._lock = threading.Lock()
        self._spans = []
        self._index = {}
        self._rows = collections.deque()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            with self._lock:
                self._spans.append(object())
                self._index[len(self._spans)] = object()
                self._rows.append(object())

    def export(self):
        return [s for s in self._spans]  # TL014: iterate outside the lock

    def dump(self):
        return list(self._index.items())  # TL014: snapshot call, no lock

    def tail(self):
        out = []
        for r in self._rows:  # TL014: for-loop outside the lock
            out.append(r)
        return out
