"""TL014 negative fixture: the shipped fixes.

* `export()` snapshots under the lock and iterates the snapshot — the
  canonical fix.
* the worker iterating its OWN container lock-free is single-threaded
  with respect to its mutations: silent.
* `replace()` swaps the whole list by plain rebind (not a mutation), so
  a lock-free iteration elsewhere reads a consistent snapshot object.
"""

import threading


class Ring:
    def __init__(self):
        self._lock = threading.Lock()
        self._spans = []
        self._latest = []
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            with self._lock:
                self._spans.append(object())
            for s in self._spans:  # same-root iteration: silent
                _ = s
            self._latest = [object(), object()]  # whole-object rebind

    def export(self):
        with self._lock:
            snap = list(self._spans)
        return [s for s in snap]

    def recent(self):
        return [x for x in self._latest]  # iterates a rebind snapshot
