"""TL015 negative fixture: consistent lock ordering.

* every nesting in the file takes `_a` before `_b`;
* `after()` calls a `_b`-acquiring helper AFTER its `with self._a:`
  block closed — sequential acquisition, not nesting;
* Condition(self._a) aliases `_a`, so nesting `_cond` inside `_a` is a
  reentrant acquisition of the SAME mutex, not a second lock (and never
  an edge).
"""

import threading


class Router:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._cond = threading.Condition(self._a)
        self.entries = []

    def dispatch(self):
        with self._a:
            with self._b:
                return len(self.entries)

    def flush(self):
        with self._a:
            self._drain()

    def _drain(self):
        with self._b:
            self.entries.clear()

    def after(self):
        with self._a:
            self.entries.append(object())
        self._take_b()

    def _take_b(self):
        with self._b:
            self.entries.clear()

    def nudge(self):
        with self._a:
            with self._cond:
                self._cond.notify_all()
