"""Regression fixture — PR 14's shipped fix: the worker CLAIMS the
export request under the queue lock before serving, so a timed-out
caller's withdraw either fully wins or fully loses. Clean."""

import threading


class ExportQueue:
    def __init__(self):
        self._cond = threading.Condition()
        self._pending_export = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            claim = None
            with self._cond:
                if self._pending_export is not None:
                    claim, self._pending_export = self._pending_export, None
            if claim is not None:
                self._serve(claim)

    def _serve(self, claim):
        del claim

    def request_export(self):
        with self._cond:
            self._pending_export = object()

    def withdraw(self):
        with self._cond:
            self._pending_export = None
