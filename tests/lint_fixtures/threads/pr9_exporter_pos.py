"""Regression fixture — PR 9's exporter-counter race, as shipped before
its review-hardening round: the shipper thread bumped delivery counters
lock-free while `export()` (request threads) bumped the drop counter and
`detail()` read them. Two TL013 findings (one per counter)."""

import collections
import threading


class TraceExporter:
    def __init__(self):
        self._lock = threading.Lock()
        self._buf = collections.deque()
        self.traces_sent = 0
        self.dropped = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            batch = None
            with self._lock:
                if self._buf:
                    batch = self._buf.popleft()
            if batch is None:
                continue
            if not self._post(batch):
                self.dropped += 1  # TL013: shipper thread, no lock
            else:
                self.traces_sent += 1  # TL013: racing detail()'s read

    def _post(self, batch):
        return batch is not None

    def export(self, trace):
        with self._lock:
            self._buf.append(trace)

    def detail(self):
        return {"sent": self.traces_sent, "dropped": self.dropped}
