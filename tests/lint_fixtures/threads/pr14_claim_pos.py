"""Regression fixture — PR 14's export-withdraw claim race, as shipped
before its review-hardening round: the batcher worker served a pending
checkpoint export with a lock-free check-then-act on the request slot,
so a timed-out caller's `withdraw()` could clear the slot between the
worker's check and its destructive serve — a nobody-asked migration.
TL013 must flag the worker's unguarded claim."""

import threading


class ExportQueue:
    def __init__(self):
        self._cond = threading.Condition()
        self._pending_export = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            # check-then-act outside the lock: withdraw() can win the
            # race between the check and the destructive serve
            if self._pending_export is not None:
                bundle = self._serve()
                self._pending_export = None  # TL013: unguarded claim
                del bundle

    def _serve(self):
        return object()

    def request_export(self):
        with self._cond:
            self._pending_export = object()

    def withdraw(self):
        with self._cond:
            self._pending_export = None
