"""TL007 positive: large host constants materialized inside lax.scan
bodies. Never executed — tracelint parses it; pytest ignores non-test_
files."""

import numpy as np
import jax.numpy as jnp
from jax import lax

BIG_MASK = np.tril(np.ones((512, 512)))  # ~262k elements, module level


def direct_ctor(xs):
    def body_direct_ctor(carry, x):
        table = jnp.asarray(np.arange(100_000))  # staged per trace
        return carry + table[0], x

    return lax.scan(body_direct_ctor, 0.0, xs)


def module_const(xs):
    def body_module_const(carry, x):
        mask = jnp.array(BIG_MASK)  # the module constant re-wrapped per trace
        return carry + mask[0, 0], x

    return lax.scan(body_module_const, 0.0, xs)


def comparison_const(xs):
    def body_comparison_const(carry, x):
        blocked = jnp.asarray(np.arange(66_000) < 50_000)  # vocab-range mask
        return carry + blocked[0], x

    return lax.scan(body_comparison_const, 0.0, xs)
