"""TL007 negative: scan-body constant patterns that are fine — small
constants (below the size heuristic), constants hoisted OUT of the body,
unknown-size wraps of traced data, and host code outside any scan."""

import numpy as np
import jax.numpy as jnp
from jax import lax

SMALL = np.arange(16)


def small_constant(xs):
    def body_small_constant(carry, x):
        t = jnp.asarray(np.arange(8))  # tiny: below the size heuristic
        return carry + t[0] + jnp.asarray(SMALL)[0], x

    return lax.scan(body_small_constant, 0.0, xs)


def hoisted(xs):
    table = jnp.asarray(np.arange(100_000))  # built ONCE, closed over

    def body_hoisted(carry, x):
        return carry + table[0], x

    return lax.scan(body_hoisted, 0.0, xs)


def strided_arange(xs):
    def body_strided_arange(carry, x):
        # 1000 elements despite the huge stop: the step divides the span
        t = jnp.asarray(np.arange(0, 1_000_000, 1000))
        return carry + t[0], x

    return lax.scan(body_strided_arange, 0.0, xs)


def traced_wrap(xs):
    def body_traced_wrap(carry, x):
        y = jnp.asarray(x)  # traced data, not a host constant
        return carry + y, x

    return lax.scan(body_traced_wrap, 0.0, xs)


def host_function():
    # the same expression OUTSIDE a scan body stages once per call site
    return jnp.asarray(np.arange(100_000))
