"""TL007 positive (block-sparse decode): the KV-tile bitmap materialized
INSIDE the chunk scan body. The serving contract (serving/sparsity.py)
ships policy bitmaps as TRACED data precisely so admission, retirement,
and policy swaps never compile; wrapping the host table inside the body
captures it into the trace and re-stages it on every retrace — every
policy change becomes a compile. Never executed — tracelint parses it;
pytest ignores non-test_ files."""

import numpy as np
import jax.numpy as jnp
from jax import lax

# [depth, max_batch, n_blocks] policy table, host-side (2048 elements)
BLOCK_BITMAP = np.ones((16, 8, 16), np.int32)


def chunk_module_bitmap(state, toks):
    def body_module_bitmap(carry, tok):
        bitmap = jnp.asarray(BLOCK_BITMAP)  # host table re-wrapped per trace
        return carry + bitmap[0, 0, 0], tok

    return lax.scan(body_module_bitmap, state, toks)


def chunk_inline_bitmap(state, toks):
    def body_inline_bitmap(carry, tok):
        bitmap = jnp.asarray(np.ones((32, 8, 16), np.int32))  # staged inline
        return carry + bitmap[0, 0, 0], tok

    return lax.scan(body_inline_bitmap, state, toks)
