"""TL020 negatives: guarded, replicated, or unresolvable placements."""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from dalle_pytorch_tpu.parallel.partition import _divisible

GLOBAL_MESH = build_mesh()  # noqa: F821


def guarded_params(mesh, params):
    # routed through the shared fallback: non-dividing dims replicate
    return jax.tree.map(
        lambda leaf: NamedSharding(
            mesh, _divisible(P(None, "tp"), leaf.shape, mesh)
        ),
        params,
    )


def asserted_batch(mesh, x):
    # divisibility is checked explicitly before placing
    assert x.shape[0] % mesh.shape["dp"] == 0
    return jax.device_put(x, NamedSharding(mesh, P("dp")))


def replicated(mesh, x):
    # P() splits nothing: no divisibility assumption to make
    return jax.device_put(x, NamedSharding(mesh, P()))


def from_variable(mesh, x, spec):
    # spec is opaque: the lint cannot see named axes, stays silent
    return jax.device_put(x, NamedSharding(mesh, spec))
