"""A suppression WITHOUT a reason: the TL002 finding still fires, and the
bare suppression adds a TL000 on top — silent opt-outs cannot accumulate."""

import jax
import numpy as np


@jax.jit
def unjustified(x):
    return np.asarray(x)  # tracelint: disable=TL002
