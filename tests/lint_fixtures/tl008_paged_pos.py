"""TL008 positive fixture (paged-pool clause): `shard_map` wrapping a
paged decode kernel whose pool specs (in_specs positions 1/2) lead with
a mesh axis — splitting the PAGE axis, the host allocator's addressing
unit. Axis names are all valid for the factory mesh, so ONLY the
page-axis findings fire here."""

from functools import partial

from dalle_pytorch_tpu.ops.pallas_decode import (
    paged_decode_attention,
    paged_flash_decode_attention,
)
from dalle_pytorch_tpu.parallel.mesh import make_mesh
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

mesh = make_mesh(dp=2, tp=4)

bad_direct = shard_map(
    paged_flash_decode_attention,
    mesh=mesh,
    in_specs=(
        P(None, "tp", None),               # q: head split, fine
        P("tp", None, None, None),         # k_pages: PAGE axis split
        P(("dp", "tp"), None, None, None),  # v_pages: page axis in a group
    ),
    out_specs=P(None, "tp", None),
)

bad_partial = shard_map(
    partial(paged_decode_attention, page_size=64),
    mesh=mesh,
    in_specs=(
        P(None, "tp", None),
        P("tp", None, None, None),  # k_pages: page axis again
        P(None, "tp", None, None),  # v_pages: head split, fine
    ),
    out_specs=P(None, "tp", None),
)
