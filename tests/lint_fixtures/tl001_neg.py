"""TL001 negative: control flow that is fine under tracing — static
arguments, shape/dtype facts, and plain host functions."""

import functools

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.jit, static_argnums=(1,))
def branch_on_static(x, n):
    if n > 2:  # n is static: concrete at trace time
        return x * n
    return x


@functools.partial(jax.jit, static_argnames=("training",))
def branch_on_static_name(x, training):
    if training:  # static by name
        return x * 2
    return x


@jax.jit
def branch_on_shape(x):
    if x.shape[0] > 4:  # shapes are static under tracing
        return x[:4]
    if x.ndim == 2 and len(x) > 0:  # so are ndim / len / isinstance
        return x
    assert x.dtype == jnp.float32  # and dtype facts
    return x


def host_function(x):
    if x > 0:  # not traced: ordinary Python is ordinary Python
        return x
    return -x


def scan_caller(xs):
    def body(carry, x):
        return carry + x, jnp.where(x > 0, x, carry)  # data-dependent via where

    return lax.scan(body, 0.0, xs)
