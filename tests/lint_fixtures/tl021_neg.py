"""TL021 negatives: replicated leaves, cold paths, and unknown placements."""

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

STATE = jax.device_put(build(), P(None, "tp"))  # noqa: F821
REPLICATED = jax.device_put(ready(), P())  # noqa: F821
OPAQUE = jax.device_put(thing(), host_shardings)  # noqa: F821


# tracelint: hotloop
def replicated_read():
    # every device holds the full value: the read is shard-local
    return np.asarray(REPLICATED)


# tracelint: hotloop
def unknown_placement():
    # symbolic sharding: UNKNOWN, the lint stays silent
    return np.asarray(OPAQUE)


def cold_snapshot():
    # not hotloop-reachable: a one-off debug gather is fine
    return np.asarray(STATE)


# tracelint: hotloop
def unplaced(batch):
    # no recorded placement for `batch`: nothing to flag
    return np.asarray(batch)
