"""TL002 cross-procedural positive: host syncs inside `_*_impl` bodies
called only from jitted code — the sync fires on every traced call even
though the helper itself carries no jit decorator."""

import jax
import numpy as np


def _pull_impl(x):
    v = np.asarray(x)  # host pull under inherited tracing
    return x + v.mean()


def _item_impl(x):
    return x.item()  # forces a sync under inherited tracing


@jax.jit
def entry(x):
    return _pull_impl(x) + _item_impl(x)
