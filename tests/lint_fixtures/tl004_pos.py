"""TL004 positive: one PRNG key feeding two draws with no split between."""

import jax


def double_draw(rng):
    a = jax.random.normal(rng, (3,))
    b = jax.random.uniform(rng, (3,))  # same key: a and b are correlated
    return a + b


def reuse_after_derive(rng):
    child = jax.random.fold_in(rng, 1)
    noise = jax.random.gumbel(child, (4,))
    more = jax.random.gumbel(child, (4,))  # child consumed twice
    return noise + more


def reuse_fresh_key():
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (2,))
    y = jax.random.bernoulli(key, 0.5, (2,))  # PRNGKey(0) drawn twice
    return x, y
