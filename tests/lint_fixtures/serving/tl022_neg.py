"""TL022 negative fixture: the label-hygiene shapes the rule must
trust — constant labels, small closed enums, and request data routed
through a bounding clamp before it reaches the registry."""

OTHER = "__other__"


def _bounded_tenant(tenant, seen, cap=32):
    """Charset/length clamp with an `__other__` overflow bucket — the
    UsageLedger pattern TL022's guard recognizes by name."""
    safe = "".join(c for c in str(tenant or "") if c.isalnum())[:64]
    if safe not in seen and len(seen) >= cap:
        return OTHER
    seen.add(safe)
    return safe


def constant_labels(metric):
    metric.labels("queue").observe(0.25)
    metric.labels("generate").observe(1.5)


def closed_enum_labels(metric, rep, reason):
    # replica names and ejection reasons come from config / a closed
    # set, not from request payloads
    metric.labels(rep.name).set(3)
    metric.labels(reason).inc()


def clamped_tenant(metric, body, seen):
    # routed through the bound: trusted even though `tenant` appears
    metric.labels(_bounded_tenant(body["tenant"], seen)).inc()


def opaque_local(metric, label):
    # an opaque local stays silent (false-negative bias): the rule
    # cannot see where `label` came from and does not guess
    metric.labels_extra(label, priority="bulk").inc()
