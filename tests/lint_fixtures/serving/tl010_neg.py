"""TL010 negative fixture: every safe retry shape — budget-gated,
backoff-gated, loop-exiting handlers, bare-except that re-raises, and
narrow handlers; none may fire."""

import time


def budget_gated(dispatch, budget):
    attempt = 0
    while True:
        try:
            return dispatch()
        except Exception:
            attempt += 1
            if not budget.withdraw():  # budget call: bounded retries
                raise


def backoff_gated(dispatch, stop):
    backoff_s = 0.1
    while not stop.is_set():
        try:
            return dispatch()
        except Exception:
            stop.wait(backoff_s)  # wait(): the loop cannot run hot
            backoff_s = min(backoff_s * 2, 5.0)


def sleep_in_loop_body(dispatch, log):
    while True:
        time.sleep(0.5)  # backoff anywhere in the loop body counts
        try:
            dispatch()
        except Exception as exc:
            log(exc)


def handler_exits_loop(dispatch, log):
    while True:
        try:
            return dispatch()
        except Exception as exc:
            log(exc)
            break  # failure ends the loop: not a retry loop


def bare_except_reraises(dispatch, cleanup):
    while True:
        try:
            return dispatch()
        except:  # noqa: E722 -- re-raised below, interrupts survive
            cleanup()
            raise


def base_exception_named_reraise(dispatch, cleanup):
    while True:
        try:
            return dispatch()
        except BaseException as exc:
            cleanup()
            raise exc  # named re-raise swallows nothing either


def narrow_handler(dispatch):
    while True:
        try:
            return dispatch()
        except ConnectionError:
            continue  # narrow catches are the caller's policy call


def try_outside_loop(dispatch, log):
    try:
        dispatch()
    except Exception as exc:
        log(exc)  # no enclosing while: nothing to amplify
