"""TL010 positive fixture (path carries `serving/`, so the rule is in
scope): broad handlers inside retry loops that swallow interrupts or
retry hot with no backoff/budget discipline."""

import time


def swallows_interrupt(dispatch):
    while True:
        try:
            return dispatch()
        except:  # noqa: E722 -- deliberately bare for the fixture
            time.sleep(0.1)  # backoff does not excuse eating Ctrl-C


def swallows_base_exception(dispatch, log):
    while True:
        try:
            return dispatch()
        except BaseException as exc:
            log(exc)  # no bare raise: shutdown sentinels die here
            time.sleep(0.1)


def hot_retry_no_backoff(dispatch, log):
    done = False
    while not done:
        try:
            dispatch()
            done = True
        except Exception as exc:
            log(exc)  # loops straight back into dispatch() — no
            continue  # sleep/wait/budget call anywhere in the loop
