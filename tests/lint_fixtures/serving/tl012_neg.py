"""TL012 negatives: boundary-guarded snapshots, and snapshots outside
serving loops — none of these may fire.
"""


def encode_checkpoint(cp, fp):  # stand-in for serving.migrate's codec
    return b""


class GuardedWorker:
    def run(self):
        while True:
            self.engine.step_chunk()
            if self._migrate_request is not None:
                # boundary guard: explicit migration handshake
                toks = self.engine.snapshot_rows(list(self.inflight))
                self.out = encode_checkpoint(toks, self.fingerprint)
            if self.spool is not None and self.chunk_index % 8 == 0:
                # cadence guard: %-expression
                self.beacon = self.engine.snapshot_rows(range(8))
            if self.beacon_due():
                # boundary guard by name
                self.beacon = encode_checkpoint(self.beacon, self.fp)

    def export_once(self):
        # not a loop: a one-shot admin export is the designed call shape
        toks = self.engine.snapshot_rows(list(self.inflight))
        return encode_checkpoint(toks, self.fingerprint)
