"""TL022 positive fixture (path carries `serving/`, so the rule is in
scope): request-scoped data flowing into metric label values — each
distinct value mints a new child series, unbounded over open traffic."""


def per_trace_series(metric, req):
    # trace IDs are unique per request: one series per request, forever
    metric.labels(req.trace_id).inc()


def raw_tenant_from_body(metric, body):
    # the raw tenant string arrives from the wire unclamped
    metric.labels(body["tenant"]).inc()


def user_kwarg_through_str(metric, user):
    # str() is a pass-through, not a bound: still one series per user
    metric.labels_extra("ok", who=str(user)).set(1)
