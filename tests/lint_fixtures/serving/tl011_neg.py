"""TL011 negatives: every serving-side jit flows through a recognized
warmup/AOT-export ladder shape."""

import jax


class CoveredEngine:
    def __init__(self):
        self._pixels_jit = None

    def decode(self, x):
        # lazily built, but `_capture_decode_cost` (a ladder-named
        # function) references the handle — the engine.py idiom
        if self._pixels_jit is None:
            self._pixels_jit = jax.jit(lambda t: t)
        return self._pixels_jit(x)

    def _capture_decode_cost(self):
        return self._pixels_jit

    def warmup(self):
        # constructed inside warmup(): compiled before traffic by
        # definition
        probe = jax.jit(lambda x: x - 1)
        self.decode(probe(0))


class ShardedLike:
    def _sharded_program(self, name, build):
        return build()

    def _chunk_op(self, s):
        # the sharded-engine memo: the jit is an argument of a
        # ladder-named call
        fn = self._sharded_program(
            "chunk",
            lambda: jax.jit(
                lambda v: v, out_shardings=self._state_shardings
            ),
        )
        return fn(s)
