"""TL011 positives: serving-side jit programs outside the warmup ladder.

Each of the three constructions below builds a compiled program that no
warmup/AOT-export ladder ever registers — after a warm-cache boot it
would cold-compile in the middle of live traffic.
"""

import jax

# module-level program used only by the serve path below
_scale = jax.jit(lambda x: x * 3)  # finding: never referenced by a ladder


class LeakyEngine:
    def __init__(self):
        # finding: handle `_hot` is never referenced by any
        # warmup/capture/register function
        self._hot = jax.jit(lambda x: x * 2)

    def serve(self, x):
        # finding: constructed mid-request, invoked immediately
        return jax.jit(lambda y: y + 1)(self._hot(x)) + _scale(x)
