"""TL016 positive fixture: blocking calls inside `with <lock>:` bodies.
Three findings — a sleep, an engine dispatch, and a thread join — while
the condition's own `wait()` (which releases the lock) stays silent."""

import threading
import time


class Batcher:
    def __init__(self, engine):
        self._cond = threading.Condition()
        self._lock = threading.Lock()
        self.engine = engine
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while True:
            with self._cond:
                time.sleep(0.01)  # TL016: parked with the lock held
                out = self.engine.step_chunk()  # TL016: dispatch under lock
                self._cond.wait(0.1)  # silent: releases the held lock
            self._retire(out)

    def _retire(self, out):
        del out

    def stop(self):
        with self._lock:
            self._thread.join()  # TL016: waits out a thread under a lock
