"""TL012 positives: unguarded decode-state snapshots in a serving loop.

Each flagged call reads or serializes decode state on the host on EVERY
iteration of the worker loop — a per-iteration device sync, the exact
stall class the chunk-boundary guard exists to prevent.
"""


def encode_checkpoint(cp, fp):  # stand-in for serving.migrate's codec
    return b""


class EagerWorker:
    def run(self):
        while True:
            self.engine.step_chunk()
            # finding: snapshot every iteration, no boundary guard
            toks = self.engine.snapshot_rows(list(self.inflight))
            # finding: serialization every iteration too
            blob = encode_checkpoint(toks, self.fingerprint)
            self.buf.append(blob)

    def drain_loop(self):
        while self.alive:
            if self.verbose:  # a guard, but not a BOUNDARY guard
                # finding: `verbose` names no boundary condition
                self.spool_rows = self.engine.snapshot_rows(range(8))
