"""TL016 negative fixture: the disciplined shapes.

* the lock protects only the bookkeeping; sleep / engine dispatch / the
  thread join all happen OUTSIDE the `with` block (the batcher's
  dispatch-lock timing idiom);
* the held condition's own `wait_for` releases the lock while parked;
* `", ".join(...)` under a lock is string work, not a thread join.
"""

import threading
import time


class Batcher:
    def __init__(self, engine):
        self._cond = threading.Condition()
        self.engine = engine
        self.names = []
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while True:
            with self._cond:
                self._cond.wait_for(lambda: bool(self.names), timeout=0.1)
                batch = list(self.names)
            out = self.engine.step_chunk(batch)  # dispatch OUTSIDE the lock
            time.sleep(0.01)
            with self._cond:
                label = ", ".join(str(x) for x in out)
            del label

    def stop(self):
        with self._cond:
            self.names.clear()
        self._thread.join()
