"""TL009 positive fixture: begin/end pairs in the same function with no
exception-path end — a raise between them leaks the span open until
finish() marks it abandoned."""


def straight_line(trace, work):
    span = trace.begin("respond")
    work()  # a raise here leaks the span
    trace.end(span)


def end_inside_unprotected_if(req, ok):
    span = req.trace.begin("harvest")
    if ok:
        req.trace.end(span)
    else:
        req.trace.end(span, error="bad")  # still straight-line code


def try_without_cleanup_path(trace, work):
    span = trace.begin("chunk")
    try:
        work()
    except ValueError:
        pass  # handler never ends the span; the success-path end
    trace.end(span)  # is not exception-reachable for other raises
