"""TL004 negative: correct key hygiene — split/fold_in between draws,
and per-scope single use."""

import jax


def split_between(rng):
    rng, sub = jax.random.split(rng)
    a = jax.random.normal(sub, (3,))
    rng, sub = jax.random.split(rng)  # rng rebound by the split
    b = jax.random.uniform(sub, (3,))
    return a + b


def fold_in_between(rng):
    a = jax.random.normal(jax.random.fold_in(rng, 0), (3,))
    b = jax.random.normal(jax.random.fold_in(rng, 1), (3,))  # distinct streams
    return a + b


def rebind_fresh(rng):
    x = jax.random.normal(rng, (2,))
    rng = jax.random.PRNGKey(7)  # brand-new key, not a reuse
    y = jax.random.normal(rng, (2,))
    return x, y


def numpy_random_is_not_a_key_api(mu):
    import numpy as np

    a = np.random.normal(mu, 0.1)  # first arg is a mean, not a PRNG key
    b = np.random.normal(mu, 0.2)
    return a + b


def loop_target_is_fresh(rng):
    keys = jax.random.split(rng, 4)
    out = []
    for key in keys:  # each iteration binds a fresh key: the standard idiom
        out.append(jax.random.normal(key, (2,)))
    return out


def single_use_per_scope(rng):
    def inner(key):
        return jax.random.gumbel(key, (2,))  # its own scope, its own use

    return jax.random.normal(rng, (2,)) + inner(rng)
