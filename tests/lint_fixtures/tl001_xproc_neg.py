"""TL001 cross-procedural negative: helpers that must NOT inherit
tracedness — a host call site exists, only static values flow in, or the
helper sits two hops from the jit (outside the one-hop frontier)."""

import jax


def _helper(x):
    if x > 0:  # also called from host code below: no inheritance
        return x
    return -x


@jax.jit
def entry(x):
    return _helper(x)


def host_path(v):
    return _helper(v)  # the host call site that disables inheritance


def _static_impl(x, n):
    if n > 2:  # n only receives shape facts — static under tracing
        return x[:n]
    return x


@jax.jit
def entry2(x):
    return _static_impl(x, x.shape[0])


def _two_hops(x):
    if x > 0:  # only reachable THROUGH an inherited helper: out of range
        return x
    return -x


def _one_hop(x):
    return _two_hops(x)


@jax.jit
def entry3(x):
    return _one_hop(x)
