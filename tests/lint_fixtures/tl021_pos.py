"""TL021 positives: host reads of mesh-sharded leaves inside hot loops.

Never executed — parsed by tests/test_shardlint.py only.
"""

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

STATE = jax.device_put(build(), P(None, "tp"))  # noqa: F821
COUNTS = jax.device_put(zeros(), P("dp"))  # noqa: F821


# tracelint: hotloop
def snapshot():
    # TL021: materializes the tp-sharded state on host every call
    return np.asarray(STATE)


# tracelint: hotloop
def histogram():
    local = COUNTS
    # TL021: np.array gathers the dp-sharded counters
    return np.array(local)


# tracelint: hotloop
def first_logit():
    # TL021: scalar read forces a cross-device gather of the tp shards
    return float(STATE[0])
