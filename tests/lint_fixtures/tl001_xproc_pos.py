"""TL001 cross-procedural positive: `_*_impl` bodies whose only call
sites are jitted functions inherit tracedness (one hop). Never executed —
tracelint parses it; pytest ignores non-test_ files."""

import jax


def _branch_impl(x):
    if x > 0:  # x is traced at the only (jitted) call site
        return x
    return -x


@jax.jit
def entry(x):
    return _branch_impl(x)


def _mixed_impl(y, n):
    if n > 2:  # n only ever receives a host constant: static, fine
        y = y * n
    if y.sum() > 0:  # y receives `x + 1` — traced
        return y
    return -y


@jax.jit
def entry2(x):
    return _mixed_impl(x + 1, 4)


class Stepper:
    def _step_impl(self, state):
        assert state.sum() > 0  # traced via the method call below
        return state * 2

    @jax.jit
    def step(self, state):
        return self._step_impl(state)
