"""TL003 negative: the correct donation idioms — rebind the reference,
or read only the dispatch's return value."""

import jax


def _chunk_builder(model, key):
    def fn(state):
        return state

    return fn


_chunk_builder._donate_argnums = (0,)


def _jit_sample(builder, model, key, *args):
    return builder(model, key)(*args)


def chunk(state):
    return _jit_sample(_chunk_builder, None, (), state)


step = jax.jit(lambda s: s, donate_argnums=(0,))


def rebind_is_fine(state):
    state = chunk(state)  # the PR-2 engine idiom: replace the reference
    return state["img_pos"]  # reads the NEW state


def read_result_only(state):
    new = step(state)
    return new["row"]  # only the return value is touched


def fresh_binding_after(state):
    _ = chunk(state)
    state = {"img_pos": 0}  # rebound to a fresh object
    return state["img_pos"]


def undonated_call_is_fine(state):
    probe = len(state)  # reads before the dispatch are fine
    new = chunk(state)
    return new, probe
