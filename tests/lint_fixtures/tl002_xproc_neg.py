"""TL002 cross-procedural negative: helpers with a host call site, or
fed only static values, stay host functions — their numpy work is not a
sync hazard."""

import jax
import numpy as np


def _save_impl(x):
    return np.asarray(x)  # legitimately host: called from save() below


@jax.jit
def entry(x):
    return x * 2


def save(x):
    return _save_impl(x)


def _table_impl(n):
    return np.asarray(range(n))  # n only receives static shape facts


@jax.jit
def entry2(x):
    return x[: len(_table_impl(x.shape[0]))]
