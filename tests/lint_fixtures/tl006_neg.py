"""TL006 negative: identifiers that merely resemble debugger calls."""


def first(items):
    return items[0]


def not_a_debugger(self_test):
    # `st` with arguments is some function named st, not the ipdb alias;
    # mentioning breakpoint in a string or comment is documentation
    result = list(range(3))
    note = "never ship a breakpoint() call"
    stats = {"st": 1}
    return self_test(result), note, stats


class Stage:
    def st(self, x):  # a method named st is fine
        return x
