"""TL018 positives: donated inputs whose pinned output sharding differs.

Never executed — parsed by tests/test_shardlint.py only.
"""

import jax
from jax.sharding import PartitionSpec as P


def resharded_state(fn):
    # TL018: state comes in split over tp, leaves replicated — the donated
    # buffer cannot be reused and XLA inserts a collective every step
    return jax.jit(
        fn,
        donate_argnums=(0,),
        in_shardings=(P(None, "tp"),),
        out_shardings=P(),
    )


def second_arg_migrates(fn):
    # TL018: arg 1 is donated under dp but every output lands on tp
    return jax.jit(
        fn,
        donate_argnums=(1,),
        in_shardings=(P(), P("dp")),
        out_shardings=(P(), P("tp")),
    )


def no_output_matches(fn):
    # TL018: neither output slot can absorb the tp-sharded donation
    return jax.jit(
        fn,
        donate_argnums=(0,),
        in_shardings=(P("tp"),),
        out_shardings=(P(), P(None, "tp")),
    )
