"""TL020 positives: named-axis placements with no divisibility fallback.

Never executed — parsed by tests/test_shardlint.py only.
"""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

GLOBAL_MESH = build_mesh()  # noqa: F821


def params_shardings(mesh, params):
    # TL020: assumes every leading dim divides the tp axis size
    return jax.tree.map(
        lambda leaf: NamedSharding(mesh, P(None, "tp")),
        params,
    )


def place_batch(mesh, x):
    # TL020: dp-sized batches only; a ragged tail batch fails to commit
    return jax.device_put(x, NamedSharding(mesh, P("dp")))


# TL020: module-level placement, same assumption
SHARDING = NamedSharding(GLOBAL_MESH, P("fsdp"))
