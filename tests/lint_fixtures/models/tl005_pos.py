"""TL005 positive: dtype-less constructors in a `models/` path — the
default dtype drifts with x64 flags and platform."""

import jax.numpy as jnp


def build_state(n):
    row = jnp.zeros((n, 16))  # float32? float64? depends on flags
    mask = jnp.ones(n)
    table = jnp.array([1, 2, 3])  # int32 vs int64 platform drift
    return row, mask, table
