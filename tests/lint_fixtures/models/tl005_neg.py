"""TL005 negative: pinned dtypes (keyword or positional), dtype-inheriting
constructors, and jnp.array outside the disciplined dirs is out of scope."""

import jax.numpy as jnp


def build_state(n, like):
    row = jnp.zeros((n, 16), jnp.float32)  # positional dtype pins it
    mask = jnp.ones(n, dtype=jnp.bool_)
    table = jnp.array([1, 2, 3], dtype=jnp.int32)
    ring = jnp.zeros_like(like)  # inherits its dtype: no drift
    return row, mask, table, ring
