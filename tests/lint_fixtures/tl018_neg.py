"""TL018 negatives: fixed-point donations and unresolvable specs."""

import jax
from jax.sharding import PartitionSpec as P


def fixed_point(fn):
    # in == out: the donated buffer is reused in place
    return jax.jit(
        fn,
        donate_argnums=(0,),
        in_shardings=(P(None, "tp"),),
        out_shardings=P(None, "tp"),
    )


def same_symbol(fn, state_shardings):
    # both sides are the same name: trivially the same placement
    return jax.jit(
        fn,
        donate_argnums=(0,),
        in_shardings=(state_shardings,),
        out_shardings=state_shardings,
    )


def symbol_vs_literal(fn, state_shardings):
    # one side is opaque: UNKNOWN, the lint stays silent
    return jax.jit(
        fn,
        donate_argnums=(0,),
        in_shardings=(state_shardings,),
        out_shardings=P("dp"),
    )


def one_output_absorbs(fn):
    # some output slot matches the donated input: the buffer has a home
    return jax.jit(
        fn,
        donate_argnums=(0,),
        in_shardings=(P("dp"),),
        out_shardings=(P("tp"), P("dp")),
    )


def trailing_none_equivalent(fn):
    # P("tp", None) and P("tp") are the same placement
    return jax.jit(
        fn,
        donate_argnums=(0,),
        in_shardings=(P("tp", None),),
        out_shardings=P("tp"),
    )
