"""TL006 positive: debugger artifacts — the reference repo's import-time
breakpoint regression (SURVEY.md §0). Parsed, never imported."""

import ipdb


def hung_on_import():
    ipdb.set_trace()


def forgotten_breakpoint(x):
    breakpoint()
    return x


def st_alias(x):
    st()
    return x
