"""TL017 negatives: pinned ladder programs and out-of-scope jits."""

import jax


class ShardedEngine:
    def _chunk_op(self, s):
        fn = self._sharded_program(
            "chunk",
            lambda: jax.jit(  # pinned: the donated state's fixed point
                self._chunk_builder(),
                donate_argnums=(1,),
                out_shardings=self._state_shardings,
            ),
        )
        return fn(self.variables, s)

    def _prefill_op(self, s, texts):
        fn = self._sharded_program(
            "prefill",
            lambda: jax.jit(  # pytree-prefix pin (state, sidecar)
                self._prefill_builder(),
                donate_argnums=(1,),
                out_shardings=(
                    self._state_shardings, self._replicated_sharding(),
                ),
            ),
        )
        return fn(self.variables, s, texts)


def plain_single_device(fn):
    # no mesh awareness at all: the single-device engines donate without
    # in/out shardings and stay out of scope
    return jax.jit(fn, donate_argnums=(0,))


def pinned_with_in(fn, state_shardings):
    return jax.jit(
        fn,
        donate_argnums=(0,),
        in_shardings=(state_shardings,),
        out_shardings=state_shardings,
    )


def in_without_donation(fn, sharding):
    # nothing donated: no buffer whose layout can drift out from under
    # the caller, out of scope
    return jax.jit(fn, in_shardings=(sharding,))
