"""TL008 negative fixture: known axes, factory-built meshes, tuple
axis groups, empty specs, and unresolvable meshes (silent by design)."""

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from dalle_pytorch_tpu.parallel.mesh import make_mesh

mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("data", "model"))
mesh4 = make_mesh(dp=2, tp=4)


def body(q, k):
    return q + k


ok = shard_map(
    body,
    mesh=mesh,
    # axis groups inside tuples resolve too
    in_specs=(P("data", "model"), P(("data", "model"), None)),
    out_specs=P("data", None),
)

ok4 = shard_map(
    body, mesh=mesh4, in_specs=(P("dp", "tp"), P()), out_specs=P("dp"),
)

replicated = NamedSharding(mesh, P())


def wrapped(unknown_mesh, spec):
    # a mesh the rule cannot resolve (parameter) stays silent, even with
    # an axis name no mesh here defines — false-negative bias; so does a
    # spec built elsewhere
    fn = shard_map(
        body, mesh=unknown_mesh, in_specs=(P("wat"), P()), out_specs=P("wat"),
    )
    return fn, NamedSharding(unknown_mesh, spec)
