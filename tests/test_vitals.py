"""Engine vitals: program cost table, vitals sampler, stall watchdog,
SLO burn rate, degraded /healthz, and the /debug endpoints.

The acceptance path (TestRealEngineVitals) pins the tentpole contract: a
warm continuous engine served over HTTP with vitals + watchdog + SLO
tracking all enabled compiles ZERO new programs while the sampler ticks
(`assert_no_recompiles`), and `/debug/programs` reports non-empty
FLOPs/bytes/HBM rows for every warmed program. The zero-overhead
contract mirrors the tracer's: a disabled `EngineVitals` allocates no
samples whatever traffic flows (`samples_taken` counter gate). All other
tests stub the device seams (no real `memory_stats`, no profiler init) —
watchdog/SLO logic is synthetic and deterministic via explicit `tick()`/
`check()` calls, never thread timing.
"""

import io
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from dalle_pytorch_tpu.obs import (
    EngineVitals,
    NULL_VITALS,
    ProgramCostTable,
    SLOTarget,
    SLOTracker,
    StallWatchdog,
    StructuredLog,
    Tracer,
)
from dalle_pytorch_tpu.obs.vitals import extract_cost, extract_memory
from dalle_pytorch_tpu.serving.batcher import ContinuousBatcher
from dalle_pytorch_tpu.serving.server import ServingServer
from dalle_pytorch_tpu.training.metrics import MetricsRegistry

from test_continuous import FakeContinuousEngine, IMG_SEQ, _build, spec
from test_serving_e2e import FakeServingEngine, _get, _post


# ------------------------------------------------------- program cost table


class FakeCompiled:
    """Stand-in for jax.stages.Compiled: the two analysis surfaces."""

    class _Mem:
        argument_size_in_bytes = 1024
        output_size_in_bytes = 256
        temp_size_in_bytes = 64
        alias_size_in_bytes = 0
        generated_code_size_in_bytes = 12

    def __init__(self, flops=2.0e9, nbytes=1.0e7, as_list=True):
        self._cost = {"flops": flops, "bytes accessed": nbytes}
        self._as_list = as_list

    def cost_analysis(self):
        return [self._cost] if self._as_list else self._cost

    def memory_analysis(self):
        return self._Mem()


class TestProgramCostTable:
    def test_extract_helpers_handle_both_jax_shapes(self):
        flat = extract_cost(FakeCompiled(as_list=False))
        wrapped = extract_cost(FakeCompiled(as_list=True))
        assert flat == wrapped and flat["flops"] == 2.0e9
        mem = extract_memory(FakeCompiled())
        assert mem["argument_size_in_bytes"] == 1024
        assert mem["temp_size_in_bytes"] == 64

    def test_rows_and_mfu_from_synced_wall(self):
        reg = MetricsRegistry()
        table = ProgramCostTable(
            peak_flops=1e12, hbm_bps=1e11, registry=reg
        )
        table.add("chunk", FakeCompiled(flops=1e9, nbytes=1e8))
        # unsynced wall: watchdog baseline only, no MFU exported
        table.record_wall("chunk", 0.010, synced=False)
        assert table.mfu("chunk") is None
        (row,) = table.rows()
        assert row["wall_includes_sync"] is False and "mfu" not in row
        # synced wall: EMA folds in, MFU = flops / (wall * peak)
        table.record_wall("chunk", 0.010, synced=True)
        mfu = table.mfu("chunk")
        assert mfu == pytest.approx(1e9 / (0.010 * 1e12), rel=1e-6)
        (row,) = table.rows()
        assert row["mfu"] == pytest.approx(mfu, rel=1e-3)
        assert row["hbm_gbps"] == pytest.approx(1e8 / 0.010 / 1e9, rel=1e-3)
        assert row["memory"]["argument_size_in_bytes"] == 1024
        # gauges landed with the program label
        out = reg.render()
        assert 'dalle_serving_mfu{program="chunk"}' in out
        assert 'dalle_serving_hbm_gbps{program="chunk"}' in out

    def test_mfu_clamped_and_unknown_program_ignored(self):
        table = ProgramCostTable(peak_flops=1.0)  # absurd peak -> clamp
        table.add("p", FakeCompiled(flops=1e9, nbytes=1.0))
        table.record_wall("p", 0.001)
        assert table.mfu("p") == 1.0
        table.record_wall("never_captured", 0.5)  # must not raise
        assert table.mfu("never_captured") is None

    def test_capture_records_errors_instead_of_raising(self):
        table = ProgramCostTable()

        def bad_lower():
            raise RuntimeError("no backend")

        assert table.capture("broken", bad_lower) is False
        (row,) = table.rows()
        assert row["program"] == "broken" and "no backend" in row["error"]
        # eager-fallback samplers lower to None: skipped, not an error
        assert table.capture("eager", lambda: None) is False
        assert not table.has("eager")


class PerShardCompiled:
    """Compiled stand-in whose cost_analysis reports one entry per
    partition — the 'where jax exposes per-shard data' arm."""

    def __init__(self, per_dev):
        self._per = per_dev

    def cost_analysis(self):
        return [dict(c) for c in self._per]

    def memory_analysis(self):
        return None


class TestPerShardCostRows:
    PER_DEV = [
        {"flops": 1e9, "bytes accessed": 1e8},
        {"flops": 3e9, "bytes accessed": 3e8},
    ]

    def _table(self, reg=None):
        table = ProgramCostTable(
            peak_flops=1e12, hbm_bps=1e11, registry=reg
        )
        table.add(
            "chunk", PerShardCompiled(self.PER_DEV),
            devices=["cpu:0", "cpu:1"],
        )
        return table

    def test_per_shard_rows_and_global_sum(self):
        table = self._table()
        (row,) = table.rows(per_shard=True)
        # the global row is the SUM of the partitions, not entry 0
        assert row["flops"] == 4e9 and row["bytes_accessed"] == 4e8
        shards = {s["device"]: s for s in row["per_shard"]}
        assert shards["cpu:0"]["flops"] == 1e9
        assert shards["cpu:1"]["flops"] == 3e9
        # default rows() view is unchanged (no per_shard key)
        (plain,) = table.rows()
        assert "per_shard" not in plain

    def test_per_shard_mfu_gauges_and_row_values(self):
        reg = MetricsRegistry()
        table = self._table(reg)
        table.record_wall("chunk", 0.010, synced=True)
        (row,) = table.rows(per_shard=True)
        shards = {s["device"]: s for s in row["per_shard"]}
        # per-device MFU divides each shard's OWN flops by the shared
        # collective wall — the lopsided shard reads 3x the other
        assert shards["cpu:1"]["mfu"] == pytest.approx(
            3e9 / (0.010 * 1e12), rel=1e-3
        )
        assert shards["cpu:1"]["mfu"] == pytest.approx(
            3 * shards["cpu:0"]["mfu"], rel=1e-3
        )
        out = reg.render()
        assert 'dalle_serving_mfu{program="chunk"}' in out
        assert 'dalle_serving_mfu{program="chunk",device="cpu:0"}' in out
        assert 'dalle_serving_hbm_gbps{program="chunk",device="cpu:1"}' in out

    def test_global_only_analysis_falls_back(self):
        """The common jax shape (one entry for the whole partitioned
        program) keeps the global row alone even with devices passed."""
        table = ProgramCostTable()
        table.add(
            "prefill", FakeCompiled(flops=5e9), devices=["cpu:0", "cpu:1"]
        )
        (row,) = table.rows(per_shard=True)
        assert "per_shard" not in row and row["flops"] == 5e9

    def test_debug_programs_per_shard_query(self):
        """GET /debug/programs?per_shard=1 surfaces the block; the plain
        endpoint stays global-only."""
        eng = FakeServingEngine()
        eng.cost_table = self._table(eng.registry)
        server = ServingServer(eng, port=0, max_delay_ms=5).start()
        try:
            status, body = _get(server.port, "/debug/programs")
            assert status == 200
            (row,) = json.loads(body)["programs"]
            assert "per_shard" not in row
            status, body = _get(server.port, "/debug/programs?per_shard=1")
            assert status == 200
            (row,) = json.loads(body)["programs"]
            assert [s["device"] for s in row["per_shard"]] == [
                "cpu:0", "cpu:1",
            ]
        finally:
            server.shutdown()


# ----------------------------------------------------------------- SLO burn


class TestSLOTracker:
    def _tracker(self, threshold_s=0.25, objective=0.9, window_s=60.0):
        reg = MetricsRegistry()
        hist = reg.histogram("lat_seconds", "test latency")
        slo = SLOTracker(
            [SLOTarget("lat", threshold_s, histogram="lat_seconds",
                       objective=objective)],
            registry=reg, window_s=window_s,
        )
        return reg, hist, slo

    def test_burn_zero_when_compliant(self):
        reg, hist, slo = self._tracker()
        for _ in range(10):
            hist.observe(0.01)
        slo.update()
        assert slo.burning() == []
        (st,) = slo.status()
        assert st["burn_rate"] == 0.0 and st["window_observations"] == 10

    def test_burn_exceeds_one_on_violations(self):
        reg, hist, slo = self._tracker(threshold_s=0.25, objective=0.9)
        for _ in range(8):
            hist.observe(0.01)
        hist.observe(5.0)
        hist.observe(5.0)  # 2/10 violating vs 10% budget -> burn 2.0
        slo.update()
        assert slo.burning() == ["lat"]
        (st,) = slo.status()
        assert st["burn_rate"] == pytest.approx(2.0)
        assert st["window_violations"] == 2
        out = reg.render()
        assert 'dalle_slo_burn_rate{slo="lat"} 2' in out

    def test_rolling_window_forgets_old_violations(self):
        reg, hist, slo = self._tracker(window_s=60.0)
        hist.observe(5.0)
        slo.update(now=0.0)
        assert slo.burning() == ["lat"]
        # a window later: only fresh compliant traffic counts
        for _ in range(10):
            hist.observe(0.01)
        slo.update(now=100.0)
        assert slo.burning() == []

    def test_off_bucket_threshold_fails_conservative(self):
        """A threshold between bucket bounds counts the straddling bucket
        as violating — the SLO over-alerts rather than going silently
        blind (an observation at 0.4s against a 0.3s target IS a
        violation the optimistic rounding would have hidden)."""
        reg, hist, slo = self._tracker(threshold_s=0.3, objective=0.9)
        for _ in range(9):
            hist.observe(0.01)
        hist.observe(0.4)  # lands in the (0.25, 0.5] bucket
        slo.update()
        (st,) = slo.status()
        assert st["window_violations"] == 1
        assert slo.burning() == ["lat"]

    def test_missing_histogram_is_harmless(self):
        reg = MetricsRegistry()
        slo = SLOTracker(
            [SLOTarget("ghost", 0.1, histogram="never_registered")],
            registry=reg,
        )
        slo.update()
        assert slo.burning() == []


# ------------------------------------------------------------ stall watchdog


class TestStallWatchdog:
    def _watchdog(self, log_buf=None, **kw):
        kw.setdefault("dispatch_mult", 4.0)
        kw.setdefault("dispatch_min_s", 0.05)
        kw.setdefault("queue_age_budget_s", 1.0)
        kw.setdefault("no_progress_ticks", 2)
        reg = MetricsRegistry()
        log = StructuredLog(stream=log_buf) if log_buf is not None else None
        wd = StallWatchdog(
            registry=reg, log=log,
            state_dump_fn=lambda: {"slot_table": [0, 1]},
            **kw,
        )
        return reg, wd

    def test_silent_on_healthy_cycle(self):
        _, wd = self._watchdog()
        healthy = {
            "dispatch_inflight": {"program": "chunk", "age_s": 0.01},
            "queue_head_age_s": 0.2,
            "chunk_index": 7,
            "slots_active": 2,
        }
        for i in range(5):
            healthy = dict(healthy, chunk_index=7 + i)  # decode progresses
            assert wd.check(healthy, {"chunk": 0.02}) == []
        assert wd.stalls_fired == 0

    def test_fires_on_stuck_dispatch_with_state_dump(self):
        buf = io.StringIO()
        _, wd = self._watchdog(log_buf=buf)
        stuck = {"dispatch_inflight": {"program": "chunk", "age_s": 2.0}}
        (fired,) = wd.check(stuck, {"chunk": 0.02})  # budget = 4 * 0.02
        assert fired["reason"] == StallWatchdog.DISPATCH_STUCK
        assert fired["program"] == "chunk" and fired["age_s"] == 2.0
        rec = json.loads(buf.getvalue())
        assert rec["event"] == "stall"
        assert rec["reason"] == "dispatch_stuck"
        assert rec["state"] == {"slot_table": [0, 1]}
        # the custom dump carries no stacks, so the watchdog's fallback
        # capture rides the event under the SAME schema key the server
        # dump uses
        assert "worker_stacks" in rec
        assert wd.last_stall_age_s() < 1.0

    def test_first_dispatch_gets_compile_budget_not_ema_budget(self):
        """A program's first dispatch may be paying a legitimate XLA
        compile (--no_warmup cold start): no false stall within the large
        fixed budget — but the budget is BOUNDED, so a deadlocked first
        dispatch still eventually fires (nothing else would catch it)."""
        _, wd = self._watchdog()
        compiling = {
            "dispatch_inflight": {
                "program": "generate:8", "age_s": 45.0, "first": True,
            },
        }
        assert wd.check(compiling, {}) == []
        assert wd.stalls_fired == 0
        # the same age on a non-first dispatch IS a stall
        stuck = dict(compiling)
        stuck["dispatch_inflight"] = dict(
            compiling["dispatch_inflight"], first=False
        )
        assert wd.check(stuck, {})[0]["reason"] == wd.DISPATCH_STUCK
        # past the first-dispatch budget, even a "compiling" dispatch is
        # declared stuck
        _, wd2 = self._watchdog(first_dispatch_budget_s=10.0)
        (fired,) = wd2.check(compiling, {})
        assert fired["reason"] == wd2.DISPATCH_STUCK
        assert fired["budget_s"] == 10.0

    def test_serve_rejects_slo_without_vitals(self):
        """serve.py fails loudly on --no_vitals + --slo_*: the sampler
        drives burn updates, so the combination would silently export a
        dead burn gauge."""
        import sys
        from pathlib import Path

        sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
        import serve

        with pytest.raises(SystemExit):
            serve.parse_args(
                ["--dalle_path", "x", "--no_vitals", "--slo_ttft_ms", "500"]
            )
        args = serve.parse_args(["--dalle_path", "x", "--slo_ttft_ms", "500"])
        assert args.slo_ttft_ms == 500.0

    def test_cooldown_suppresses_repeat_firing(self):
        _, wd = self._watchdog(cooldown_s=60.0)
        stuck = {"dispatch_inflight": {"program": "chunk", "age_s": 2.0}}
        assert len(wd.check(stuck, {"chunk": 0.02})) == 1
        assert wd.check(stuck, {"chunk": 0.02}) == []
        assert wd.stalls_fired == 1

    def test_fires_on_stale_queue_head(self):
        reg, wd = self._watchdog(queue_age_budget_s=0.5)
        (fired,) = wd.check(
            {"queue_head_age_s": 3.0, "queue_depth_rows": 9}, {}
        )
        assert fired["reason"] == StallWatchdog.QUEUE_HEAD_STALE
        assert fired["queue_depth_rows"] == 9
        fam = reg.get("dalle_serving_stalls_total")
        assert fam.labels("queue_head_stale").value == 1

    def test_fires_on_frozen_decode_progress(self):
        _, wd = self._watchdog(no_progress_ticks=2)
        frozen = {"chunk_index": 5, "slots_active": 3}
        assert wd.check(frozen, {}) == []  # tick 1: baseline
        assert wd.check(frozen, {}) == []  # tick 2: 1 stuck tick
        (fired,) = wd.check(frozen, {})  # tick 3: threshold
        assert fired["reason"] == StallWatchdog.NO_PROGRESS
        assert fired["slots_active"] == 3

    def test_progress_resets_the_frozen_counter(self):
        _, wd = self._watchdog(no_progress_ticks=2)
        wd.check({"chunk_index": 5, "slots_active": 1}, {})
        wd.check({"chunk_index": 5, "slots_active": 1}, {})
        wd.check({"chunk_index": 6, "slots_active": 1}, {})  # progressed
        wd.check({"chunk_index": 6, "slots_active": 1}, {})
        assert wd.check({"chunk_index": 6, "slots_active": 1}, {}) != []
        assert wd.stalls_fired == 1


# --------------------------------------------------------- sampler (fakes)


class StubVitals(EngineVitals):
    """Device seam stubbed per the tier-1 contract: no real
    jax.devices()/memory_stats touch from the sampler."""

    def _device_memory_stats(self):
        return {"bytes_in_use": 12345, "peak_bytes_in_use": 23456}


class TestEngineVitalsSampler:
    def test_snapshot_fields_from_fake_stack(self):
        reg = MetricsRegistry()
        eng = FakeContinuousEngine()
        b = ContinuousBatcher(eng, registry=eng.registry)
        try:
            vit = StubVitals(interval_s=60.0, registry=reg)
            vit.bind(engine=eng, batcher=b)
            snap = vit.tick()
            assert snap["queue_depth_rows"] == 0
            assert snap["slots_active"] == 0
            assert snap["queue_head_age_s"] is None
            assert snap["memory_stats"]["bytes_in_use"] == 12345
            assert snap["dispatch_inflight"] is None
            assert "compile_count" in snap
            assert vit.samples_taken == 1
            assert vit.recent() == [snap]
            # the memory gauge follows the stubbed device stats
            assert reg.get(
                "dalle_serving_device_bytes_in_use"
            ).value == 12345
        finally:
            b.shutdown()

    def test_dispatch_clock_tracks_inflight_and_ema(self):
        vit = StubVitals(interval_s=60.0)
        assert vit.inflight() is None
        vit.dispatch_begin("chunk")
        time.sleep(0.01)
        inflight = vit.inflight()
        assert inflight["program"] == "chunk"
        assert inflight["age_s"] >= 0.01
        # a program's FIRST post-bind dispatch is stuck-exempt (it may
        # be compiling) but on a warmed server no compile lands, so its
        # wall DOES seed the EMA — the second dispatch has a baseline
        assert inflight["first"] is True
        vit.dispatch_end("chunk", 0.03)
        assert vit.inflight() is None
        assert vit._wall_ema["chunk"] == pytest.approx(0.03)
        vit.dispatch_begin("chunk")
        assert vit.inflight()["first"] is False
        vit.dispatch_end("chunk", 0.03)
        assert vit._wall_ema["chunk"] == pytest.approx(0.03)

    def test_compiling_dispatch_never_seeds_the_ema(self, monkeypatch):
        """A dispatch during which a backend compile landed (--no_warmup
        cold start) must not fold its ~compile-length wall into the EMA
        the watchdog's stuck budget multiplies."""
        from dalle_pytorch_tpu.utils import compile_guard

        vit = StubVitals(interval_s=60.0)
        vit.dispatch_begin("chunk")
        monkeypatch.setattr(  # a compile lands mid-dispatch
            compile_guard, "_compile_count",
            compile_guard.compile_count() + 1,
        )
        vit.dispatch_end("chunk", 60.0)
        assert "chunk" not in vit._wall_ema
        # the next (warm) dispatch seeds the honest baseline
        vit.dispatch_begin("chunk")
        vit.dispatch_end("chunk", 0.02)
        assert vit._wall_ema["chunk"] == pytest.approx(0.02)

    def test_window_summary_means_and_peaks(self):
        vit = StubVitals(interval_s=60.0)
        eng = FakeContinuousEngine()
        b = ContinuousBatcher(eng, registry=eng.registry)
        try:
            vit.bind(engine=eng, batcher=b)
            vit.tick()
            b.allocator.alloc()  # 2 live slots for the second sample
            b.allocator.alloc()
            vit.tick()
            summary = vit.window_summary()
            assert summary["samples"] == 2
            assert summary["slots_active"] == {"mean": 1.0, "peak": 2}
            vit.reset_window()
            assert vit.window_summary()["samples"] == 0
            assert vit.samples_taken == 2  # the gate counter never resets
        finally:
            b.shutdown()

    def test_disabled_vitals_zero_allocations_under_traffic(self):
        """The acceptance gate: a vitals-off server serves traffic with
        ZERO sampler allocations — counter-gated, like the tracer."""
        eng = FakeServingEngine()
        vit = EngineVitals(enabled=False, registry=eng.registry)
        server = ServingServer(
            eng, port=0, max_delay_ms=5, vitals=vit,
        ).start()
        try:
            for i in range(3):
                status, _ = _post(server.port, {"prompt": f"req {i}"})
                assert status == 200
            assert vit.samples_taken == 0
            assert vit.recent() == []
            assert vit.start() is vit  # start() on disabled = no thread
            assert vit._thread is None
            # the engine keeps the null clock: nothing bound
            assert eng.registry.get(
                "dalle_serving_dispatch_inflight_age_seconds"
            ) is None
        finally:
            server.shutdown()

    def test_null_vitals_singleton_is_inert(self):
        assert not NULL_VITALS
        NULL_VITALS.dispatch_begin("x")
        NULL_VITALS.dispatch_end("x", 1.0)
        assert NULL_VITALS.samples_taken == 0


class ShardStubVitals(StubVitals):
    """Per-shard seam stubbed: a fake 2-device mesh's memory stats (the
    PR 7 follow-on — one process used to sample only device 0)."""

    def _device_memory_stats_all(self):
        return {
            "tpu:0": {"bytes_in_use": 1000, "peak_bytes_in_use": 1500},
            "tpu:1": {"bytes_in_use": 3000, "peak_bytes_in_use": 3500},
        }


class TestPerShardVitals:
    def test_per_device_rollup_and_gauge_family(self):
        """One snapshot carries EVERY shard's memory stats plus their
        total, and the dalle_serving_hbm_bytes{device=} family exports
        one series per shard — the sick one is nameable."""
        reg = MetricsRegistry()
        eng = FakeContinuousEngine()
        b = ContinuousBatcher(eng, registry=eng.registry)
        try:
            vit = ShardStubVitals(interval_s=60.0, registry=reg)
            vit.bind(engine=eng, batcher=b)
            snap = vit.tick()
            per_dev = snap["memory_stats_per_device"]
            assert per_dev["tpu:0"]["bytes_in_use"] == 1000
            assert per_dev["tpu:1"]["bytes_in_use"] == 3000
            assert snap["bytes_in_use_total"] == 4000
            fam = reg.get("dalle_serving_hbm_bytes")
            by_dev = {label: child.value for label, child in fam.items()}
            assert by_dev == {"tpu:0": 1000, "tpu:1": 3000}
        finally:
            vit.stop()
            b.shutdown(drain=False)

    def test_vitals_detail_carries_mesh_block(self):
        """An engine exposing mesh_detail() (the sharded engine) gets its
        rollup into the /debug/vitals payload."""
        reg = MetricsRegistry()
        eng = FakeContinuousEngine()
        eng.mesh_detail = lambda: {
            "axes": {"tp": 2}, "devices": 2,
            "per_device_state_bytes": {"tpu:0": 7, "tpu:1": 7},
        }
        b = ContinuousBatcher(eng, registry=eng.registry)
        try:
            vit = ShardStubVitals(interval_s=60.0, registry=reg)
            vit.bind(engine=eng, batcher=b)
            vit.tick()
            detail = vit.detail()
            assert detail["mesh"]["axes"] == {"tp": 2}
            assert detail["mesh"]["per_device_state_bytes"]["tpu:1"] == 7
        finally:
            vit.stop()
            b.shutdown(drain=False)

    def test_mesh_devices_prefers_engine_mesh(self):
        """The per-shard seam reads the ENGINE's mesh devices when one is
        bound, not every process-visible device."""

        class _Dev:
            def __init__(self, i):
                self.platform, self.id = "tpu", i

            def memory_stats(self):
                return {"bytes_in_use": 10 * (self.id + 1)}

        class _Mesh:
            class devices:
                flat = [_Dev(0), _Dev(1)]

        eng = FakeContinuousEngine()
        eng.mesh = _Mesh()
        vit = EngineVitals(enabled=True, interval_s=60.0)
        vit.bind(engine=eng)
        try:
            stats = vit._device_memory_stats_all()
            assert stats == {
                "tpu:0": {"bytes_in_use": 10},
                "tpu:1": {"bytes_in_use": 20},
            }
        finally:
            vit.stop()


# -------------------------------------------------- /debug + health (HTTP)


class TestDebugEndpoints:
    def test_trace_id_exact_lookup_and_404(self):
        server = ServingServer(
            FakeServingEngine(), port=0, max_delay_ms=5,
            tracer=Tracer(max_traces=4),
        ).start()
        try:
            status, payload = _post(server.port, {"prompt": "find me"})
            assert status == 200
            tid = payload["trace_id"]
            status, body = _get(
                server.port, f"/debug/traces?trace_id={tid}"
            )
            assert status == 200
            events = json.loads(body)["traceEvents"]
            assert events and all(
                e["args"]["trace_id"] == tid
                for e in events if e["ph"] == "X"
            )
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(server.port, "/debug/traces?trace_id=deadbeef")
            assert e.value.code == 404
            # eviction: flood the 4-trace ring, the old ID 404s
            for i in range(5):
                _post(server.port, {"prompt": f"flood {i}"})
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(server.port, f"/debug/traces?trace_id={tid}")
            assert e.value.code == 404
        finally:
            server.shutdown()

    def test_debug_vitals_and_programs_endpoints(self):
        eng = FakeServingEngine()
        vit = StubVitals(interval_s=60.0, registry=eng.registry)
        server = ServingServer(
            eng, port=0, max_delay_ms=5, vitals=vit,
        ).start()
        try:
            vit.tick()  # deterministic: don't wait for the thread
            status, body = _get(server.port, "/debug/vitals?n=1")
            assert status == 200
            payload = json.loads(body)
            assert payload["enabled"] is True
            assert len(payload["samples"]) == 1
            assert payload["samples"][0]["memory_stats"]["bytes_in_use"] == 12345
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(server.port, "/debug/vitals?n=0")
            assert e.value.code == 400
            # no cost table attached: explicit note, not a 500
            status, body = _get(server.port, "/debug/programs")
            assert status == 200
            payload = json.loads(body)
            assert payload["programs"] == [] and "note" in payload
        finally:
            server.shutdown()

    def test_debug_state_renders_midflight_dump(self):
        """/debug/state while the worker is parked inside a chunk: the
        dump shows the in-flight slot with its trace ID and the queued
        request behind it — a consistent postmortem view mid-stall."""
        gate = threading.Event()
        eng = FakeContinuousEngine(block_event=gate)
        b = ContinuousBatcher(eng, registry=eng.registry)
        tr = Tracer()
        try:
            t1 = tr.start_trace()
            first = b.submit([spec(0)], trace=t1)
            assert eng.chunk_entered.wait(10.0)  # worker provably parked
            queued = b.submit([spec(1)], trace=tr.start_trace())
            summary = b.state_summary()
            assert summary["queue_requests"] == 1
            assert summary["queue_head_age_s"] is not None
            assert summary["slots_active"] == 1
            (slot_info,) = summary["slots_inflight"].values()
            assert slot_info["trace_id"] == t1.trace_id
            assert slot_info["rows"] == 1
        finally:
            gate.set()
            first.future.result(timeout=10)
            queued.future.result(timeout=10)
            b.shutdown()

    def test_request_log_carries_admission_context(self):
        """Satellite: every request log line records the load it was
        admitted under (queue_depth_rows / slots_active at submit)."""
        from dalle_pytorch_tpu.data.tokenizer import ByteTokenizer

        _, cont = _build(max_batch=2, chunk_tokens=4, prefill_batch=2)
        cont.tokenizer = ByteTokenizer()
        cont.warmup()
        buf = io.StringIO()
        server = ServingServer(
            cont, port=0, request_timeout_s=60,
            log=StructuredLog(stream=buf),
        ).start()
        try:
            status, payload = _post(server.port, {"prompt": "ctx", "seed": 3})
            assert status == 200
            (rec,) = [
                json.loads(line) for line in buf.getvalue().splitlines()
                if json.loads(line).get("event") == "request"
            ]
            assert rec["trace_id"] == payload["trace_id"]
            assert rec["queue_depth_rows"] == 0
            assert rec["slots_active"] == 0  # sampled at submit time
        finally:
            server.shutdown()

    def test_healthz_degraded_tier(self):
        """Between ok and 503: a recent watchdog stall (or burning SLO)
        turns /healthz into 200 + status=degraded with reasons; hard
        failures still 503."""
        eng = FakeServingEngine()
        wd = StallWatchdog(dispatch_min_s=0.01, cooldown_s=600)
        vit = StubVitals(
            interval_s=60.0, registry=eng.registry, watchdog=wd,
        )
        server = ServingServer(
            eng, port=0, max_delay_ms=5, vitals=vit,
        ).start()
        try:
            status, body = _get(server.port, "/healthz")
            assert status == 200
            assert json.loads(body)["status"] == "ok"
            # synthetic stall -> degraded, still HTTP 200
            wd.check(
                {"dispatch_inflight": {"program": "chunk", "age_s": 9.9}},
                {},
            )
            status, body = _get(server.port, "/healthz")
            health = json.loads(body)
            assert status == 200
            assert health["status"] == "degraded"
            assert health["degraded_reasons"] == ["stall:dispatch_stuck"]
        finally:
            server.shutdown()


# ------------------------------------- acceptance: real engine, everything on


@pytest.fixture(scope="module")
def vital_server():
    """Warm toy continuous engine + cost table + sampler + watchdog + SLO
    behind one HTTP server (the PR's full stack, device seams stubbed)."""
    from dalle_pytorch_tpu.data.tokenizer import ByteTokenizer

    _, cont = _build(max_batch=2, chunk_tokens=4, prefill_batch=2)
    cont.tokenizer = ByteTokenizer()
    cont.cost_table = ProgramCostTable(registry=cont.registry)
    cont.warmup()
    slo = SLOTracker(
        [
            SLOTarget("ttft", 30.0, histogram="dalle_serving_ttft_seconds"),
            SLOTarget(
                "request", 60.0,
                histogram="dalle_serving_request_latency_seconds",
            ),
        ],
        registry=cont.registry,
    )
    vitals = StubVitals(
        interval_s=0.05, registry=cont.registry,
        watchdog=StallWatchdog(
            registry=cont.registry, dispatch_min_s=30.0,
            queue_age_budget_s=30.0,
        ),
        slo=slo,
    )
    server = ServingServer(
        cont, port=0, request_timeout_s=60,
        tracer=Tracer(max_traces=16), vitals=vitals,
    ).start()
    try:
        yield server, cont, vitals
    finally:
        server.shutdown()


class TestRealEngineVitals:
    def test_warm_serve_cycle_zero_compiles_with_everything_on(
        self, vital_server
    ):
        """The acceptance pin: vitals sampling, watchdog checks, SLO burn
        updates, and MFU accounting all run DURING a served request on a
        warm engine — and nothing compiles."""
        from dalle_pytorch_tpu.utils.compile_guard import assert_no_recompiles

        server, cont, vitals = vital_server
        _post(server.port, {"prompt": "warm", "seed": 1})
        before = vitals.samples_taken
        with assert_no_recompiles():
            status, payload = _post(
                server.port, {"prompt": "steady", "seed": 2}
            )
            deadline = time.monotonic() + 5.0
            while vitals.samples_taken == before:  # sampler ticked inside
                assert time.monotonic() < deadline, "sampler never ticked"
                time.sleep(0.02)
        assert status == 200 and payload["trace_id"]
        assert vitals.watchdog.stalls_fired == 0  # healthy cycle: silent

    def test_debug_programs_rows_for_every_warmed_program(self, vital_server):
        server, cont, _ = vital_server
        status, body = _get(server.port, "/debug/programs")
        assert status == 200
        payload = json.loads(body)
        rows = {r["program"]: r for r in payload["programs"]}
        # the continuous ladder (toy engine has no VAE -> no pixel decode)
        assert {"prefill", "chunk", "release"} <= set(rows)
        for name in ("prefill", "chunk", "release"):
            row = rows[name]
            assert "error" not in row
            assert row["bytes_accessed"] > 0
            assert row["memory"]["argument_size_in_bytes"] > 0
        assert rows["chunk"]["flops"] > 0 and rows["prefill"]["flops"] > 0
        assert payload["peak_flops"] > 0 and payload["hbm_bps"] > 0

    def test_live_mfu_exported_after_traffic(self, vital_server):
        server, cont, _ = vital_server
        _post(server.port, {"prompt": "mfu", "seed": 5})
        assert cont.cost_table.mfu("chunk") is not None
        _, metrics = _get(server.port, "/metrics")
        assert 'dalle_serving_mfu{program="chunk"}' in metrics
        assert 'dalle_serving_hbm_gbps{program="chunk"}' in metrics

    def test_vitals_and_state_reflect_served_traffic(self, vital_server):
        server, cont, vitals = vital_server
        _post(server.port, {"prompt": "vitals", "seed": 7})
        status, body = _get(server.port, "/debug/vitals?n=8")
        assert status == 200
        payload = json.loads(body)
        assert payload["samples"]
        assert payload["stalls"] == []
        assert {s["slo"] for s in payload["slo"]} == {"ttft", "request"}
        assert all(s["burn_rate"] == 0.0 for s in payload["slo"])
        status, body = _get(server.port, "/debug/state")
        assert status == 200
        dump = json.loads(body)
        assert dump["engine"]["engine"] == "ContinuousEngine"
        assert dump["engine"]["chunk_index"] >= IMG_SEQ // 4
        assert dump["batcher"]["slots_active"] == 0  # idle between tests
        assert "worker_stacks" in dump
        # healthz shows the SLO status block alongside ok
        status, body = _get(server.port, "/healthz")
        health = json.loads(body)
        assert health["status"] == "ok" and "slo" in health
