"""Fleet telemetry plane: exposition parsing, the scraper's failure
matrix, per-tenant usage attribution, and the capacity/goodput signal.

The load-bearing contracts:

  * the parser is the EXACT inverse of this repo's own exposition
    renderer (both flavors), so `/fleet/metrics` federation round-trips
    through `parse_exposition` with no third-party client library;
  * a counter reset (replica restart) clamps the delta to 0 — fleet
    totals NEVER go backwards and never spike negative;
  * every scrape failure mode — hard-killed replica, garbage body, hung
    endpoint — degrades to a stale-marked generation and an error
    counter; a hung endpoint cannot starve the other replicas' freshness
    (scrapes are concurrent, sweep time = max not sum);
  * tenant label cardinality is BOUNDED (`__other__` overflow) — the
    usage ledger must survive an open endpoint inventing tenants.
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dalle_pytorch_tpu.obs.fleetmetrics import (
    CapacityModel,
    FleetScraper,
    ReplicaScrape,
    UsageLedger,
)
from dalle_pytorch_tpu.serving.router import FleetRouter, RouterServer
from dalle_pytorch_tpu.training.metrics import (
    MetricsRegistry,
    counter_delta,
    merge_histogram_points,
    parse_exposition,
)


def _sample_registry(counter=100.0, mfu=0.2):
    """A small real registry exercising every instrument shape the
    replicas actually export: plain counter, labeled gauge family,
    histogram with an exemplar."""
    reg = MetricsRegistry()
    c = reg.counter("dalle_serving_decoded_tokens_total", "decoded")
    c.inc(counter)
    g = reg.gauge_family("dalle_serving_mfu", "mfu", label_name="program")
    g.labels("decode_b4").set(mfu)
    h = reg.histogram(
        "dalle_serving_stage_seconds", "stages", buckets=(0.1, 1.0)
    )
    h.observe(0.05, exemplar="tr1")
    h.observe(0.5)
    return reg


# ---------------------------------------------------------------- parser


class TestExpositionParser:
    def test_round_trips_own_classic_render(self):
        reg = _sample_registry()
        fams = parse_exposition(reg.render())
        c = fams["dalle_serving_decoded_tokens_total"]
        assert c.type == "counter"
        assert [s.value for s in c.samples] == [100.0]
        g = fams["dalle_serving_mfu"]
        assert g.samples[0].labels == {"program": "decode_b4"}
        assert g.samples[0].value == 0.2
        h = fams["dalle_serving_stage_seconds"]
        series = h.histogram_series()
        ((_, point),) = series.items()
        assert point["count"] == 2 and point["cum"] == [1, 2]
        assert point["bounds"] == [0.1, 1.0]  # +Inf lives in "count"

    def test_round_trips_openmetrics_flavor(self):
        """exemplars=True: `_total`-stripped family names, exemplar
        annotations on buckets, and the `# EOF` terminator — all must
        parse, with exemplars stripped from the sample values."""
        reg = _sample_registry()
        fams = parse_exposition(reg.render(exemplars=True))
        # OpenMetrics names the counter FAMILY without `_total`; the
        # sample keeps it
        c = fams["dalle_serving_decoded_tokens"]
        assert c.type == "counter"
        assert c.samples[0].name == "dalle_serving_decoded_tokens_total"
        assert [s.value for s in c.samples] == [100.0]
        h = fams["dalle_serving_stage_seconds"]
        ((_, point),) = h.histogram_series().items()
        assert point["count"] == 2

    def test_malformed_sample_line_raises(self):
        with pytest.raises(ValueError):
            parse_exposition("dalle_x{unclosed 1.0\n")
        with pytest.raises(ValueError):
            parse_exposition("dalle_x not_a_number\n")

    def test_counter_delta_clamps_never_negative(self):
        assert counter_delta(None, 10.0) == 0.0   # no baseline yet
        assert counter_delta(100.0, 40.0) == 0.0  # reset: clamp, not -60
        assert counter_delta(10.0, 15.0) == 5.0

    def test_histogram_merge_identical_bounds_sums_exactly(self):
        a = {"bounds": [0.1, 1.0], "cum": [1, 3], "count": 4, "sum": 2.0}
        b = {"bounds": [0.1, 1.0], "cum": [0, 2], "count": 5, "sum": 9.0}
        m = merge_histogram_points([a, b])
        assert m["cum"] == [1, 5] and m["count"] == 9
        assert m["sum"] == 11.0

    def test_histogram_merge_mismatched_bounds_floors_to_union(self):
        """Unknown cut points floor to the nearest LOWER known bound
        (undercount bias — a merged p95 can read low, never high)."""
        a = {"bounds": [0.5], "cum": [2], "count": 4, "sum": 3.0}
        b = {"bounds": [0.1], "cum": [1], "count": 3, "sum": 2.0}
        m = merge_histogram_points([a, b])
        assert m["bounds"] == [0.1, 0.5]
        # a contributes 0 at 0.1 (its 2-at-0.5 can't be split lower);
        # b's 1-at-0.1 carries forward to the coarser 0.5 cut
        assert m["cum"] == [1, 3]
        assert m["count"] == 7 and m["sum"] == 5.0


# ------------------------------------------------------- scripted scraper


def _scripted(payloads, **kw):
    """FleetScraper whose `_fetch` serves from a dict instead of a
    socket — the same seam the router's probe tests stub. `payloads`
    maps replica name -> {path: str | bytes | dict | Exception}."""

    class Scripted(FleetScraper):
        def _fetch(self, url, path):
            body = payloads[url][path]
            if isinstance(body, Exception):
                raise body
            if isinstance(body, dict):
                return json.dumps(body).encode()
            return body.encode() if isinstance(body, str) else body

    kw.setdefault("registry", MetricsRegistry())
    return Scripted([(name, name) for name in payloads], **kw)


def _ok_payload(counter=100.0, mfu=0.2, health=None):
    return {
        "/metrics": _sample_registry(counter=counter, mfu=mfu).render(),
        "/healthz": health if health is not None else {
            "status": "ok", "queue_depth_rows": 0, "slots_active": 1,
            "uptime_s": 5.0,
            "work": {"warmup_batches": 2, "image_seq_len": 16,
                     "max_batch": 4},
        },
        "/debug/vitals?n=1": {"samples": []},
    }


def _counter_value(registry, name, label):
    fam = registry.get(name)
    items = dict(fam.items()) if fam is not None else {}
    return int(items[label].value) if label in items else 0


class TestScraperFailureMatrix:
    def test_successful_sweep_commits_generation_and_monotonic(self):
        payloads = {"r0": _ok_payload(counter=100.0)}
        s = _scripted(payloads)
        s.scrape_once(now=1.0)
        snap = s.snapshot()["r0"]
        assert snap.generation == 1 and snap.stale is False
        # first sight is the baseline: totals count growth SINCE
        # scraper start, so a pre-existing 100 contributes 0
        assert s.fleet_totals("dalle_serving_decoded_tokens_total") == 0.0
        payloads["r0"] = _ok_payload(counter=115.0)
        s.scrape_once(now=2.0)
        assert s.fleet_totals("dalle_serving_decoded_tokens_total") == 15.0

    def test_counter_reset_clamps_delta_to_zero(self):
        """A replica restart resets its counters; the fleet total must
        hold, not go negative or double-count."""
        payloads = {"r0": _ok_payload(counter=100.0)}
        s = _scripted(payloads)
        s.scrape_once(now=1.0)
        payloads["r0"] = _ok_payload(counter=140.0)
        s.scrape_once(now=2.0)
        assert s.fleet_totals("dalle_serving_decoded_tokens_total") == 40.0
        payloads["r0"] = _ok_payload(counter=5.0)   # restart: 140 -> 5
        s.scrape_once(now=3.0)
        assert s.fleet_totals("dalle_serving_decoded_tokens_total") == 40.0
        payloads["r0"] = _ok_payload(counter=25.0)  # growth resumes
        s.scrape_once(now=4.0)
        assert s.fleet_totals("dalle_serving_decoded_tokens_total") == 60.0

    def test_garbage_body_marks_stale_keeps_last_payload(self):
        payloads = {"r0": _ok_payload(counter=100.0, mfu=0.3)}
        s = _scripted(payloads)
        s.scrape_once(now=1.0)
        payloads["r0"] = dict(
            _ok_payload(), **{"/metrics": "%%% not exposition {{{ 1"}
        )
        s.scrape_once(now=2.0)
        snap = s.snapshot()["r0"]
        assert snap.stale is True and snap.error
        assert snap.generation == 1  # the generation is HISTORY
        # last good payload still readable (mfu from sweep 1)
        assert snap.families["dalle_serving_mfu"].samples[0].value == 0.3
        assert _counter_value(
            s.registry, "dalle_fleet_scrape_errors_total", "r0"
        ) == 1

    def test_truncated_health_body_marks_stale(self):
        payloads = {"r0": dict(_ok_payload(), **{"/healthz": '{"status": '})}
        s = _scripted(payloads)
        s.scrape_once(now=1.0)
        assert s.snapshot()["r0"].stale is True

    def test_dead_replica_marks_stale_never_raises(self):
        payloads = {
            "r0": {
                "/metrics": ConnectionRefusedError("dead"),
                "/healthz": ConnectionRefusedError("dead"),
                "/debug/vitals?n=1": ConnectionRefusedError("dead"),
            },
            "r1": _ok_payload(),
        }
        s = _scripted(payloads)
        s.scrape_once(now=1.0)
        assert s.snapshot()["r0"].stale is True
        assert s.snapshot()["r1"].stale is False

    def test_hard_killed_replica_real_socket(self):
        """Real transport against a port nothing listens on
        (ECONNREFUSED) — the unstubbed `_fetch` path must degrade the
        same way."""
        sock = socket.socket()
        sock.bind(("127.0.0.1", 0))
        dead_port = sock.getsockname()[1]
        sock.close()
        s = FleetScraper(
            [("r0", f"http://127.0.0.1:{dead_port}")],
            registry=MetricsRegistry(), timeout_s=1.0,
        )
        s.scrape_once()
        snap = s.snapshot()["r0"]
        assert snap.stale is True and snap.generation == 0
        assert _counter_value(
            s.registry, "dalle_fleet_scrape_errors_total", "r0"
        ) == 1

    def test_hung_endpoint_does_not_starve_other_replicas(self):
        """One replica hangs past the scrape timeout: the sweep is
        bounded by the TIMEOUT (scrapes run concurrently), and the
        healthy replica's generation still advances."""
        hung = _HangingServer(delay_s=5.0)
        healthy = _FleetStub("r1")
        try:
            s = FleetScraper(
                [("r0", hung.url), ("r1", healthy.url)],
                registry=MetricsRegistry(), timeout_s=0.5,
            )
            t0 = time.monotonic()
            s.scrape_once()
            wall = time.monotonic() - t0
            assert wall < 4.0, f"sweep waited out the hang: {wall:.1f}s"
            assert s.snapshot()["r0"].stale is True
            assert s.snapshot()["r1"].stale is False
            assert s.snapshot()["r1"].generation == 1
        finally:
            hung.kill()
            healthy.kill()

    def test_sweep_never_raises_even_if_capacity_model_breaks(self):
        """The scrape loop must survive anything — drive the loop body
        with a payload whose health block is adversarial junk."""
        payloads = {"r0": _ok_payload(health={
            "status": None, "queue_depth_rows": "junk",
            "slots_active": {}, "slo": [{"burn_rate": "NaN-ish"}],
        })}
        s = _scripted(payloads)
        try:
            s.scrape_once(now=1.0)
        except Exception as exc:  # pragma: no cover - the assertion
            pytest.fail(f"sweep raised: {exc!r}")


# ----------------------------------------------- federation round-trip


class TestFederation:
    def test_federated_render_round_trips_with_rollups(self):
        payloads = {
            "r0": _ok_payload(counter=100.0, mfu=0.2),
            "r1": _ok_payload(counter=50.0, mfu=0.3),
        }
        s = _scripted(payloads)
        s.scrape_once(now=1.0)
        payloads["r0"] = _ok_payload(counter=130.0, mfu=0.25)
        payloads["r1"] = _ok_payload(counter=60.0, mfu=0.1)
        s.scrape_once(now=2.0)

        fams = parse_exposition(s.federated_render())

        # per-replica samples carry the replica label
        mfu = fams["dalle_serving_mfu"]
        by_replica = {
            s_.labels["replica"]: s_.value
            for s_ in mfu.samples if "replica" in s_.labels
        }
        assert by_replica == {"r0": 0.25, "r1": 0.1}
        # gauge rollups: sum and max across the fleet
        assert fams["dalle_serving_mfu:fleet_sum"].samples[0].value == 0.35
        assert fams["dalle_serving_mfu:fleet_max"].samples[0].value == 0.25
        # counter rollup is reset-corrected growth since scraper start
        assert fams[
            "dalle_serving_decoded_tokens_total:fleet_sum"
        ].samples[0].value == 40.0
        # histogram rollup merges buckets across replicas (2 obs each)
        hist = fams["dalle_serving_stage_seconds:fleet"]
        ((_, point),) = hist.histogram_series().items()
        assert point["count"] == 4
        # freshness meta rides the federated body itself
        stale = {
            s_.labels["replica"]: s_.value
            for s_ in fams["dalle_fleet_scrape_stale"].samples
        }
        assert stale == {"r0": 0.0, "r1": 0.0}
        gen = {
            s_.labels["replica"]: s_.value
            for s_ in fams["dalle_fleet_scrape_generation"].samples
        }
        assert gen == {"r0": 2.0, "r1": 2.0}


# ------------------------------------------------------------ usage ledger


class TestUsageLedger:
    def test_chip_seconds_attributed_per_tenant_and_priority(self):
        reg = MetricsRegistry()
        u = UsageLedger(registry=reg)
        u.record("acme", "normal", rows=2, wall_s=1.5, decoded_tokens=32)
        u.record("acme", "normal", rows=1, wall_s=0.5, decoded_tokens=16)
        u.record("acme", "bulk", rows=4, wall_s=2.0, decoded_tokens=64)
        s = u.summary()
        rows = {(r["tenant"], r["priority"]): r for r in s["tenants"]}
        assert rows[("acme", "normal")]["chip_seconds"] == 2.0
        assert rows[("acme", "normal")]["decoded_tokens"] == 48
        assert rows[("acme", "bulk")]["chip_seconds"] == 2.0
        assert s["totals"]["chip_seconds"] == 4.0
        # the counter family carries the same attribution
        fam = dict(reg.get("dalle_fleet_chip_seconds_total").items())
        assert any("acme" in label and "bulk" in label for label in fam)

    def test_tenant_cardinality_bounded_with_other_bucket(self):
        u = UsageLedger(max_tenants=2)
        u.record("a", "normal", rows=1, wall_s=1.0)
        u.record("b", "normal", rows=1, wall_s=1.0)
        for i in range(20):
            u.record(f"attacker-{i}", "normal", rows=1, wall_s=1.0)
        s = u.summary()
        tenants = {r["tenant"] for r in s["tenants"]}
        assert tenants == {"a", "b", UsageLedger.OTHER}
        rows = {r["tenant"]: r for r in s["tenants"]}
        assert rows[UsageLedger.OTHER]["requests"] == 20
        # a KNOWN tenant still attributes to itself after the fold
        u.record("a", "normal", rows=1, wall_s=1.0)
        rows = {r["tenant"]: r for r in u.summary()["tenants"]}
        assert rows["a"]["requests"] == 2

    def test_tenant_string_sanitized(self):
        u = UsageLedger(max_tenants=8)
        u.record('ev"il\nten{ant}' + "x" * 200, "normal", rows=1,
                 wall_s=1.0)
        (row,) = u.summary()["tenants"]
        assert all(
            ch in UsageLedger._SAFE for ch in row["tenant"]
        ) and len(row["tenant"]) <= 64
        u.record(None, "normal", rows=1, wall_s=1.0)
        assert any(
            r["tenant"] == "anonymous" for r in u.summary()["tenants"]
        )

    def test_flops_attribution_uses_current_rate(self):
        u = UsageLedger()
        u.note_flops_rate(1e12)
        u.record("a", "normal", rows=1, wall_s=2.0)
        (row,) = u.summary()["tenants"]
        assert row["est_flops"] == 2e12


# --------------------------------------------------------- capacity model


def _synthetic_scrape(name, stale=False, mfu=None, queue=0, slots=0,
                      max_batch=4, burn=0.0, warmup_batches=0):
    s = ReplicaScrape(name, name)
    s.stale = stale
    s.generation = 0 if stale else 3
    s.health = {
        "status": "ok", "queue_depth_rows": queue, "slots_active": slots,
        "slo": [{"burn_rate": burn}],
        "work": {"warmup_batches": warmup_batches, "image_seq_len": 16,
                 "max_batch": max_batch},
    }
    if mfu is not None:
        s.families = parse_exposition(
            "# TYPE dalle_serving_mfu gauge\n"
            f'dalle_serving_mfu{{program="decode"}} {mfu}\n'
        )
    return s


class TestCapacityModel:
    def test_mfu_headroom_against_serving_ceiling(self):
        r = CapacityModel.replica_assessment(
            _synthetic_scrape("r0", mfu=0.175)
        )
        assert r["mfu"] == 0.175
        assert r["mfu_headroom"] == 0.5  # ceiling is 0.35, not 1.0

    def test_slo_burn_asks_for_scale_up(self):
        scrapes = {
            "r0": _synthetic_scrape("r0", slots=2, burn=2.5),
            "r1": _synthetic_scrape("r1", slots=2),
        }
        rep = CapacityModel.assess(scrapes)
        assert rep["suggested_replicas"] == 3
        assert rep["max_slo_burn"] == 2.5

    def test_saturation_asks_for_scale_up(self):
        scrapes = {
            "r0": _synthetic_scrape("r0", slots=4, queue=20),
            "r1": _synthetic_scrape("r1", slots=4, queue=20),
        }
        assert CapacityModel.assess(scrapes)["suggested_replicas"] == 3

    def test_idle_fleet_releases_one_replica(self):
        scrapes = {
            "r0": _synthetic_scrape("r0", slots=0, queue=0),
            "r1": _synthetic_scrape("r1", slots=0, queue=0),
        }
        assert CapacityModel.assess(scrapes)["suggested_replicas"] == 1

    def test_stale_fleet_never_releases(self):
        """No fresh data -> hold, don't scale down on blindness."""
        scrapes = {
            "r0": _synthetic_scrape("r0", stale=True),
            "r1": _synthetic_scrape("r1", stale=True),
        }
        rep = CapacityModel.assess(scrapes)
        assert rep["suggested_replicas"] == 2
        assert rep["fresh_replicas"] == 0

    def test_goodput_counts_redecode_and_warmup_as_waste(self):
        scrapes = {
            "r0": _synthetic_scrape("r0", warmup_batches=2, max_batch=4),
        }
        rep = CapacityModel.assess(
            scrapes,
            fleet_decoded_tokens=300.0,   # fleet burned 300 tokens
            usage={"totals": {"decoded_tokens": 172}},  # delivered 172
        )
        g = rep["goodput"]
        assert g["useful_tokens"] == 172
        assert g["warmup_tokens"] == 2 * 16 * 4
        assert g["wasted_tokens"] == (300 - 172) + 128
        assert g["fraction"] == pytest.approx(172 / (172 + 256), abs=1e-3)

    def test_goodput_never_negative_on_accounting_skew(self):
        """Ledger ahead of the scrape (usage recorded before the next
        sweep): waste clamps at warmup, fraction stays in [0, 1]."""
        rep = CapacityModel.assess(
            {"r0": _synthetic_scrape("r0")},
            fleet_decoded_tokens=100.0,
            usage={"totals": {"decoded_tokens": 150}},
        )
        assert rep["goodput"]["wasted_tokens"] == 0
        assert rep["goodput"]["fraction"] == 1.0


# ------------------------------------------------- router HTTP integration


class _FleetStubHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def do_GET(self):
        owner = self.server.owner
        if self.path == "/metrics":
            self._body(200, owner.registry.render().encode(),
                       "text/plain; version=0.0.4")
        elif self.path.startswith("/healthz"):
            self._body(200, json.dumps(owner.health).encode())
        elif self.path.startswith("/debug/vitals"):
            self._body(200, json.dumps({"samples": []}).encode())
        else:
            self.send_error(404)

    def do_POST(self):
        owner = self.server.owner
        length = int(self.headers.get("Content-Length", "0") or 0)
        body = json.loads(self.rfile.read(length) or b"{}")
        if owner.delay_s:
            time.sleep(owner.delay_s)
        owner.registry.counter(
            "dalle_serving_decoded_tokens_total", "decoded"
        ).inc(16)
        self._body(200, json.dumps({
            "tokens": [[int(body.get("seed", 0))] * 4],
            "seed": body.get("seed"),
            "replica": owner.name,
            "latency_ms": owner.latency_ms,
            "usage": {"rows": 1, "decoded_tokens": 16,
                      "resumed_tokens": 0},
        }).encode())

    def _body(self, code, body, ctype="application/json"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass


class _StubHTTP(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True


class _FleetStub:
    """Replica stub serving the full scrape surface (/metrics, /healthz,
    /debug/vitals) plus a /generate that reports a usage block — what
    the telemetry integration needs beyond test_router's StubReplica."""

    def __init__(self, name, latency_ms=250.0):
        self.name = name
        self.latency_ms = latency_ms
        self.delay_s = 0.0
        self.registry = _sample_registry()
        self.health = {
            "status": "ok", "queue_depth_rows": 0, "slots_active": 0,
            "uptime_s": 9.0,
            "work": {"warmup_batches": 1, "image_seq_len": 16,
                     "max_batch": 4},
            "kv": {"prefix_cache": {"bloom": {
                "bits": 256, "hashes": 2, "entries": 1, "b64": "AAAA",
            }}},
        }
        self._httpd = _StubHTTP(("127.0.0.1", 0), _FleetStubHandler)
        self._httpd.owner = self
        threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.02}, daemon=True,
        ).start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self._httpd.server_address[1]}"

    def kill(self):
        self._httpd.shutdown()
        self._httpd.server_close()


class _HangingServer:
    """Accepts the TCP connection, then never answers — the hung-socket
    flavor of a dying replica (distinct from ECONNREFUSED)."""

    def __init__(self, delay_s=5.0):
        self.delay_s = delay_s
        self._sock = socket.socket()
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(4)
        self._stop = threading.Event()
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self):
        self._sock.settimeout(0.2)
        conns = []
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
                conns.append(conn)  # hold it open, answer nothing
            except socket.timeout:
                continue
            except OSError:
                break
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    @property
    def url(self):
        return f"http://127.0.0.1:{self._sock.getsockname()[1]}"

    def kill(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


def _http(method, port, path, body=None, timeout=10):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=(json.dumps(body).encode() if body is not None else None),
        headers={"Content-Type": "application/json"},
        method=method,
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        raw = resp.read()
        ctype = resp.headers.get("Content-Type", "")
        return resp.status, raw, ctype


class TestRouterFleetEndpoints:
    def _fleet(self, n=2):
        stubs = [_FleetStub(f"r{i}") for i in range(n)]
        router = FleetRouter(
            [f"{s.name}={s.url}" for s in stubs],
            registry=MetricsRegistry(),
        )
        scraper = FleetScraper(
            [(rep.name, rep.url) for rep in router.replicas],
            registry=router.registry, usage=router.usage,
            interval_s=30.0,  # driven by hand via scrape_once
        )
        server = RouterServer(
            router, port=0, probes=False, fleet=scraper
        ).start()
        return stubs, router, scraper, server

    def test_fleet_metrics_round_trip_and_usage_join(self):
        stubs, router, scraper, server = self._fleet(2)
        try:
            port = server.port
            for seed, tenant in ((1, "acme"), (2, "acme"), (3, "zyx")):
                status, raw, _ = _http(
                    "POST", port, "/generate",
                    {"prompt": "x", "seed": seed, "tenant": tenant},
                )
                assert status == 200
            scraper.scrape_once()

            # federation round-trips through our own parser
            status, raw, ctype = _http("GET", port, "/fleet/metrics")
            assert status == 200 and "text/plain" in ctype
            fams = parse_exposition(raw.decode())
            assert "dalle_serving_mfu:fleet_max" in fams
            replicas = {
                s.labels.get("replica")
                for s in fams["dalle_serving_mfu"].samples
            }
            assert replicas == {"r0", "r1"}

            # usage: chip-seconds joined from the replicas' latency_ms
            status, raw, _ = _http("GET", port, "/debug/usage")
            usage = json.loads(raw)
            rows = {r["tenant"]: r for r in usage["tenants"]}
            assert rows["acme"]["requests"] == 2
            assert rows["zyx"]["requests"] == 1
            # 3 requests x 250ms replica-reported wall
            assert usage["totals"]["chip_seconds"] == pytest.approx(
                0.75, abs=1e-6
            )
            assert usage["totals"]["decoded_tokens"] == 48

            # /debug/fleet: freshness + bloom digest + capacity signal
            status, raw, _ = _http("GET", port, "/debug/fleet")
            detail = json.loads(raw)
            assert detail["replicas"]["r0"]["generation"] >= 1
            assert detail["replicas"]["r0"]["stale"] is False
            assert detail["replicas"]["r0"]["prefix_bloom"]["b64"] == "AAAA"
            assert "suggested_replicas" in detail["capacity"]
            assert detail["usage"]["totals"]["requests"] == 3
        finally:
            server.shutdown()
            for s in stubs:
                s.kill()

    def test_killed_replica_goes_stale_routing_unaffected(self):
        stubs, router, scraper, server = self._fleet(2)
        try:
            port = server.port
            scraper.scrape_once()
            stubs[0].kill()
            scraper.scrape_once()
            status, raw, _ = _http("GET", port, "/fleet/metrics")
            fams = parse_exposition(raw.decode())
            stale = {
                s.labels["replica"]: s.value
                for s in fams["dalle_fleet_scrape_stale"].samples
            }
            assert stale["r0"] == 1.0 and stale["r1"] == 0.0
            # routing still works through the surviving replica
            status, raw, _ = _http(
                "POST", port, "/generate", {"prompt": "x", "seed": 9}
            )
            assert status == 200
        finally:
            server.shutdown()
            for s in stubs:
                s.kill()

    def test_fleet_endpoints_404_when_disabled(self):
        stub = _FleetStub("r0")
        router = FleetRouter(
            [f"r0={stub.url}"], registry=MetricsRegistry()
        )
        server = RouterServer(router, port=0, probes=False).start()
        try:
            for path in ("/fleet/metrics", "/debug/fleet"):
                with pytest.raises(urllib.error.HTTPError) as e:
                    _http("GET", server.port, path)
                assert e.value.code == 404
                e.value.read()
            # /debug/usage always works: the ledger is the router's own
            status, raw, _ = _http("GET", server.port, "/debug/usage")
            assert status == 200
        finally:
            server.shutdown()
            stub.kill()


# ----------------------------------------- warm-fleet acceptance (slow)


@pytest.mark.slow
def test_warm_fleet_under_scrape_zero_compiles_and_usage_joins():
    """The PR's acceptance pin: a warm 2-replica fleet under active
    scraping serves with ZERO new compiles, /fleet/metrics round-trips
    through our own parser with both replicas fresh, and the 2-tenant
    chip-second attribution lands within 10% of the measured dispatch
    wall."""
    import jax
    import jax.numpy as jnp

    from dalle_pytorch_tpu.data.tokenizer import ByteTokenizer
    from dalle_pytorch_tpu.models.dalle import DALLE
    from dalle_pytorch_tpu.serving.engine import ContinuousEngine
    from dalle_pytorch_tpu.serving.server import ServingServer

    text_seq, fmap = 8, 4
    model = DALLE(
        dim=32, depth=2, heads=2, dim_head=8, num_image_tokens=32,
        image_fmap_size=fmap, num_text_tokens=64, text_seq_len=text_seq,
        shift_tokens=True, rotary_emb=True,
    )
    params = jax.jit(model.init)(
        jax.random.PRNGKey(42),
        jnp.zeros((1, text_seq), jnp.int32),
        jnp.zeros((1, fmap * fmap), jnp.int32),
    )
    engines, servers = [], []
    for _ in range(2):
        eng = ContinuousEngine(
            model=model, variables=params, max_batch=2, chunk_tokens=4,
            prefill_batch=2, registry=MetricsRegistry(),
        )
        eng.tokenizer = ByteTokenizer()
        engines.append(eng)
        servers.append(
            ServingServer(eng, port=0, request_timeout_s=60).start()
        )
    router = FleetRouter(
        [f"r{i}=http://127.0.0.1:{s.port}" for i, s in enumerate(servers)],
        registry=MetricsRegistry(),
    )
    scraper = FleetScraper(
        [(rep.name, rep.url) for rep in router.replicas],
        registry=router.registry, usage=router.usage, interval_s=30.0,
    )
    front = RouterServer(router, port=0, probes=False, fleet=scraper).start()

    def _misses():
        return [
            e.registry.get(
                "dalle_serving_engine_compile_misses_total"
            ).value
            for e in engines
        ]

    try:
        # warm: enough sequential singles to compile both replicas
        for seed in range(4):
            status, _, _ = _http(
                "POST", front.port, "/generate",
                {"prompt": "warm", "seed": seed}, timeout=300,
            )
            assert status == 200
        warm_misses = _misses()

        scraper.scrape_once()
        dispatch_wall = 0.0
        client_wall = 0.0
        for seed, tenant in (
            (10, "tenant-a"), (11, "tenant-b"),
            (12, "tenant-a"), (13, "tenant-b"),
        ):
            t0 = time.monotonic()
            status, raw, _ = _http(
                "POST", front.port, "/generate",
                {"prompt": "x", "seed": seed, "tenant": tenant},
                timeout=300,
            )
            client_wall += time.monotonic() - t0
            assert status == 200
            dispatch_wall += json.loads(raw)["latency_ms"] / 1000.0
            scraper.scrape_once()  # scraping interleaves with dispatch

        # the acceptance headline: warm traffic under scrape pins ZERO
        # new compiles (a scrape that perturbed program shapes would
        # show up here)
        assert _misses() == warm_misses

        status, raw, _ = _http("GET", front.port, "/fleet/metrics")
        fams = parse_exposition(raw.decode())
        stale = {
            s.labels["replica"]: s.value
            for s in fams["dalle_fleet_scrape_stale"].samples
        }
        assert stale == {"r0": 0.0, "r1": 0.0}
        # the replicas' decode counters federate with fleet rollups
        assert any(name.endswith(":fleet_sum") for name in fams)

        # 2-tenant chip-seconds within 10% of the total dispatch wall
        # (the replica-reported latency; the client clock bounds it
        # from above with router+HTTP overhead on top)
        rows = [
            r for r in router.usage.summary()["tenants"]
            if r["tenant"].startswith("tenant-")
        ]
        assert {r["tenant"] for r in rows} == {"tenant-a", "tenant-b"}
        attributed = sum(r["chip_seconds"] for r in rows)
        assert attributed == pytest.approx(dispatch_wall, rel=0.10)
        assert attributed <= client_wall
    finally:
        front.shutdown()
        for s in servers:
            s.shutdown()
