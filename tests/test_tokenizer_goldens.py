"""Byte-exact CLIP-BPE parity for SimpleTokenizer.

The fixture `tests/fixtures/clip_bpe_goldens.json` holds token ids
produced by the published OpenAI-CLIP BPE algorithm (as vendored by the
reference, `/root/reference/dalle_pytorch/tokenizer.py:55-152`) over the
standard `bpe_simple_vocab_16e6.txt` merges file, with ftfy text-fixing
as identity (every fixture string is already clean text — ftfy is absent
in this environment for both implementations, so the comparison is
apples-to-apples).

These goldens caught two real divergences when first introduced: the
vocabulary must list printable byte symbols before the remapped
non-printables (ids are positions in that list), and the control tokens
<|startoftext|>/<|endoftext|> must bypass byte-BPE entirely.

Regenerating the fixture requires a CLIP-format merges file; the golden
ids themselves are environment-independent facts about the published
vocabulary, so the fixture is committed.
"""

import json
from pathlib import Path

import pytest

FIXTURE = Path(__file__).parent / "fixtures" / "clip_bpe_goldens.json"
# the standard 262k-line CLIP merges file; vendored by the reference but
# not by this repo (3 MB, and this environment has no egress to fetch it)
VOCAB_CANDIDATES = [
    Path("/root/reference/dalle_pytorch/data/bpe_simple_vocab_16e6.txt"),
    Path.home() / ".cache" / "dalle" / "bpe_simple_vocab_16e6.txt",
]

vocab_path = next((p for p in VOCAB_CANDIDATES if p.exists()), None)

pytestmark = pytest.mark.skipif(
    vocab_path is None,
    reason="no CLIP bpe_simple_vocab_16e6.txt available on this machine",
)


@pytest.fixture(scope="module")
def simple_tokenizer():
    from dalle_pytorch_tpu.data.tokenizer import SimpleTokenizer

    return SimpleTokenizer(vocab_path)


@pytest.fixture(scope="module")
def goldens():
    return json.loads(FIXTURE.read_text(encoding="utf8"))


class TestClipBpeGoldens:
    def test_vocab_size(self, simple_tokenizer, goldens):
        assert simple_tokenizer.vocab_size == goldens["vocab_size"] == 49408

    def test_control_token_ids(self, simple_tokenizer):
        # fixed positions at the end of the 49,408-token vocabulary
        assert simple_tokenizer.sot == 49406
        assert simple_tokenizer.eot == 49407

    def test_encode_byte_exact(self, simple_tokenizer, goldens):
        for case in goldens["cases"]:
            got = simple_tokenizer.encode(case["text"])
            assert got == case["ids"], (
                f"tokenization of {case['text']!r} diverged from the "
                f"published CLIP BPE: want {case['ids']}, got {got}"
            )

    def test_decode_round_trip(self, simple_tokenizer, goldens):
        # decode(encode(x)) recovers the cleaned, lowercased text for
        # word-and-space cases; punctuation does NOT round-trip exactly
        # because every end-of-word marker becomes a space (reference
        # decode behaves identically, `tokenizer.py:105-110`)
        checked = 0
        for case in goldens["cases"]:
            text = case["text"]
            if not text or not text.replace(" ", "").isalnum() or not text.isascii():
                continue
            cleaned = " ".join(text.split()).strip().lower()
            assert simple_tokenizer.decode(case["ids"]) == cleaned
            checked += 1
        assert checked >= 3  # the fixture keeps several such cases

    def test_tokenize_packs_and_truncates(self, simple_tokenizer):
        arr = simple_tokenizer.tokenize(["a cat", "a dog"], context_length=8)
        assert arr.shape == (2, 8) and arr.dtype.name == "int32"
        with pytest.raises(RuntimeError, match="too long"):
            simple_tokenizer.tokenize(
                "a very long caption about a cat", context_length=2
            )
