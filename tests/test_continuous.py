"""Continuous batching: slot allocator, decode-composition invariance,
admission batcher, and the HTTP front end over a `ContinuousEngine`.

The load-bearing contract is DECODE-COMPOSITION INVARIANCE: a request's
tokens are bit-identical whether served alone, inside a padded micro-batch,
or admitted mid-flight into a running continuous batch. It holds because
every per-row quantity — cache index, token-shift ring position, RNG key
(seed, image position), temperature/top-k — is threaded per slot, and the
per-row numerics of the chunked decode match the lockstep scan exactly
(`ops/sampling.py:per_row_step_keys` is the single RNG derivation for
both). These tests pin it for the unrolled executor with token-shift rings
(the per-row ring path), the scan executor (depth-stacked per-row cache),
and the non-rotary axial positional table (per-row row lookup).
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dalle_pytorch_tpu.models.dalle import DALLE
from dalle_pytorch_tpu.serving.batcher import ContinuousBatcher
from dalle_pytorch_tpu.serving.engine import (
    ContinuousEngine,
    GenerationEngine,
    SampleSpec,
    SlotAllocator,
)
from dalle_pytorch_tpu.serving.server import ServingServer
from dalle_pytorch_tpu.training.metrics import MetricsRegistry

TEXT_SEQ = 8
FMAP = 4
IMG_SEQ = FMAP * FMAP


def _build(batch_shapes=(1, 4), max_batch=4, chunk_tokens=4,
           prefill_batch=4, **model_kw):
    """(micro engine, continuous engine) over ONE set of toy weights."""
    kw = dict(
        dim=32, depth=2, heads=2, dim_head=8,
        num_image_tokens=32, image_fmap_size=FMAP,
        num_text_tokens=64, text_seq_len=TEXT_SEQ,
        shift_tokens=True, rotary_emb=True,
    )
    kw.update(model_kw)
    model = DALLE(**kw)
    text = jnp.zeros((1, TEXT_SEQ), jnp.int32)
    toks = jnp.zeros((1, IMG_SEQ), jnp.int32)
    params = jax.jit(model.init)(jax.random.PRNGKey(42), text, toks)
    micro = GenerationEngine(
        model=model, variables=params, batch_shapes=batch_shapes,
        registry=MetricsRegistry(),
    )
    cont = ContinuousEngine(
        model=model, variables=params, max_batch=max_batch,
        chunk_tokens=chunk_tokens, prefill_batch=prefill_batch,
        registry=MetricsRegistry(),
    )
    return micro, cont


def spec(seed, temperature=1.0, top_k=0.9):
    ids = np.zeros(TEXT_SEQ, np.int32)
    ids[:3] = (5, 6, 7)
    return SampleSpec(ids, seed=seed, temperature=temperature, top_k=top_k)


def _drain(cont, max_chunks=32):
    """Chunk until every active slot finishes; returns (img_pos, active)."""
    for _ in range(max_chunks):
        pos, act = cont.step_chunk()
        if (pos[act] >= cont.image_seq_len).all():
            return pos, act
    raise AssertionError("continuous decode never finished")


# ---------------------------------------------------------- slot allocator


class TestSlotAllocator:
    def test_exhaustion_returns_none(self):
        a = SlotAllocator(2)
        assert a.alloc() is not None
        assert a.alloc() is not None
        assert a.alloc() is None  # exhausted -> caller keeps request queued
        assert a.n_free == 0 and a.n_active == 2

    def test_retire_then_reuse(self):
        a = SlotAllocator(2)
        s0, s1 = a.alloc(), a.alloc()
        a.free(s0)
        assert a.n_free == 1
        assert a.alloc() == s0  # lowest free slot comes back

    def test_no_aliasing(self):
        """A slot is never handed out twice while in use, across heavy
        alloc/free churn."""
        a = SlotAllocator(4)
        live = set()
        rng = np.random.default_rng(0)
        for _ in range(200):
            if live and rng.random() < 0.4:
                s = live.pop()
                a.free(s)
            else:
                s = a.alloc()
                if s is None:
                    assert len(live) == 4
                    continue
                assert s not in live, "allocator aliased a live slot"
                live.add(s)
        assert a.n_active == len(live)

    def test_double_free_rejected(self):
        a = SlotAllocator(1)
        s = a.alloc()
        a.free(s)
        with pytest.raises(AssertionError):
            a.free(s)


# ------------------------------------- decode-composition invariance (core)


@pytest.fixture(scope="module")
def engines():
    return _build()


class TestDecodeCompositionInvariance:
    def test_alone_vs_padded_vs_midflight(self, engines):
        """The acceptance invariant: one request, three serving paths, one
        bit pattern. Mid-flight admission happens while another slot is
        half-way through its image."""
        micro, cont = engines
        alone, _ = micro.generate([spec(55)])
        padded, _ = micro.generate([spec(99), spec(55), spec(7)])
        np.testing.assert_array_equal(alone[0], padded[1])

        cont.prefill_slot(0, spec(99))
        cont.step_chunk()  # slot 0 is now mid-image
        cont.prefill_slot(1, spec(55))  # admitted mid-flight
        pos, act = _drain(cont)
        assert act[:2].all() and (pos[:2] >= IMG_SEQ).all()
        harvested = cont.harvest([0, 1])
        cont.release([0, 1])
        np.testing.assert_array_equal(harvested[1], alone[0])
        np.testing.assert_array_equal(harvested[0], padded[0])

    def test_slot_reuse_no_state_leak(self, engines):
        """A retired slot's next occupant decodes the same tokens as a
        fresh engine would — admission overwrites every cache position."""
        micro, cont = engines
        alone, _ = micro.generate([spec(123)])
        cont.prefill_slot(2, spec(7))
        _drain(cont)
        cont.release([2])
        cont.prefill_slot(2, spec(123))  # reuse the just-retired slot
        _drain(cont)
        toks = cont.harvest([2])
        cont.release([2])
        np.testing.assert_array_equal(toks[0], alone[0])

    def test_per_row_params_mid_flight(self, engines):
        """Per-slot temperature/top-k really are per slot: a greedy row
        admitted next to a hot row reproduces the micro engine's greedy
        output."""
        micro, cont = engines
        greedy = spec(3, temperature=1e-6, top_k=1.0)
        alone, _ = micro.generate([greedy])
        cont.prefill_slot(0, spec(9, temperature=1.0, top_k=0.0))
        cont.step_chunk()
        cont.prefill_slot(1, greedy)
        _drain(cont)
        toks = cont.harvest([0, 1])
        cont.release([0, 1])
        np.testing.assert_array_equal(toks[1], alone[0])


class TestBatchedPrefill:
    """Batched multi-slot admission (`prefill_into_slots`): composition
    invariance and the ceil(R / prefill_batch) dispatch contract."""

    def test_together_vs_one_at_a_time(self, engines):
        """The acceptance invariant: admitting rows {a, b} in ONE batched
        dispatch yields the same tokens as admitting them one at a time —
        and both match the micro engine serving each row alone."""
        micro, _ = engines
        _, cont = _build(prefill_batch=2)
        alone99, _ = micro.generate([spec(99)])
        alone55, _ = micro.generate([spec(55)])

        cont.prefill_slots([(0, spec(99)), (1, spec(55))])  # together
        _drain(cont)
        together = cont.harvest([0, 1])
        cont.release([0, 1])

        cont.prefill_slot(2, spec(99))  # one at a time, mid-flight apart
        cont.step_chunk()
        cont.prefill_slot(3, spec(55))
        _drain(cont)
        separate = cont.harvest([2, 3])
        cont.release([2, 3])

        np.testing.assert_array_equal(together[0], alone99[0])
        np.testing.assert_array_equal(together[1], alone55[0])
        np.testing.assert_array_equal(separate[0], together[0])
        np.testing.assert_array_equal(separate[1], together[1])

    def test_short_wave_padding_is_harmless(self):
        """A 1-row wave through prefill_batch=4 pads by repeating the row;
        the duplicate writes must not perturb the admitted slot or the
        mid-image neighbor they sit next to."""
        micro, cont = _build(prefill_batch=4)
        alone7, _ = micro.generate([spec(7)])
        alone99, _ = micro.generate([spec(99)])
        cont.prefill_slot(0, spec(99))  # established neighbor
        cont.step_chunk()  # slot 0 is mid-image
        cont.prefill_slots([(2, spec(7))])  # short wave, 3 padding rows
        pos, act = _drain(cont)
        assert act[0] and act[2]
        toks = cont.harvest([0, 2])
        cont.release([0, 2])
        np.testing.assert_array_equal(toks[1], alone7[0])
        np.testing.assert_array_equal(toks[0], alone99[0])

    def test_dispatch_count_and_zero_compiles(self):
        """Admitting R rows costs ceil(R / prefill_batch) prefill
        dispatches, and — warmup having compiled the ONE batched prefill
        program — a full post-warmup admit/decode/retire cycle compiles
        nothing (utils/compile_guard.py)."""
        from dalle_pytorch_tpu.utils import assert_no_recompiles

        _, cont = _build(prefill_batch=2)
        cont.warmup()
        with assert_no_recompiles() as tally:
            cont.prefill_slots([(0, spec(1)), (1, spec(2))])
            cont.prefill_slots([(2, spec(3))])  # R=3 -> ceil(3/2)=2 waves
            _drain(cont)
            toks = cont.harvest([0, 1, 2])
            cont.release([0, 1, 2])
        assert tally.count == 0
        assert toks.shape == (3, IMG_SEQ)
        reg = cont.registry
        assert reg.get("dalle_serving_prefills_total").value == 3
        assert reg.get("dalle_serving_prefill_dispatches_total").value == 2

    def test_batcher_splits_admission_waves(self):
        """The worker admits a queued backlog in groups of the engine's
        prefill_batch. A dummy request parks the worker inside a gated
        chunk while the real backlog queues, so the admission wave is
        deterministic: 4 free slots, 4 queued rows -> dispatches [2, 2],
        then the leftover row -> [1]."""
        gate = threading.Event()
        eng = FakeBatchedEngine(prefill_batch=2, chunk=8, block_event=gate)
        b = ContinuousBatcher(eng, registry=eng.registry)
        park = b.submit([spec(41)])  # worker admits this, blocks in chunk
        assert eng.chunk_entered.wait(10.0)  # worker provably parked
        reqs = [b.submit([spec(i)]) for i in range(5)]
        gate.set()
        park.future.result(timeout=10)
        for i, r in enumerate(reqs):
            toks, _ = r.future.result(timeout=10)
            assert int(toks[0, 0]) == i
        b.shutdown()
        # calls: [1] (dummy), [2, 2] (the parked backlog wave), [1]
        assert eng.prefill_calls == [1, 2, 2, 1]


class TestInvarianceAcrossExecutors:
    def test_scan_executor(self):
        """Per-row index rides the depth-stacked scan cache too."""
        micro, cont = _build(executor="scan")
        alone, _ = micro.generate([spec(55)])
        cont.prefill_slot(3, spec(99))
        cont.step_chunk()
        cont.prefill_slot(0, spec(55))
        _drain(cont)
        toks = cont.harvest([0])
        np.testing.assert_array_equal(toks[0], alone[0])

    def test_flash_decode_impl(self):
        """The whole continuous stack over the Pallas flash-decode kernel
        (attn_impl="flash", interpret mode on CPU): batched admission and
        mid-flight admission still reproduce the micro engine bit-for-bit
        — both engines run the SAME kernel per row, so per-row live
        lengths vs lockstep decode cannot drift."""
        micro, cont = _build(attn_impl="flash", prefill_batch=2)
        alone, _ = micro.generate([spec(55)])
        cont.prefill_slot(0, spec(99))
        cont.step_chunk()  # slot 0 mid-image
        cont.prefill_slots([(1, spec(55)), (2, spec(7))])
        _drain(cont)
        toks = cont.harvest([1])
        cont.release([0, 1, 2])
        np.testing.assert_array_equal(toks[0], alone[0])

    def test_non_rotary_axial_positions(self):
        """Per-row lookup into the axial positional table."""
        micro, cont = _build(rotary_emb=False, shift_tokens=False)
        alone, _ = micro.generate([spec(55)])
        cont.prefill_slot(1, spec(99))
        cont.step_chunk()
        cont.prefill_slot(2, spec(55))
        _drain(cont)
        toks = cont.harvest([2])
        np.testing.assert_array_equal(toks[0], alone[0])


# ------------------------------------------------------- engine-level misc


class TestContinuousEngine:
    def test_warmup_counts_compile_only(self):
        _, cont = _build()
        cont.warmup()
        assert cont.stats.warmup_batches == 1
        assert cont.stats.batches == 0
        assert cont.stats.rows_generated == 0
        assert cont.stats.compiled_shapes == (4,)
        # post-warmup state is clean: no active slots, no positions
        pos, act = cont.step_chunk(_warmup=True)
        assert not act.any() and (pos == 0).all()

    def test_steady_state_compiles_nothing_after_warmup(self):
        """Warmup compiles the full fixed-shape program set (prefill,
        chunk, release, pixel decode); a post-warmup serve cycle — admit,
        chunk to completion, mid-flight admission, harvest, release — must
        hit only the compile cache. Guarded by the jax.monitoring-based
        `assert_no_recompiles`, which counts every backend compilation
        including first-execution compiles of stray eager ops."""
        from dalle_pytorch_tpu.utils import assert_no_recompiles

        _, cont = _build()
        cont.warmup()
        with assert_no_recompiles() as tally:
            cont.prefill_slot(0, spec(11))
            cont.step_chunk()
            cont.prefill_slot(1, spec(22))  # mid-flight admission
            _drain(cont)
            toks = cont.harvest([0, 1])
            cont.release([0, 1])
        assert tally.count == 0
        assert toks.shape == (2, IMG_SEQ)

    def test_recompile_guard_catches_new_shape(self):
        """The guard actually fires: a fresh batch shape inside the block
        is a compile, and the error names the compile event."""
        import jax.numpy as jnp

        from dalle_pytorch_tpu.utils import RecompileError, assert_no_recompiles

        f = jax.jit(lambda x: x * 2)
        f(jnp.ones((3,)))  # warm one shape
        with assert_no_recompiles():
            f(jnp.ones((3,)))  # cache hit: fine
        with pytest.raises(RecompileError, match="compiled"):
            with assert_no_recompiles():
                f(jnp.ones((5,)))  # new shape -> new program

    def test_cond_scale_rejected(self):
        micro, _ = _build()
        with pytest.raises(AssertionError, match="cond_scale"):
            ContinuousEngine(
                model=micro.model, variables=micro.variables,
                max_batch=2, cond_scale=3.0,
            )

    def test_pixels_match_micro_engine(self):
        """`decode_pixels` (pad-to-shape dVAE decode + un-normalize) must
        produce the same pixels as the micro engine's fused decode for the
        same request — including when the harvested row count does not
        divide the decode shape."""
        from dalle_pytorch_tpu.models.dvae import DiscreteVAE

        vae = DiscreteVAE(
            image_size=16, num_layers=2, num_tokens=32,
            codebook_dim=16, hidden_dim=16,
        )
        vae_params = vae.init(
            {"params": jax.random.PRNGKey(0), "gumbel": jax.random.PRNGKey(1)},
            jnp.zeros((1, 16, 16, 3)),
        )["params"]
        micro, cont = _build()
        micro.vae = cont.vae = vae
        micro.vae_params = cont.vae_params = vae_params
        toks, pixels = micro.generate([spec(4), spec(5), spec(6)])
        assert pixels.shape == (3, 16, 16, 3)
        cont_pixels = cont.decode_pixels(toks)  # 3 rows through shape 4
        np.testing.assert_allclose(cont_pixels, pixels, atol=1e-6)
        # > max_batch rows: the padding loop wraps into two dispatches
        toks6 = np.concatenate([toks, toks])
        np.testing.assert_allclose(
            cont.decode_pixels(toks6), np.concatenate([pixels, pixels]),
            atol=1e-6,
        )

    def test_micro_warmup_tagged(self):
        micro, _ = _build(batch_shapes=(1, 2))
        micro.warmup()
        assert micro.stats.warmup_batches == 2
        assert micro.stats.batches == 0
        assert micro.stats.rows_generated == 0
        assert micro.stats.rows_padded == 0
        micro.generate([spec(0)])
        assert micro.stats.batches == 1
        assert micro.stats.rows_generated == 1


# ----------------------------------------------------- continuous batcher


class FakeContinuousEngine:
    """Slot-surface double for batcher policy tests: each chunk advances
    every active slot by `chunk` positions; tokens carry the seed."""

    image_seq_len = 8
    max_batch = 4

    def __init__(
        self, chunk=4, fail_chunks=False, fail_release=False,
        block_event=None,
    ):
        self.registry = MetricsRegistry()
        self.chunk = chunk
        self.fail_chunks = fail_chunks
        self.fail_release = fail_release
        self.block_event = block_event
        # set when the worker ENTERS a gated chunk — tests that need the
        # worker provably parked wait on this instead of sleeping
        self.chunk_entered = threading.Event()
        self.pos = np.zeros(self.max_batch, np.int64)
        self.active = np.zeros(self.max_batch, bool)
        self.seeds = np.zeros(self.max_batch, np.int64)

    def prefill_slot(self, slot, sp):
        self.pos[slot] = 0
        self.active[slot] = True
        self.seeds[slot] = sp.seed

    def step_chunk(self):
        if self.block_event is not None:
            self.chunk_entered.set()
            assert self.block_event.wait(10.0)
        if self.fail_chunks:
            raise RuntimeError("XLA fell over")
        live = self.active & (self.pos < self.image_seq_len)
        self.pos[live] += self.chunk
        return self.pos.copy(), self.active.copy()

    def harvest(self, slots):
        return np.stack([
            np.full(self.image_seq_len, self.seeds[s], np.int32)
            for s in slots
        ])

    def release(self, slots):
        if self.fail_release:
            raise RuntimeError("release blew up")
        for s in slots:
            self.active[s] = False

    def decode_pixels(self, tokens):
        return None

    def slots_active_gauge(self, n):
        self.registry.gauge("dalle_serving_slots_active").set(n)


class FakeBatchedEngine(FakeContinuousEngine):
    """Adds the batched-admission surface: `prefill_slots` + `prefill_batch`,
    recording each dispatch's row count for the wave-splitting tests."""

    def __init__(self, prefill_batch=2, **kw):
        super().__init__(**kw)
        self.prefill_batch = prefill_batch
        self.prefill_calls = []

    def prefill_slots(self, assignments):
        assert 1 <= len(assignments) <= self.prefill_batch
        self.prefill_calls.append(len(assignments))
        for slot, sp in assignments:
            super().prefill_slot(slot, sp)

    def prefill_slot(self, slot, sp):  # the batcher must not use this path
        raise AssertionError(
            "batcher fell back to per-row prefill despite prefill_slots"
        )


class TestContinuousBatcher:
    def test_requests_complete_and_ttft_recorded(self):
        eng = FakeContinuousEngine()
        b = ContinuousBatcher(eng, registry=eng.registry)
        reqs = [b.submit([spec(i)]) for i in range(3)]
        outs = [r.future.result(timeout=10) for r in reqs]
        for i, (toks, pix) in enumerate(outs):
            assert toks.shape == (1, 8) and int(toks[0, 0]) == i
            assert pix is None
        assert all(r.first_token_at is not None for r in reqs)
        ttft = b.registry.get("dalle_serving_ttft_seconds")
        assert ttft.count == 3
        b.shutdown()
        assert b.registry.get("dalle_serving_slots_active").value == 0

    def test_multi_row_request_stays_whole(self):
        eng = FakeContinuousEngine()
        b = ContinuousBatcher(eng, registry=eng.registry)
        r = b.submit([spec(5), spec(6), spec(7)])
        toks, _ = r.future.result(timeout=10)
        assert [int(t[0]) for t in toks] == [5, 6, 7]
        b.shutdown()

    def test_backfill_more_requests_than_slots(self):
        """8 single-row requests through 4 slots: retirements free slots
        for queued requests without any flush barrier."""
        eng = FakeContinuousEngine(chunk=2)
        b = ContinuousBatcher(eng, registry=eng.registry)
        reqs = [b.submit([spec(i)]) for i in range(8)]
        for i, r in enumerate(reqs):
            toks, _ = r.future.result(timeout=10)
            assert int(toks[0, 0]) == i
        assert b.registry.get("dalle_serving_admitted_total").value == 8
        b.shutdown()

    def test_engine_error_fails_fast(self):
        eng = FakeContinuousEngine(fail_chunks=True)
        b = ContinuousBatcher(eng, registry=eng.registry)
        r = b.submit([spec(0)])
        with pytest.raises(RuntimeError, match="XLA fell over"):
            r.future.result(timeout=10)
        assert isinstance(b.last_error, RuntimeError)
        # recovery: the engine comes back, new requests succeed
        eng.fail_chunks = False
        eng.active[:] = False
        r2 = b.submit([spec(1)])
        toks, _ = r2.future.result(timeout=10)
        assert int(toks[0, 0]) == 1
        assert b.last_error is None
        b.shutdown()

    def test_retire_failure_does_not_kill_worker(self):
        """harvest/release are engine dispatches too: a failure at the
        retirement boundary must fail the live requests and leave the
        worker alive (a dead worker would accept requests forever without
        serving or timing them out)."""
        eng = FakeContinuousEngine(fail_release=True)
        b = ContinuousBatcher(eng, registry=eng.registry)
        r = b.submit([spec(0)])
        with pytest.raises(RuntimeError, match="release blew up"):
            r.future.result(timeout=10)
        assert isinstance(b.last_error, RuntimeError)
        eng.fail_release = False  # transient; slot reuse re-prefills anyway
        r2 = b.submit([spec(1)])
        toks, _ = r2.future.result(timeout=10)
        assert int(toks[0, 0]) == 1
        assert b.last_error is None
        b.shutdown()

    def test_graceful_shutdown_drains(self):
        gate = threading.Event()
        eng = FakeContinuousEngine(block_event=gate)
        b = ContinuousBatcher(eng, registry=eng.registry)
        reqs = [b.submit([spec(i)]) for i in range(6)]
        time.sleep(0.1)
        gate.set()
        b.shutdown(drain=True)
        for i, r in enumerate(reqs):
            toks, _ = r.future.result(timeout=1)
            assert int(toks[0, 0]) == i

    def test_real_engine_through_batcher_matches_alone(self, engines):
        """End-to-end: tokens served through the admission loop equal the
        micro engine's single-request output bit-for-bit."""
        micro, _ = engines
        _, cont = _build(max_batch=2, chunk_tokens=4)
        alone, _ = micro.generate([spec(55)])
        b = ContinuousBatcher(cont, registry=cont.registry)
        reqs = [b.submit([spec(s)]) for s in (99, 55, 7)]
        outs = [r.future.result(timeout=60) for r in reqs]
        np.testing.assert_array_equal(outs[1][0][0], alone[0])
        b.shutdown()


# ------------------------------------------------------------- HTTP layer


def _post(port, body, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(port, path, timeout=10):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as resp:
        return resp.status, resp.read().decode()


class TestContinuousServing:
    def test_server_over_continuous_engine(self):
        from dalle_pytorch_tpu.data.tokenizer import ByteTokenizer

        _, cont = _build(max_batch=2, chunk_tokens=4)
        cont.tokenizer = ByteTokenizer()
        cont.warmup()
        server = ServingServer(cont, port=0, request_timeout_s=60).start()
        try:
            port = server.port
            body = {"prompt": "red circle", "seed": 77}
            _, p1 = _post(port, body)
            _, p2 = _post(port, body)
            assert p1["tokens"] == p2["tokens"]
            assert len(p1["tokens"][0]) == IMG_SEQ

            status, health = _get(port, "/healthz")
            health = json.loads(health)
            assert status == 200 and health["status"] == "ok"
            assert health["engine"] == "continuous"
            assert health["slots_active"] == 0
            assert health["chunk_tokens"] == 4

            _, text = _get(port, "/metrics")
            assert "dalle_serving_slots_active" in text
            assert "dalle_serving_ttft_seconds_bucket" in text
            assert "dalle_serving_chunks_total" in text
        finally:
            server.shutdown()
