"""Analytic FLOPs/MFU model (utils/flops.py)."""

import pytest

from dalle_pytorch_tpu.utils.flops import (
    dalle_train_flops_per_sample,
    mfu,
    peak_flops_per_chip,
    transformer_train_flops,
)


class TestFlops:
    def test_peak_lookup(self):
        assert peak_flops_per_chip("TPU v5 lite") == 197e12
        assert peak_flops_per_chip("TPU v4") == 275e12
        assert peak_flops_per_chip("cpu") == 5e11
        assert peak_flops_per_chip("mystery accelerator") == 197e12

    def test_flagship_magnitude(self):
        # dim1024/depth12/seq1280: ~1.8e12 matmul FLOPs per sample
        f = transformer_train_flops(1024, 12, 16, 64, 1280)
        assert 1e12 < f < 3e12

    def test_model_accessor_matches_direct(self):
        from dalle_pytorch_tpu.models.dalle import DALLE

        m = DALLE(dim=64, depth=2, heads=4, dim_head=16, num_image_tokens=32,
                  image_fmap_size=4, num_text_tokens=60, text_seq_len=12)
        assert dalle_train_flops_per_sample(m) == transformer_train_flops(
            64, 2, 4, 16, m.total_seq_len, vocab=m.total_tokens
        )
        # the logits head is counted (standard MFU includes the LM head)
        assert dalle_train_flops_per_sample(m) > transformer_train_flops(
            64, 2, 4, 16, m.total_seq_len
        )

    def test_mode_aware_passes(self):
        # dual-objective modes run the transformer twice per sample
        # (training/steps.py loss_fn), so the MFU numerator doubles
        from dalle_pytorch_tpu.models.dalle import DALLE
        from dalle_pytorch_tpu.training.steps import MODES
        from dalle_pytorch_tpu.utils.flops import OBJECTIVE_PASSES

        assert set(OBJECTIVE_PASSES) == set(MODES)
        m = DALLE(dim=64, depth=2, heads=4, dim_head=16, num_image_tokens=32,
                  image_fmap_size=4, num_text_tokens=60, text_seq_len=12)
        base = dalle_train_flops_per_sample(m, mode="forward_only")
        assert dalle_train_flops_per_sample(m, mode="reverse_only") == base
        assert dalle_train_flops_per_sample(m, mode="forward_forward") == 2 * base
        assert (
            dalle_train_flops_per_sample(m, mode="forward_reverse_partial")
            == 2 * base
        )
        with pytest.raises(KeyError):
            dalle_train_flops_per_sample(m, mode="nonsense")

    def test_mfu(self):
        # 1 sample/s at exactly peak-flops-per-sample == MFU 1.0
        assert mfu(1.0, 197e12, "TPU v5e") == pytest.approx(1.0)
        assert mfu(0.5, 197e12, "TPU v5e", n_chips=1) == pytest.approx(0.5)
