"""GPipe pipeline-parallel engine: numerical parity with sequential
execution on the virtual 8-device CPU mesh (conftest forces
--xla_force_host_platform_device_count=8).

The oracle is the same depth-stacked lax.scan the scan executor runs;
the engine must reproduce it bitwise-close through the full
M + P - 1-tick schedule, forward AND gradients (autodiff through
ppermute runs the backward pipeline in reverse automatically).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from dalle_pytorch_tpu.parallel.gpipe import (
    gpipe_apply,
    make_pp_mesh,
    stage_params_sharding,
)

DEPTH, DIM, BATCH, SEQ = 8, 16, 8, 4


def _params(key):
    k1, k2 = jax.random.split(key)
    scale = 1.0 / np.sqrt(DIM)
    return {
        "w1": jax.random.normal(k1, (DEPTH, DIM, 2 * DIM)) * scale,
        "w2": jax.random.normal(k2, (DEPTH, 2 * DIM, DIM)) * scale,
    }


def _layer(lp, x):
    # residual MLP block: order-sensitive (non-commuting layers), so any
    # schedule mistake that reorders or drops a stage shows up
    return x + jnp.tanh(x @ lp["w1"]) @ lp["w2"]


def _sequential(params, x):
    def body(h, lp):
        return _layer(lp, h), None

    out, _ = lax.scan(body, x, params)
    return out


@pytest.mark.parametrize("pp,n_micro", [(2, 4), (4, 2), (8, 4), (4, 8)])
def test_forward_matches_sequential(pp, n_micro):
    params = _params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (BATCH, SEQ, DIM))
    want = _sequential(params, x)
    mesh = make_pp_mesh(pp)
    got = jax.jit(
        lambda p, x: gpipe_apply(mesh, p, _layer, x, n_micro)
    )(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_grads_match_sequential():
    params = _params(jax.random.PRNGKey(2))
    x = jax.random.normal(jax.random.PRNGKey(3), (BATCH, SEQ, DIM))
    mesh = make_pp_mesh(4)

    def loss_seq(p, x):
        return (_sequential(p, x) ** 2).mean()

    def loss_pp(p, x):
        return (gpipe_apply(mesh, p, _layer, x, 4) ** 2).mean()

    g_seq = jax.grad(loss_seq)(params, x)
    g_pp = jax.jit(jax.grad(loss_pp))(params, x)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        g_pp, g_seq,
    )


@pytest.mark.parametrize(
    "rotary,attn_types",
    [(False, None), (True, None),
     (True, ("full", "axial_row", "axial_col", "conv_like"))],
)
def test_pipelines_real_transformer_trunk(rotary, attn_types):
    """pipeline_trunk_apply runs the PRODUCTION trunk: a scan-executor
    Transformer's own param tree (the checkpoint layout) pipelined over
    4 stages must reproduce transformer.apply — with token-shift,
    dual-rotary embeddings, and the reference's sparse attn-type cycle
    (per-layer pattern indices ride with each stage's layer slice)."""
    from dalle_pytorch_tpu.models.transformer import (
        Transformer,
        pipeline_trunk_apply,
    )

    dim, depth, heads, dim_head, fmap = 32, 4, 2, 16, 4
    seq_len = 24  # text 9 + image 16, minus the shifted-in bos slot
    tr = Transformer(
        dim=dim, depth=depth, heads=heads, dim_head=dim_head,
        seq_len=seq_len, causal=True, image_fmap_size=fmap,
        shift_tokens=True, rotary_emb=rotary, attn_impl="dense",
        attn_types=attn_types, executor="scan",
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (BATCH, seq_len, dim))
    params = tr.init(jax.random.PRNGKey(1), x)["params"]
    want = tr.apply({"params": params}, x)

    got = jax.jit(
        lambda p, x: pipeline_trunk_apply(tr, p, make_pp_mesh(4), x, 2)
    )(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

    # per-example key-padding mask rides the microbatch schedule (aux)
    mask = jnp.arange(seq_len)[None, :] < jnp.arange(
        seq_len - BATCH, seq_len
    )[:, None]
    want_m = tr.apply({"params": params}, x, key_mask=mask)
    got_m = jax.jit(
        lambda p, x, m: pipeline_trunk_apply(
            tr, p, make_pp_mesh(4), x, 2, key_mask=m
        )
    )(params, x, mask)
    np.testing.assert_allclose(
        np.asarray(got_m), np.asarray(want_m), atol=1e-5
    )


@pytest.mark.slow  # ~21 s: remat + bf16 variants re-compile the pipelined
# trunk twice (tier-1 budget)
def test_trunk_remat_and_bf16():
    """Deployment settings: (a) reversible=True + remat policy — the
    pipelined trunk wraps layers in jax.checkpoint, values and grads
    unchanged; (b) bf16 compute dtype — pipelined forward matches the
    module at bf16 tolerance."""
    import jax.numpy as jnp

    from dalle_pytorch_tpu.models.transformer import (
        Transformer,
        pipeline_trunk_apply,
    )

    kw = dict(
        dim=32, depth=4, heads=2, dim_head=16, seq_len=24, causal=True,
        image_fmap_size=4, shift_tokens=True, rotary_emb=True,
        attn_impl="dense", executor="scan",
    )
    mesh = make_pp_mesh(4)

    # (a) remat parity incl. grads
    tr = Transformer(
        reversible=True,
        remat_policy="dots_with_no_batch_dims_saveable", **kw,
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (BATCH, 24, 32))
    params = tr.init(jax.random.PRNGKey(1), x)["params"]

    def loss_mod(p):
        return (tr.apply({"params": p}, x) ** 2).mean()

    def loss_pp(p):
        return (pipeline_trunk_apply(tr, p, mesh, x, 2) ** 2).mean()

    l_mod, g_mod = jax.value_and_grad(loss_mod)(params)
    l_pp, g_pp = jax.jit(jax.value_and_grad(loss_pp))(params)
    np.testing.assert_allclose(float(l_pp), float(l_mod), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        ),
        g_pp, g_mod,
    )

    # (b) bf16 forward parity
    tr16 = Transformer(dtype=jnp.bfloat16, **kw)
    p16 = tr16.init(jax.random.PRNGKey(2), x)["params"]
    want = tr16.apply({"params": p16}, x)
    got = jax.jit(
        lambda p, x: pipeline_trunk_apply(tr16, p, mesh, x, 2)
    )(p16, x)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        atol=3e-2,
    )


def test_composes_with_data_parallel_axis():
    """pipeline_layers is axis-parameterized (ring.py pattern), so it
    runs inside a 2-axis ('dp', 'pp') mesh: batch sharded over dp, each
    dp row driving its own 4-stage pipeline — the composition
    gpipe_apply's standalone mesh cannot express."""
    from jax.sharding import Mesh, PartitionSpec as P

    from dalle_pytorch_tpu.parallel.gpipe import pipeline_layers

    params = _params(jax.random.PRNGKey(6))
    x = jax.random.normal(jax.random.PRNGKey(7), (BATCH, SEQ, DIM))
    want = _sequential(params, x)

    dp, pp, n_micro = 2, 4, 2
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(dp, pp), ("dp", "pp"))
    staged = jax.tree.map(
        lambda a: a.reshape(pp, DEPTH // pp, *a.shape[1:]), params
    )
    mb = x.reshape(n_micro, BATCH // n_micro, SEQ, DIM)

    def stage_fn(params_local, mb_local):
        my_layers = jax.tree.map(lambda a: a[0], params_local)
        outs = pipeline_layers(
            _layer, my_layers, mb_local, axis_name="pp", n_micro=n_micro
        )
        return outs[None]

    from dalle_pytorch_tpu.parallel.mesh import shard_map

    outs = jax.jit(
        shard_map(
            stage_fn,
            mesh=mesh,
            in_specs=(P("pp"), P(None, "dp")),  # batch rows over dp
            out_specs=P("pp", None, "dp"),
            check_vma=False,
        )
    )(staged, mb)
    got = outs[-1].reshape(BATCH, SEQ, DIM)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_pipelines_unrolled_checkpoint_via_converter():
    """A trunk trained/checkpointed under the UNROLLED executor pipelines
    after unrolled_params_to_scan: legacy layout -> scan layout ->
    4-stage pipeline == the unrolled module's own forward."""
    from dalle_pytorch_tpu.models.transformer import (
        Transformer,
        pipeline_trunk_apply,
        unrolled_params_to_scan,
    )

    kw = dict(
        dim=32, depth=4, heads=2, dim_head=16, seq_len=24, causal=True,
        image_fmap_size=4, shift_tokens=True, rotary_emb=True,
        attn_impl="dense",
    )
    unrolled = Transformer(**kw)
    x = jax.random.normal(jax.random.PRNGKey(0), (BATCH, 24, 32))
    uparams = unrolled.init(jax.random.PRNGKey(1), x)["params"]
    want = unrolled.apply({"params": uparams}, x)

    sparams = unrolled_params_to_scan(uparams, depth=4)
    got = jax.jit(
        lambda p, x: pipeline_trunk_apply(
            Transformer(executor="scan", **kw), p, make_pp_mesh(4), x, 2
        )
    )(sparams, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


@pytest.mark.slow  # ~32 s: full DALLE loss + grads through the pipelined
# trunk (tier-1 budget); test_pipelines_real_transformer_trunk keeps the
# fast-tier pipeline-parity signal
def test_dalle_loss_with_pipelined_trunk():
    """End-to-end DALLE training loss with the trunk run pipeline-
    parallel (trunk_fn override): loss AND grads match the plain
    scan-executor forward — pipeline parallelism composes with the full
    model (embeddings, logits masks, CE) without touching its code."""
    import jax.numpy as jnp

    from dalle_pytorch_tpu.models.dalle import DALLE
    from dalle_pytorch_tpu.models.transformer import (
        Transformer,
        make_pipeline_trunk,
    )

    model = DALLE(
        dim=32, depth=4, num_image_tokens=16, image_fmap_size=4,
        num_text_tokens=26, text_seq_len=8, heads=2, dim_head=16,
        shift_tokens=True, rotary_emb=True, executor="scan",
    )
    text = jax.random.randint(jax.random.PRNGKey(0), (4, 8), 1, 26)
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 16)
    params = model.init(jax.random.PRNGKey(2), text, toks)["params"]
    mesh = make_pp_mesh(4)
    # built OUTSIDE model.apply (flax intercepts module construction
    # inside a parent scope)
    pipelined = make_pipeline_trunk(
        Transformer(**model.transformer_kwargs()), mesh, n_micro=2
    )

    def loss_plain(p):
        loss, _ = model.apply({"params": p}, text, toks, return_loss=True)
        return loss

    def loss_pp(p):
        trunk = lambda h: pipelined(p["transformer"], h)
        loss, _ = model.apply(
            {"params": p}, text, toks, return_loss=True, trunk_fn=trunk
        )
        return loss

    l_plain, g_plain = jax.value_and_grad(loss_plain)(params)
    l_pp, g_pp = jax.jit(jax.value_and_grad(loss_pp))(params)
    np.testing.assert_allclose(float(l_pp), float(l_plain), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        ),
        g_pp, g_plain,
    )


def test_trains_with_sharded_params():
    """One optimizer-style update with params device_put under the pp
    sharding: the jitted grad runs with stage-resident parameters (the
    deployment layout), and pp=1 degenerates to the plain scan."""
    params = _params(jax.random.PRNGKey(4))
    x = jax.random.normal(jax.random.PRNGKey(5), (BATCH, SEQ, DIM))
    mesh = make_pp_mesh(4)
    sharded = jax.device_put(params, stage_params_sharding(mesh, params))

    def loss(p, x):
        return (gpipe_apply(mesh, p, _layer, x, 2) ** 2).mean()

    l0, g = jax.jit(jax.value_and_grad(loss))(sharded, x)
    stepped = jax.tree.map(lambda p, g: p - 0.1 * g, sharded, g)
    l1 = jax.jit(loss)(stepped, x)
    assert np.isfinite(l0) and l1 < l0

    got1 = gpipe_apply(make_pp_mesh(1), params, _layer, x, 2)
    np.testing.assert_allclose(
        np.asarray(got1), np.asarray(_sequential(params, x)), atol=1e-6
    )
