"""launch.py: rendezvous env wiring and requeue behavior (the submitit/
SLURM-launcher equivalent, `/root/reference/config/hydra/launcher/*.yaml`)."""

import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def run_launch(*args, env_extra=None):
    env = dict(os.environ)
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, str(REPO / "launch.py"), *args],
        capture_output=True, text=True, env=env, timeout=60,
    )


class TestLaunch:
    def test_env_wiring(self, tmp_path):
        probe = tmp_path / "probe.py"
        probe.write_text(
            "import os\n"
            "print(os.environ['DALLE_TPU_COORDINATOR'],\n"
            "      os.environ['DALLE_TPU_NUM_PROCS'],\n"
            "      os.environ['DALLE_TPU_PROC_ID'])\n"
        )
        r = run_launch(
            "--coordinator", "10.0.0.1:1234", "--num-hosts", "4",
            "--host-id", "2", "--", str(probe),
        )
        assert r.returncode == 0, r.stderr
        assert r.stdout.strip() == "10.0.0.1:1234 4 2"

    def test_slurm_defaults(self):
        sys.path.insert(0, str(REPO))
        from launch import slurm_defaults

        old = {k: os.environ.pop(k, None)
               for k in ("SLURM_PROCID", "SLURM_NTASKS", "SLURM_NODELIST")}
        try:
            assert slurm_defaults() == {}
            os.environ.update(
                SLURM_PROCID="3", SLURM_NTASKS="4", SLURM_NODELIST="node[1-4]"
            )
            d = slurm_defaults()
            assert d["host_id"] == 3 and d["num_hosts"] == 4
            assert d["coordinator"].endswith(":12345")
        finally:
            for k, v in old.items():
                if v is not None:
                    os.environ[k] = v
                else:
                    os.environ.pop(k, None)

    def test_requeue_then_success(self, tmp_path):
        """First run exits 143 (preemption-style); requeue reruns it and the
        second run succeeds — the submitit-requeue story with --resume."""
        marker = tmp_path / "marker"
        script = tmp_path / "flaky.py"
        script.write_text(
            "import pathlib, sys\n"
            f"m = pathlib.Path({str(marker)!r})\n"
            "if not m.exists():\n"
            "    m.write_text('x'); sys.exit(143)\n"
            "print('recovered'); sys.exit(0)\n"
        )
        r = run_launch("--requeue", "--", str(script))
        assert r.returncode == 0
        assert "recovered" in r.stdout
        assert "requeue 1/" in r.stderr

    def test_no_requeue_on_real_failure(self, tmp_path):
        script = tmp_path / "bad.py"
        script.write_text("import sys; sys.exit(7)\n")
        r = run_launch("--requeue", "--", str(script))
        assert r.returncode == 7

    def test_single_host_noop_init(self):
        """initialize_distributed() with no rendezvous info must be a no-op
        (trainers call it unconditionally)."""
        from dalle_pytorch_tpu.parallel import initialize_distributed

        for k in ("DALLE_TPU_COORDINATOR", "DALLE_TPU_NUM_PROCS",
                  "DALLE_TPU_PROC_ID", "DALLE_TPU_DIST"):
            os.environ.pop(k, None)
        initialize_distributed()  # must not raise or hang


class TestLaunchRound3Review:
    def test_pod_auto_dist_env(self, tmp_path):
        """No rendezvous flags at all -> the child must see DALLE_TPU_DIST=1
        (TPU-pod auto-init path advertised in the README)."""
        probe = tmp_path / "probe.py"
        probe.write_text("import os; print(os.environ.get('DALLE_TPU_DIST'))\n")
        env_clear = {k: "" for k in ("SLURM_PROCID", "SLURM_NTASKS")}
        r = run_launch("--", str(probe), env_extra=env_clear)
        assert r.returncode == 0, r.stderr
        assert r.stdout.strip() == "1"

    def test_slurm_hostname_parsing(self):
        import sys as _sys
        _sys.path.insert(0, str(REPO))
        from launch import first_slurm_host

        assert first_slurm_host("node[1-4]") == "node1"
        assert first_slurm_host("gpu-node-[01-04]") == "gpu-node-01"
        assert first_slurm_host("gpu-node-[01,07]") == "gpu-node-01"
        assert first_slurm_host("hosta,hostb") == "hosta"
        assert first_slurm_host("single-host") == "single-host"
        assert first_slurm_host("") == ""

    def test_sigterm_forwarded_and_requeued(self, tmp_path):
        """Preemption signals the launcher, not (only) the child: the
        launcher must survive, forward the signal, and requeue."""
        import signal
        import subprocess
        import sys
        import time

        marker = tmp_path / "marker"
        script = tmp_path / "slow.py"
        script.write_text(
            "import pathlib, time, sys\n"
            f"m = pathlib.Path({str(marker)!r})\n"
            "if m.exists():\n"
            "    print('recovered', flush=True); sys.exit(0)\n"
            "m.write_text('x')\n"
            "print('ready', flush=True)\n"
            "time.sleep(30)\n"
        )
        proc = subprocess.Popen(
            [sys.executable, str(REPO / "launch.py"), "--requeue", "--",
             str(script)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        # wait for the child to report ready, then preempt the LAUNCHER
        deadline = time.time() + 30
        while time.time() < deadline and not marker.exists():
            time.sleep(0.1)
        assert marker.exists()
        time.sleep(0.5)
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=60)
        assert proc.returncode == 0, (out, err)
        assert "recovered" in out
        assert "requeue 1/" in err
