"""Shardlint (TL017–TL021): rule corpus, summary resolution, gates.

Layout mirrors tests/test_analysis.py — every rule has a positive
fixture that must fire EXACTLY (count and code) and a negative fixture
that must stay silent; shardctx's resolution machinery (mesh factories,
spec comparison, program summaries, wrapper propagation, the hot
frontier) is unit-tested directly on source strings; and the two
acceptance gates at the bottom pin the PR's contract: the shipped
package is clean under TL017–TL021, and unpinning a single
out_shardings= in serving/sharded.py is caught by TL017.
"""

import textwrap
from pathlib import Path

import pytest

from dalle_pytorch_tpu.analysis.baseline import load_baseline, write_baseline
from dalle_pytorch_tpu.analysis.core import FileContext
from dalle_pytorch_tpu.analysis.lint import (
    PACKAGE_DIR,
    changed_python_files,
    lint_paths,
    main,
)
from dalle_pytorch_tpu.analysis.shardctx import (
    SpecRef,
    literal_mesh_axes,
    mesh_axis_bindings,
    package_summaries,
    shard_index,
    spec_ref_of,
    specs_differ,
)

FIXTURES = Path(__file__).resolve().parent / "lint_fixtures"
SHARD_CODES = {"TL017", "TL018", "TL019", "TL020", "TL021"}


def codes(result):
    return [f.rule for f in result.findings]


def ctx_of(source, name="mod.py"):
    src = textwrap.dedent(source)
    return FileContext(Path(name), name, src, stable_path=name)


def index_of(source):
    return shard_index(ctx_of(source))


def parse_expr(source):
    import ast

    return ast.parse(textwrap.dedent(source), mode="eval").body


# -------------------------------------------------------------- rule corpus


class TestShardRuleCorpus:
    """Positive fixtures fire exactly (count AND code — a fixture that
    trips a second rule is a fixture bug); negatives stay silent."""

    @pytest.mark.parametrize(
        "fixture, code, expected",
        [
            ("tl017_pos.py", "TL017", 3),
            ("tl018_pos.py", "TL018", 3),
            ("tl019_pos.py", "TL019", 3),
            ("tl020_pos.py", "TL020", 3),
            ("tl021_pos.py", "TL021", 3),
        ],
    )
    def test_positive_fixture_caught(self, fixture, code, expected):
        result = lint_paths([FIXTURES / fixture])
        got = codes(result)
        assert got.count(code) == expected, got
        assert all(c == code for c in got), got

    @pytest.mark.parametrize(
        "fixture",
        [
            "tl017_neg.py",
            "tl018_neg.py",
            "tl019_neg.py",
            "tl020_neg.py",
            "tl021_neg.py",
        ],
    )
    def test_negative_fixture_clean(self, fixture):
        result = lint_paths([FIXTURES / fixture])
        assert result.clean, "\n".join(f.render() for f in result.findings)

    def test_shard_rules_are_error_tier(self):
        """All five are zero-compile-contract violations: error tier, so
        `rc & 1` CI gates block on them."""
        for fixture in sorted(FIXTURES.glob("tl01[789]_pos.py")) + sorted(
            FIXTURES.glob("tl02[01]_pos.py")
        ):
            result = lint_paths([fixture])
            assert result.findings and all(
                f.severity == "error" for f in result.findings
            ), fixture.name


# ------------------------------------------------------- spec resolution


class TestSpecResolution:
    def test_literal_spec_trailing_nones_normalized(self):
        a = spec_ref_of(parse_expr('P("tp", None)'))
        b = spec_ref_of(parse_expr('P("tp")'))
        assert a == b == SpecRef("literal", ("tp",))

    def test_named_sharding_unwraps_to_spec(self):
        ref = spec_ref_of(parse_expr('NamedSharding(mesh, P(None, "tp"))'))
        assert ref == SpecRef("literal", (None, "tp"))
        assert ref.named_axes() == {"tp"}
        assert not ref.replicated

    def test_axis_tuple_entries(self):
        ref = spec_ref_of(parse_expr('P(("dp", "fsdp"), "tp")'))
        assert ref.named_axes() == {"dp", "fsdp", "tp"}

    def test_replicated_and_symbol_refs(self):
        assert spec_ref_of(parse_expr("P()")).replicated
        assert spec_ref_of(parse_expr("self._replicated_sharding()")).replicated
        sym = spec_ref_of(parse_expr("self._state_shardings"))
        assert sym == SpecRef("symbol", symbol="self._state_shardings")

    def test_unresolvable_specs(self):
        assert spec_ref_of(parse_expr("P(axis)")) is None
        assert spec_ref_of(parse_expr("make_spec()")) is None
        assert spec_ref_of(None) is None

    def test_specs_differ_is_three_valued(self):
        tp = SpecRef("literal", ("tp",))
        dp = SpecRef("literal", ("dp",))
        sym = SpecRef("symbol", symbol="s")
        other = SpecRef("symbol", symbol="t")
        assert specs_differ(tp, dp) is True
        assert specs_differ(tp, SpecRef("literal", ("tp",))) is False
        assert specs_differ(sym, SpecRef("symbol", symbol="s")) is False
        # different symbols may alias the same shardings: UNKNOWN
        assert specs_differ(sym, other) is None
        assert specs_differ(tp, sym) is None
        assert specs_differ(None, tp) is None


class TestMeshResolution:
    def test_literal_mesh_and_factories(self):
        assert literal_mesh_axes(
            parse_expr('Mesh(devs, ("dp", "tp"))')
        ) == {"dp", "tp"}
        assert literal_mesh_axes(
            parse_expr('Mesh(devs, axis_names=("pp",))')
        ) == {"pp"}
        assert literal_mesh_axes(parse_expr("make_mesh()")) == {
            "dp", "fsdp", "tp", "sp",
        }
        assert literal_mesh_axes(parse_expr("make_pp_mesh(4)")) == {"pp"}
        assert literal_mesh_axes(parse_expr("Mesh(devs, names)")) is None
        assert literal_mesh_axes(parse_expr("weird_factory()")) is None

    def test_bindings_cover_attributes_and_rebinds(self):
        ctx = ctx_of(
            """
            mesh = make_pp_mesh(2)
            mesh = Mesh(devs, ("dp",))

            class S:
                def __init__(self):
                    self.mesh = build_serving_mesh(1, 1)
            """
        )
        axes = mesh_axis_bindings(ctx.tree)
        # rebinding unions rather than guessing which bind is live
        assert axes["mesh"] == {"pp", "dp"}
        assert axes["self.mesh"] == {"dp", "fsdp", "tp", "sp"}


# ---------------------------------------------------- program summaries


class TestProgramSummaries:
    def test_registered_ladder_program(self):
        idx = index_of(
            """
            import jax

            class E:
                def _op(self, s):
                    fn = self._sharded_program(
                        "chunk",
                        lambda: jax.jit(
                            self._builder(),
                            donate_argnums=(1,),
                            out_shardings=self._state_shardings,
                        ),
                    )
                    return fn(self.variables, s)
            """
        )
        prog = idx.by_name["chunk"]
        assert prog.registered and prog.kind == "jit"
        assert prog.donated == (1,)
        assert prog.has_out and not prog.has_in
        # the fixed-point pin resolves symbolically
        cands = prog.out_spec_candidates()
        assert [c.symbol for c in cands] == ["self._state_shardings"]

    def test_unpinned_program_has_no_out(self):
        idx = index_of(
            """
            import jax
            step = jax.jit(impl, donate_argnums=(0,))
            """
        )
        prog = idx.by_name["step"]
        assert not prog.has_out
        assert prog.out_spec_candidates() is None

    def test_in_spec_positions_and_broadcast(self):
        idx = index_of(
            """
            import jax
            from jax.sharding import PartitionSpec as P

            a = jax.jit(f, in_shardings=(P("dp"), P()), out_shardings=P())
            b = jax.jit(g, in_shardings=P("tp"), out_shardings=P("tp"))
            """
        )
        a, b = idx.by_name["a"], idx.by_name["b"]
        assert a.in_spec_at(0) == SpecRef("literal", ("dp",))
        assert a.in_spec_at(1).replicated
        assert a.in_spec_at(7) is None  # out of range, not broadcast
        # a single (non-tuple) expression broadcasts over every position
        assert b.in_spec_at(0) == b.in_spec_at(3) == SpecRef(
            "literal", ("tp",)
        )

    def test_shard_map_specs_and_mesh_identity(self):
        idx = index_of(
            """
            from jax.sharding import PartitionSpec as P
            k = shard_map(f, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"))
            """
        )
        prog = idx.by_name["k"]
        assert prog.kind == "shard_map" and prog.mesh == "mesh"
        assert prog.in_spec_at(0) == SpecRef("literal", ("dp",))

    def test_wrapper_propagation_is_positional_identity_only(self):
        idx = index_of(
            """
            import jax
            from jax.sharding import PartitionSpec as P

            prog = jax.jit(impl, in_shardings=(P("dp"),), out_shardings=P("dp"))

            def run(x):
                return prog(x)

            def shuffled(x, y):
                return prog(y, x)
            """
        )
        # the identity wrapper exports prog's summary under its own name
        assert idx.by_name["run"] is idx.by_name["prog"]
        # reordering args would shift spec positions: stays opaque
        assert "shuffled" not in idx.by_name

    def test_first_binding_wins_on_name_collisions(self):
        idx = index_of(
            """
            import jax
            from jax.sharding import PartitionSpec as P
            p = jax.jit(f, in_shardings=(P("dp"),), out_shardings=P("dp"))
            p = jax.jit(g, in_shardings=(P("tp"),), out_shardings=P("tp"))
            """
        )
        assert idx.by_name["p"].in_spec_at(0) == SpecRef("literal", ("dp",))
        assert len(idx.programs) == 2

    def test_package_summaries_cross_file_union(self):
        a = ctx_of(
            """
            import jax
            from jax.sharding import PartitionSpec as P
            run = jax.jit(f, in_shardings=(P("dp"),), out_shardings=P("dp"))
            """,
            name="a.py",
        )
        b = ctx_of("x = 1\n", name="b.py")
        union = package_summaries([a, b])
        summary, owner = union["run"]
        assert summary.in_spec_at(0) == SpecRef("literal", ("dp",))
        assert owner is a


class TestHotFrontier:
    SRC = """
        # tracelint: hotloop
        def hot():
            helper()
            shared()

        def helper():
            return 1

        def cold():
            shared()

        def shared():
            return 2
        """

    def test_one_hop_requires_every_call_site_hot(self):
        idx = index_of(self.SRC)
        names = {f.name for f in idx.hot}
        # helper is called ONLY from hot() -> hotloop-reachable;
        # shared() is also called from cold() -> stays out
        assert names == {"hot", "helper"}


# ------------------------------------------- suppression + baseline drift


class TestSuppressionAndBaseline:
    SRC = (
        "import jax\n"
        "step = jax.jit(  # tracelint: disable=TL017 -- output layout is "
        "probed once at startup\n"
        "    impl,\n"
        "    donate_argnums=(0,),\n"
        "    in_shardings=(state_sh,),\n"
        ")\n"
    )

    def test_suppression_with_reason_is_honored(self, tmp_path):
        f = tmp_path / "sup.py"
        f.write_text(self.SRC)
        result = lint_paths([f])
        assert result.clean
        assert [s.reason for _, s in result.suppressed] == [
            "output layout is probed once at startup"
        ]

    def test_baseline_survives_line_drift(self, tmp_path):
        """Grandfathered shardlint findings stay grandfathered when code
        moves above them (fingerprints key on content, not lines)."""
        f = tmp_path / "drift.py"
        f.write_text((FIXTURES / "tl018_pos.py").read_text())
        bl = tmp_path / "bl.json"
        first = lint_paths([f])
        assert codes(first) == ["TL018"] * 3
        write_baseline(bl, first.findings)

        f.write_text("'''moved'''\nX = 1\n\n" + f.read_text())
        again = lint_paths([f], baseline_fingerprints=load_baseline(bl))
        assert again.clean
        assert len(again.baselined) == 3


# ------------------------------------------------------------- the gates


def test_package_shardlint_gate():
    """Acceptance criterion: the shipped package has ZERO TL017–TL021
    findings (the broader all-rules gate lives in test_analysis.py)."""
    result = lint_paths([PACKAGE_DIR], select=set(SHARD_CODES))
    assert result.clean, "package findings:\n" + "\n".join(
        f.render() for f in result.findings
    )


def test_seeded_mutation_unpinned_ladder_is_caught(tmp_path):
    """Regression for the PR's seeded mutation: deleting a single
    `out_shardings=self._state_shardings` pin from serving/sharded.py
    must produce a TL017 finding (and the unmutated file stays clean)."""
    src = (PACKAGE_DIR / "serving" / "sharded.py").read_text()
    pin = "out_shardings=self._state_shardings,\n"
    assert pin in src, "sharded.py lost its ladder-pin idiom"

    pristine = tmp_path / "sharded_pristine.py"
    pristine.write_text(src)
    assert lint_paths([pristine], select={"TL017"}).clean

    mutated = tmp_path / "sharded_mutated.py"
    mutated.write_text(src.replace(pin, "", 1))
    result = lint_paths([mutated], select={"TL017"})
    assert codes(result) == ["TL017"], codes(result)


# ---------------------------------------------------------------- --watch


def test_tl019_stays_correct_through_watch_cache(tmp_path):
    """TL019 is package-scope: its findings are never finding-cached, so
    an edit that introduces a cross-file sharding mismatch must surface
    on the NEXT incremental run even though the unchanged producer file
    reuses its cached AST/ShardIndex."""
    from dalle_pytorch_tpu.analysis.watch import LintCache

    producer = tmp_path / "programs.py"
    producer.write_text(textwrap.dedent(
        """
        import jax
        from jax.sharding import PartitionSpec as P
        run_tp = jax.jit(
            impl, in_shardings=(P(None, "tp"),), out_shardings=P(None, "tp")
        )
        """
    ))
    consumer = tmp_path / "loop.py"
    ok = textwrap.dedent(
        """
        import jax
        from jax.sharding import PartitionSpec as P
        from programs import run_tp

        # tracelint: hotloop
        def step(batch):
            x = jax.device_put(batch, P(None, "tp"))
            return run_tp(x)
        """
    )
    consumer.write_text(ok)

    cache = LintCache()
    first = lint_paths([tmp_path], cache=cache)
    assert first.clean

    consumer.write_text(ok.replace('P(None, "tp"))', 'P("dp"))', 1))
    second = lint_paths([tmp_path], cache=cache)
    assert codes(second) == ["TL019"]
    # only the edited file re-parsed; the producer's index came warm
    assert second.cache["reparsed"] == 1

    consumer.write_text(ok)
    third = lint_paths([tmp_path], cache=cache)
    assert third.clean


# ------------------------------------------------------------- --changed


class TestChangedMode:
    def _repo(self, tmp_path, monkeypatch):
        import subprocess

        monkeypatch.chdir(tmp_path)
        for cmd in (
            ["git", "init", "-q"],
            ["git", "config", "user.email", "t@t"],
            ["git", "config", "user.name", "t"],
        ):
            subprocess.run(cmd, check=True, capture_output=True)
        (tmp_path / "clean.py").write_text("X = 1\n")
        subprocess.run(
            ["git", "add", "-A"], check=True, capture_output=True
        )
        subprocess.run(
            ["git", "commit", "-qm", "seed"], check=True, capture_output=True
        )
        return tmp_path

    def test_changed_lints_only_touched_files(
        self, tmp_path, monkeypatch, capsys
    ):
        repo = self._repo(tmp_path, monkeypatch)
        (repo / "clean.py").write_text("X = 2\n")  # modified, stays clean
        (repo / "fresh.py").write_text("import ipdb\n")  # untracked TL006
        assert changed_python_files("HEAD") == sorted(
            [repo / "clean.py", repo / "fresh.py"]
        )
        assert main(["--changed"]) == 1
        out = capsys.readouterr().out
        assert "fresh.py" in out and "TL006" in out

    def test_changed_with_nothing_touched_exits_zero(
        self, tmp_path, monkeypatch, capsys
    ):
        self._repo(tmp_path, monkeypatch)
        assert main(["--changed"]) == 0
        assert "no python files changed" in capsys.readouterr().out

    def test_changed_rejects_bad_ref_and_explicit_paths(
        self, tmp_path, monkeypatch, capsys
    ):
        repo = self._repo(tmp_path, monkeypatch)
        assert main(["--changed", "no-such-ref"]) == 2
        assert "no-such-ref" in capsys.readouterr().err
        assert main([str(repo / "clean.py"), "--changed"]) == 2
        assert "don't compose" in capsys.readouterr().err
