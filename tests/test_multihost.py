"""REAL multi-process distributed training test (CPU, 2 processes).

Round-3 addition: everything multi-host used to be validated only inside
one process (virtual-device meshes). This launches TWO actual processes
through `launch.py`, rendezvouses them with `jax.distributed` (Gloo), and
runs the sharded DALLE train step across both — catching the class of bug
that only appears with process_count() > 1 (e.g. the device_put-of-local-
shards bug fixed by `put_host_batch`, parallel/mesh.py).
"""

import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

WORKER = """
import jax
jax.config.update("jax_platforms", "cpu")
from dalle_pytorch_tpu.parallel import initialize_distributed
initialize_distributed()
import numpy as np
import jax.numpy as jnp
from dalle_pytorch_tpu.data.loader import host_shard_order
from dalle_pytorch_tpu.parallel import (
    make_mesh, batch_sharding, state_shardings, put_host_batch,
)
from dalle_pytorch_tpu.models.dalle import DALLE
from dalle_pytorch_tpu.training import (
    TrainState, make_optimizer, make_dalle_train_step,
)

rank, nproc = jax.process_index(), jax.process_count()
assert nproc == 2, f"expected 2 processes, got {nproc}"
assert jax.device_count() == 2, jax.device_count()

# disjoint host data shards
order = host_shard_order(np.arange(8), (rank, nproc))
assert len(order) == 4 and set(order) <= set(range(8))

mesh = make_mesh(dp=-1)  # dp=2 across the two processes
model = DALLE(dim=32, depth=1, heads=2, dim_head=16, num_image_tokens=32,
              image_fmap_size=4, num_text_tokens=64, text_seq_len=8)
t0 = jnp.zeros((1, 8), jnp.int32); i0 = jnp.zeros((1, 16), jnp.int32)
params = model.init(jax.random.PRNGKey(0), t0, i0)["params"]
state = TrainState.create(apply_fn=model.apply, params=params,
                          tx=make_optimizer(1e-3))
state_sh = state_shardings(state, mesh)
txt_sh = batch_sharding(mesh, extra_dims=1)
state = jax.device_put(state, state_sh)
step = jax.jit(
    make_dalle_train_step(model),
    in_shardings=(state_sh, {"text": txt_sh, "image_tokens": txt_sh}, None),
    out_shardings=(state_sh, None),
    donate_argnums=0,
)
# each process contributes ITS OWN local rows; put_host_batch assembles
# the global [4, ...] batch
local_text = np.full((2, 8), rank + 1, np.int32)
local_tok = np.full((2, 16), rank, np.int32)
batch = {"text": put_host_batch(local_text, txt_sh),
         "image_tokens": put_host_batch(local_tok, txt_sh)}
assert batch["text"].shape == (4, 8), batch["text"].shape
for _ in range(2):
    state, metrics = step(state, batch, jax.random.PRNGKey(1))
loss = float(metrics["loss"])
assert np.isfinite(loss)

# cross-host FSDP sharding + collective checkpoint gather: parameters are
# sharded ACROSS the two processes, so exporting must allgather first
from dalle_pytorch_tpu.parallel import gather_to_host
fsdp_mesh = make_mesh(dp=1, fsdp=2)
params_f = jax.device_put(state.params, state_shardings(state, fsdp_mesh).params)
gathered = gather_to_host(params_f)
for a, b in zip(jax.tree_util.tree_leaves(gathered),
                jax.tree_util.tree_leaves(gather_to_host(state.params))):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0)

# Orbax save/restore of the SHARDED state across both processes (pod
# preemption-resume): every process participates in save and restore
import os as _os
from dalle_pytorch_tpu.training.checkpoint import CheckpointManager
ckpt_dir = _os.environ["MULTIHOST_CKPT_DIR"]
mgr = CheckpointManager(ckpt_dir, keep_n=1)
mgr.save(7, state, metadata={"probe": rank == rank})
mgr.wait()
restored, meta, step_no = mgr.restore(state)
assert step_no == 7 and restored is not None
for a, b in zip(jax.tree_util.tree_leaves(gather_to_host(restored.params)),
                jax.tree_util.tree_leaves(gather_to_host(state.params))):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0)
mgr.close()
print(f"MULTIHOST_OK rank={rank} loss={loss:.6f}", flush=True)
"""


@pytest.mark.slow
class TestTwoProcessTraining:
    def test_sharded_step_across_two_processes(self, tmp_path):
        import socket

        worker = tmp_path / "worker.py"
        worker.write_text(WORKER)
        # free rendezvous port: a hardcoded one collides with a leaked
        # worker from a previous failed run
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs = []
        try:
            for rank in range(2):
                env = dict(os.environ)
                env["PYTHONPATH"] = str(REPO)
                env["MULTIHOST_CKPT_DIR"] = str(tmp_path / "ckpt")
                env.pop("DALLE_TPU_DIST", None)
                # one device per process (conftest's 8-virtual-device
                # XLA_FLAGS would otherwise give a 16-device global mesh)
                env.pop("XLA_FLAGS", None)
                procs.append(
                    subprocess.Popen(
                        [
                            sys.executable, str(REPO / "launch.py"),
                            "--coordinator", f"127.0.0.1:{port}",
                            "--num-hosts", "2", "--host-id", str(rank),
                            "--", str(worker),
                        ],
                        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                        text=True, env=env,
                    )
                )
            outs = []
            for p in procs:
                out, err = p.communicate(timeout=240)
                assert p.returncode == 0, f"rank failed:\n{err[-2000:]}"
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=10)
        losses = set()
        for out in outs:
            line = [l for l in out.splitlines() if "MULTIHOST_OK" in l]
            assert line, out
            losses.add(line[0].split("loss=")[1])
        # gradient psum makes every process see the identical loss
        assert len(losses) == 1, f"losses diverged across hosts: {losses}"
