"""Native C++ BPE core (native/bpe.cpp via ctypes) — the framework's
replacement for the reference's youtokentome dependency
(`/root/reference/dalle_pytorch/tokenizer.py:232-266`).

Skipped when no C++ toolchain is present.
"""

import shutil

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None, reason="no C++ toolchain"
)

CORPUS = (
    "the quick brown fox jumps over the lazy dog\n"
    "a small red circle above a large blue square\n"
    "the small blue triangle next to the red circle\n"
    "large green square below the small yellow triangle\n"
) * 40


@pytest.fixture(scope="module")
def bpe():
    from dalle_pytorch_tpu.data.native_bpe import NativeBPE

    return NativeBPE.train(CORPUS, vocab_size=400)


class TestNativeBPE:
    def test_vocab_size_bounded(self, bpe):
        assert 258 < bpe.vocab_size <= 400

    @pytest.mark.parametrize(
        "text",
        [
            "the quick red fox",
            "unseen wörds häppen",  # utf-8 multi-byte
            "  leading and   multiple spaces ",
            "",
        ],
    )
    def test_roundtrip_exact(self, bpe, text):
        assert bpe.decode(bpe.encode(text)) == text

    def test_trained_word_compresses(self, bpe):
        assert len(bpe.encode("the")) == 1
        assert len(bpe.encode("circle")) <= 2

    def test_save_load_identical(self, bpe, tmp_path):
        from dalle_pytorch_tpu.data.native_bpe import NativeBPE

        path = tmp_path / "model.bpe"
        bpe.save(path)
        bpe2 = NativeBPE.load(path)
        assert bpe2.vocab_size == bpe.vocab_size
        text = "the lazy brown circle"
        assert bpe2.encode(text) == bpe.encode(text)

    def test_batch_encode_matches_single(self, bpe):
        texts = ["the quick brown fox", "a small red circle", "dog"]
        batch = bpe.encode_batch(texts, max_len=16)
        assert batch.shape == (3, 16) and batch.dtype == np.int32
        for row, t in zip(batch, texts):
            single = bpe.encode(t)
            assert list(row[: len(single)]) == single
            assert (row[len(single) :] == 0).all()

    def test_batch_overflow_raises_without_truncate(self, bpe):
        with pytest.raises(RuntimeError, match="too long"):
            bpe.encode_batch(["word " * 100], max_len=4, truncate=False)

    def test_batch_truncates(self, bpe):
        out = bpe.encode_batch(["word " * 100], max_len=4, truncate=True)
        assert (out[0] != 0).all()

    def test_threaded_batch_consistent(self, bpe):
        texts = [f"the quick fox number {i}" for i in range(64)]
        a = bpe.encode_batch(texts, max_len=24, n_threads=1)
        b = bpe.encode_batch(texts, max_len=24, n_threads=8)
        assert (a == b).all()


class TestNativeBPETokenizer:
    def test_tokenizer_contract(self, bpe, tmp_path):
        from dalle_pytorch_tpu.data.tokenizer import NativeBPETokenizer, get_tokenizer

        path = tmp_path / "model.bpe"
        bpe.save(path)
        tok = get_tokenizer(bpe_path=str(path), native=True)
        assert isinstance(tok, NativeBPETokenizer)
        arr = tok.tokenize(["the quick fox", "a red circle"], context_length=12)
        assert arr.shape == (2, 12) and arr.dtype == np.int32
        assert tok.decode(arr[0]) == "the quick fox"
        with pytest.raises(RuntimeError):
            tok.tokenize("fox " * 100, context_length=4)
        assert tok.tokenize("fox " * 100, 4, truncate_text=True).shape == (1, 4)

    def test_corrupt_model_rejected(self, tmp_path):
        from dalle_pytorch_tpu.data.native_bpe import NativeBPE

        bad = tmp_path / "bad.bpe"
        bad.write_text("NATIVEBPE v1\n2\n999999 -5\n3 4\n")
        with pytest.raises(FileNotFoundError):
            NativeBPE.load(bad)
