"""Observability subsystem: span tracer, Perfetto export, structured logs,
stage metrics + exemplars, debug endpoints, trace dump on drain.

The acceptance path (TestContinuousServingTraces) pins the tentpole
contract: a request served end-to-end through the `ContinuousBatcher`
yields ONE complete trace whose stages are exactly
queue → prefill → chunk* → harvest → respond, exported as valid Perfetto
`trace_event` JSON from /debug/traces, with the same stage durations
reflected in `dalle_serving_stage_seconds{stage=}` on /metrics — and the
whole instrumented path compiles nothing after warmup.

The zero-overhead contract is guarded by a counter, not timing: a
disabled tracer creates ZERO Span objects however much traffic flows
past it (`Tracer.spans_created`).
"""

import io
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from dalle_pytorch_tpu.obs import (
    NULL_TRACE,
    ProfilerBusy,
    ProfilerCapture,
    StructuredLog,
    Tracer,
)
from dalle_pytorch_tpu.serving.batcher import ContinuousBatcher, MicroBatcher
from dalle_pytorch_tpu.serving.server import ServingServer
from dalle_pytorch_tpu.training.metrics import Histogram, MetricsRegistry

from test_continuous import FakeContinuousEngine, IMG_SEQ, _build, spec
from test_serving_e2e import FakeServingEngine, _get, _post


#: the pinned stage vocabulary of a continuous-engine request trace
CONTINUOUS_STAGES = ("queue", "prefill", "chunk", "harvest", "respond")


# ----------------------------------------------------------------- tracer


class TestTracer:
    def test_span_tree_and_stage_seconds(self):
        tr = Tracer()
        t = tr.start_trace("request", rows=2)
        with t.span("queue"):
            time.sleep(0.01)
        for i in range(3):
            with t.span("chunk", chunk_index=i):
                time.sleep(0.002)
        t.finish("ok")
        assert t.complete()
        stages = t.stage_seconds()
        assert set(stages) == {"queue", "chunk"}
        assert stages["queue"] >= 0.01
        assert stages["chunk"] >= 0.006  # three chunk spans SUM
        # spans are parented on the root request span
        root = t.root
        assert root.name == "request" and root.args["outcome"] == "ok"
        assert all(
            s.parent_id == root.span_id for s in t.spans if s is not root
        )

    def test_cross_thread_begin_end(self):
        """The queue span begins on the submitting thread and ends on the
        worker — the explicit begin/end API the batcher relies on."""
        tr = Tracer()
        t = tr.start_trace()
        s = t.begin("queue")
        worker = threading.Thread(target=lambda: t.end(s, outcome="admitted"))
        worker.start()
        worker.join()
        assert s.closed and s.args["outcome"] == "admitted"

    def test_finish_closes_abandoned_spans(self):
        """Error paths abandon stage spans mid-flight; finish() must still
        produce a complete (exportable) trace."""
        tr = Tracer()
        t = tr.start_trace()
        t.begin("chunk")
        t.finish("error")
        assert t.complete()
        (chunk,) = [s for s in t.spans if s.name == "chunk"]
        assert chunk.args.get("abandoned") is True

    def test_ring_buffer_bounded(self):
        tr = Tracer(max_traces=4)
        for i in range(10):
            tr.start_trace("request", i=i).finish()
        recent = tr.recent()
        assert len(recent) == 4
        assert [t.root.args["i"] for t in recent] == [6, 7, 8, 9]

    def test_trace_ids_unique(self):
        tr = Tracer()
        ids = {tr.start_trace().trace_id for _ in range(64)}
        assert len(ids) == 64

    def test_disabled_tracer_is_null_and_allocation_free(self):
        tr = Tracer(enabled=False)
        t = tr.start_trace("request", rows=1)
        assert t is NULL_TRACE and not t
        with t.span("chunk", chunk_index=0):
            pass
        s = t.begin("queue")
        t.end(s)
        t.finish("ok")
        assert t.stage_seconds() == {}
        assert tr.spans_created == 0
        assert tr.trace_events() == {
            "traceEvents": [], "displayTimeUnit": "ms"
        }


class TestPerfettoExport:
    def test_export_round_trips_and_has_complete_events(self, tmp_path):
        tr = Tracer()
        t = tr.start_trace("request")
        with t.span("queue"):
            pass
        t.finish()
        payload = json.loads(json.dumps(tr.trace_events()))
        events = payload["traceEvents"]
        names = {e["name"] for e in events}
        assert {"thread_name", "request", "queue"} <= names
        for e in events:
            if e["ph"] == "X":
                assert e["ts"] >= 0 and e["dur"] >= 0
                assert e["args"]["trace_id"] == t.trace_id
                assert e["cat"] == "serving"
        # dump() writes the same payload as a loadable file
        out = tr.dump(tmp_path / "traces.json")
        assert json.loads(out.read_text())["traceEvents"]

    def test_concurrent_traces_get_distinct_tracks(self):
        tr = Tracer()
        t1, t2 = tr.start_trace(), tr.start_trace()
        t1.finish()
        t2.finish()
        events = tr.trace_events()["traceEvents"]
        tids = {
            e["args"]["trace_id"]: e["tid"] for e in events if e["ph"] == "X"
        }
        assert tids[t1.trace_id] != tids[t2.trace_id]


# --------------------------------------------------------- structured log


class TestStructuredLog:
    def test_request_line_schema(self):
        buf = io.StringIO()
        log = StructuredLog(stream=buf)
        log.request(
            trace_id="abc123", outcome="ok", status=200, latency_ms=41.07,
            stages={"queue": 0.0101, "chunk": 0.0302}, rows=2,
        )
        rec = json.loads(buf.getvalue())
        assert rec["event"] == "request"
        assert rec["trace_id"] == "abc123"
        assert rec["outcome"] == "ok" and rec["status"] == 200
        assert rec["latency_ms"] == 41.07
        assert rec["stages"] == {"queue": 10.1, "chunk": 30.2}  # ms
        assert rec["rows"] == 2 and rec["ts"] > 0

    def test_event_line_and_write_failure_is_silent(self):
        buf = io.StringIO()
        log = StructuredLog(stream=buf)
        log.event("warmup_done", compiled_shapes=[1, 4])
        assert json.loads(buf.getvalue())["event"] == "warmup_done"
        buf.close()
        log.event("after_close")  # must not raise into the serving path

    def test_file_mode_rotates_at_cap_keep_one(self, tmp_path):
        """--request_log_max_mb: crossing the cap renames the file to
        `<path>.1` (replacing any prior .1 — disk use bounded at ~2x)
        and keeps writing to a fresh file; no line is lost to rotation."""
        import os

        p = tmp_path / "req.jsonl"
        log = StructuredLog(path=str(p), max_mb=0.0005)  # ~512 bytes
        for i in range(40):
            log.event("tick", i=i, pad="x" * 40)
        assert (tmp_path / "req.jsonl.1").exists()
        assert os.path.getsize(p) < 2 * 512  # rotated, not runaway
        lines = []
        for f in (p.with_name("req.jsonl.1"), p):
            lines += [json.loads(l) for l in f.read_text().splitlines()]
        # keep-one drops older ROTATED files, never lines mid-stream:
        # the survivors are a contiguous tail ending at the last write
        seen = [r["i"] for r in lines if r["event"] == "tick"]
        assert seen == list(range(seen[0], 40))

    def test_file_mode_write_failure_is_silent(self, tmp_path):
        """A vanished log directory (node cleanup) must not raise into
        the request path — writes degrade to no-ops."""
        import shutil

        d = tmp_path / "logs"
        d.mkdir()
        log = StructuredLog(path=str(d / "req.jsonl"), max_mb=0.0005)
        log.event("before")
        shutil.rmtree(d)
        for i in range(200):  # enough to force a rotation attempt too
            log.event("tick", i=i, pad="y" * 40)  # must not raise


# ------------------------------------------------- stage metrics/exemplars


class TestExemplars:
    def test_histogram_exemplar_behind_flag(self):
        h = Histogram("lat", buckets=(0.1, 1.0))
        h.observe(0.05, exemplar="tr1")
        h.observe(0.5)  # no exemplar: the last exemplar-carrying obs wins
        plain = "\n".join(h.render())
        assert "trace_id" not in plain
        annotated = "\n".join(h.render(exemplars=True))
        assert 'lat_bucket{le="0.1"} 1 # {trace_id="tr1"} 0.05' in annotated
        # exactly one bucket line carries the exemplar
        assert annotated.count("trace_id") == 1

    def test_exemplar_lands_in_inf_bucket(self):
        h = Histogram("lat", buckets=(0.1,))
        h.observe(5.0, exemplar="big")
        out = "\n".join(h.render(exemplars=True))
        assert 'le="+Inf"} 1 # {trace_id="big"}' in out

    def test_counter_total_suffix_stripped_in_openmetrics(self):
        """OpenMetrics reserves `_total`: the counter FAMILY is named
        without it (samples keep it), else the OpenMetrics parser the
        exemplar exposition exists for rejects the whole scrape."""
        reg = MetricsRegistry()
        reg.counter("dalle_serving_requests_total", "reqs").inc()
        plain = reg.render()
        assert "# TYPE dalle_serving_requests_total counter" in plain
        om = reg.render(exemplars=True)
        assert "# TYPE dalle_serving_requests counter" in om
        assert "# TYPE dalle_serving_requests_total counter" not in om
        assert "dalle_serving_requests_total 1" in om  # sample keeps suffix

    def test_family_exemplars_with_labels(self):
        reg = MetricsRegistry()
        fam = reg.histogram_family(
            "stage_seconds", "per stage", label_name="stage",
            buckets=(0.1, 1.0),
        )
        fam.labels("chunk").observe(0.05, exemplar="tr9")
        out = reg.render(exemplars=True)
        assert (
            'stage_seconds_bucket{stage="chunk",le="0.1"} 1 '
            '# {trace_id="tr9"} 0.05'
        ) in out
        assert "trace_id" not in reg.render()


# --------------------------------------- batcher propagation (fake engine)


class TestBatcherTracing:
    def test_continuous_stages_recorded_through_fake_engine(self):
        tr = Tracer()
        eng = FakeContinuousEngine()
        b = ContinuousBatcher(eng, registry=eng.registry)
        traces = [tr.start_trace("request") for _ in range(3)]
        reqs = [
            b.submit([spec(i)], trace=traces[i]) for i in range(3)
        ]
        for r in reqs:
            r.future.result(timeout=10)
        b.shutdown()
        for t in traces:
            t.finish("ok")
            names = [s.name for s in t.spans if s is not t.root]
            assert names[0] == "queue" and names[1] == "prefill"
            assert names[-1] == "harvest"
            assert all(n == "chunk" for n in names[2:-1]) and "chunk" in names
            assert t.complete()
        # stage family observed for every batcher-side stage
        fam = eng.registry.get("dalle_serving_stage_seconds")
        stages = dict(fam.items())
        assert {"queue", "prefill", "chunk", "harvest"} <= set(stages)

    def test_micro_stages_recorded(self):
        from test_serving import FakeEngine

        tr = Tracer()
        eng = FakeEngine(max_batch=4)
        reg = MetricsRegistry()
        b = MicroBatcher(eng, max_delay_ms=5, registry=reg)
        t = tr.start_trace("request")
        req = b.submit([spec(3)], trace=t)
        req.future.result(timeout=10)
        b.shutdown()
        t.finish("ok")
        assert [s.name for s in t.spans if s is not t.root] == [
            "queue", "generate",
        ]
        fam = reg.get("dalle_serving_stage_seconds")
        assert {"queue", "generate"} <= set(dict(fam.items()))

    def test_disabled_tracer_zero_allocations_in_chunk_loop(self):
        """The tier-1 zero-overhead gate: a disabled tracer adds no
        per-token/per-chunk allocations — guarded by the spans_created
        counter, not timing."""
        tr = Tracer(enabled=False)
        eng = FakeContinuousEngine(chunk=2)  # several chunks per request
        b = ContinuousBatcher(eng, registry=eng.registry)
        reqs = [
            b.submit([spec(i)], trace=tr.start_trace("request"))
            for i in range(6)
        ]
        for r in reqs:
            r.future.result(timeout=10)
        b.shutdown()
        assert tr.spans_created == 0
        assert len(tr.recent()) == 0

    def test_timed_out_request_trace_still_completes(self):
        gate = threading.Event()
        eng = FakeContinuousEngine(block_event=gate)
        b = ContinuousBatcher(eng, registry=eng.registry)
        tr = Tracer()
        first = b.submit([spec(0)], trace=tr.start_trace())
        assert eng.chunk_entered.wait(10.0)
        doomed_trace = tr.start_trace()
        doomed = b.submit([spec(1)], timeout_s=0.05, trace=doomed_trace)
        time.sleep(0.2)
        gate.set()
        first.future.result(timeout=10)
        with pytest.raises(Exception):
            doomed.future.result(timeout=10)
        doomed_trace.finish("timeout")
        assert doomed_trace.complete()
        (queue,) = [s for s in doomed_trace.spans if s.name == "queue"]
        assert queue.args.get("outcome") == "timeout"
        b.shutdown()


# -------------------------------------------- acceptance: HTTP end-to-end


@pytest.fixture(scope="module")
def traced_server():
    from dalle_pytorch_tpu.data.tokenizer import ByteTokenizer

    _, cont = _build(max_batch=4, chunk_tokens=4, prefill_batch=2)
    cont.tokenizer = ByteTokenizer()
    cont.warmup()
    log_buf = io.StringIO()
    server = ServingServer(
        cont, port=0, request_timeout_s=60,
        tracer=Tracer(max_traces=64),
        log=StructuredLog(stream=log_buf),
    ).start()
    try:
        yield server, log_buf
    finally:
        server.shutdown()


def _events_by_trace(payload):
    by_trace = {}
    for e in payload["traceEvents"]:
        if e["ph"] != "X":
            continue
        by_trace.setdefault(e["args"]["trace_id"], []).append(e)
    return by_trace


class TestContinuousServingTraces:
    def test_stage_order_and_metrics_agree_single_request(self, traced_server):
        """One request end-to-end: span stages pinned, Perfetto export
        valid, and stage durations consistent between the trace and the
        `dalle_serving_stage_seconds{stage=}` family."""
        server, _ = traced_server
        fam = server.registry.get("dalle_serving_stage_seconds")
        before = {
            label: (child.sum, child.count) for label, child in fam.items()
        }
        status, payload = _post(
            server.port, {"prompt": "red circle", "seed": 5}
        )
        assert status == 200 and payload["trace_id"]
        trace = next(
            t for t in server.tracer.recent()
            if t.trace_id == payload["trace_id"]
        )
        assert trace.complete() and trace.outcome == "ok"
        names = [s.name for s in trace.spans if s is not trace.root]
        assert names[0] == "queue"
        assert names[1] == "prefill"
        assert names[-1] == "respond"
        assert names[-2] == "harvest"
        chunks = names[2:-2]
        assert chunks and all(n == "chunk" for n in chunks)
        assert len(chunks) == -(-IMG_SEQ // 4)  # ceil(image_seq/chunk_tokens)
        # chunk spans carry engine dispatch metadata
        chunk_spans = [s for s in trace.spans if s.name == "chunk"]
        assert all("chunk_index" in s.args for s in chunk_spans)
        (pf,) = [s for s in trace.spans if s.name == "prefill"]
        assert pf.args["wave_rows"] == 1 and pf.args["dispatches"] == 1

        # the same durations land in the stage family (deltas over this
        # request; generous tolerance — the two are measured at slightly
        # different code points)
        stages = trace.stage_seconds()
        for name in CONTINUOUS_STAGES:
            child = fam.labels(name)
            s0, c0 = before.get(name, (0.0, 0))
            assert child.count > c0, f"stage {name} never observed"
            np.testing.assert_allclose(
                child.sum - s0, stages[name], rtol=0.5, atol=0.25,
                err_msg=f"stage {name}: /metrics and trace disagree",
            )

    def test_parallel_requests_yield_complete_disjoint_traces(
        self, traced_server
    ):
        """N concurrent HTTP requests → N complete, non-interleaved span
        trees, all exported as valid Perfetto JSON from /debug/traces."""
        server, _ = traced_server
        n = 4
        results = {}

        def client(i):
            results[i] = _post(
                server.port,
                {"prompt": f"prompt number {i}", "seed": 100 + i},
            )

        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        ids = []
        for i in range(n):
            status, payload = results[i]
            assert status == 200
            ids.append(payload["trace_id"])
        assert len(set(ids)) == n

        status, body = _get(server.port, "/debug/traces")
        assert status == 200
        by_trace = _events_by_trace(json.loads(body))
        recent = {t.trace_id: t for t in server.tracer.recent()}
        for tid in ids:
            trace = recent[tid]
            assert trace.complete(), f"trace {tid} has unclosed spans"
            events = sorted(by_trace[tid], key=lambda e: e["ts"])
            stage_events = [
                e["name"] for e in events if e["name"] != "request"
            ]
            # stage order by start time: queue → prefill → chunk* →
            # harvest* → respond (a multi-wave boundary may harvest twice)
            assert stage_events[0] == "queue"
            assert stage_events[1] == "prefill"
            assert stage_events[-1] == "respond"
            core = stage_events[2:-1]
            assert set(core) == {"chunk", "harvest"}
            assert "chunk" in core and core[-1] == "harvest"
            first_harvest = core.index("harvest")
            assert all(s == "chunk" for s in core[:first_harvest])
            # non-interleaved: every event of this tid belongs to this
            # request's span set, 1:1
            assert len(events) == len(trace.spans)

    def test_request_log_line_emitted(self, traced_server):
        server, log_buf = traced_server
        status, payload = _post(
            server.port, {"prompt": "logged", "seed": 9}
        )
        assert status == 200
        lines = [
            json.loads(line) for line in log_buf.getvalue().splitlines()
        ]
        mine = [
            r for r in lines
            if r["event"] == "request"
            and r["trace_id"] == payload["trace_id"]
        ]
        assert len(mine) == 1
        rec = mine[0]
        assert rec["outcome"] == "ok" and rec["status"] == 200
        assert rec["latency_ms"] > 0 and rec["rows"] == 1
        assert set(CONTINUOUS_STAGES) <= set(rec["stages"])

    def test_metrics_exemplars_carry_trace_id(self, traced_server):
        server, _ = traced_server
        status, payload = _post(
            server.port, {"prompt": "exemplar", "seed": 13}
        )
        assert status == 200
        _, plain = _get(server.port, "/metrics")
        assert "trace_id" not in plain and "# EOF" not in plain
        _, annotated = _get(server.port, "/metrics?exemplars=1")
        assert 'dalle_serving_stage_seconds_bucket{stage="' in annotated
        assert '# {trace_id="' in annotated
        # OpenMetrics flavor ends with the mandatory EOF terminator
        assert annotated.rstrip().endswith("# EOF")

    def test_traced_serving_compiles_nothing_after_warmup(self, traced_server):
        """The instrumentation itself must not break the fixed-shape
        discipline: a fully traced request on a warm server is
        zero-compile (compile_guard-pinned)."""
        from dalle_pytorch_tpu.utils.compile_guard import assert_no_recompiles

        server, _ = traced_server
        _post(server.port, {"prompt": "warm path", "seed": 21})
        with assert_no_recompiles():
            status, payload = _post(
                server.port, {"prompt": "steady state", "seed": 22}
            )
        assert status == 200 and payload["trace_id"]


# ------------------------------------------------------- debug endpoints


class TestDebugEndpoints:
    def test_trace_dump_written_on_drain(self, tmp_path):
        """`serve.py --trace-dump PATH` surface: the ring buffer lands on
        disk as loadable Perfetto JSON when the server drains."""
        dump = tmp_path / "traces" / "dump.json"
        server = ServingServer(
            FakeServingEngine(), port=0, max_delay_ms=5,
            trace_dump_path=str(dump),
        ).start()
        _post(server.port, {"prompt": "dump me"})
        server.shutdown()  # drain, then dump
        payload = json.loads(dump.read_text())
        names = {
            e["name"] for e in payload["traceEvents"] if e["ph"] == "X"
        }
        assert {"request", "queue", "generate", "respond"} <= names
        server.shutdown()  # second shutdown must not re-dump or raise

    def test_profile_endpoint_wiring(self, tmp_path):
        """HTTP contract of /debug/profile against a stubbed capture
        backend (the guard-rail logic — single-flight, root gate, bounds
        — is the REAL ProfilerCapture; only the jax.profiler calls are
        stubbed: a first real capture pays O(10 s) of one-time profiler
        init in a compile-heavy process, which belongs in the slow
        tier — see test_profile_capture_real)."""

        class StubProfiler(ProfilerCapture):
            process_index = 0

            def _process_index(self):
                return self.process_index

            def _start(self, trace_dir):
                (trace_dir / "stub.trace").write_text("x")

            def _stop(self):
                pass

        profiler = StubProfiler(out_dir=str(tmp_path / "prof"))
        server = ServingServer(
            FakeServingEngine(), port=0, max_delay_ms=5, profiler=profiler,
        ).start()

        def post_profile(q, timeout=10):
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/debug/profile?{q}",
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, json.loads(resp.read())

        try:
            status, payload = post_profile("seconds=0.05")
            assert status == 200 and payload["seconds"] == 0.05
            import pathlib

            trace_dir = pathlib.Path(payload["trace_dir"])
            assert trace_dir.is_dir()
            assert (trace_dir / "stub.trace").exists()

            # single-flight: a capture in progress rejects the next one
            assert profiler._lock.acquire(blocking=False)
            try:
                with pytest.raises(urllib.error.HTTPError) as e:
                    post_profile("seconds=1")
                assert e.value.code == 409
            finally:
                profiler._lock.release()

            # malformed seconds is a client error
            for q in ("seconds=abc", "seconds=-1"):
                with pytest.raises(urllib.error.HTTPError) as e:
                    post_profile(q)
                assert e.value.code == 400

            # an oversized body is rejected (and the connection closed)
            # rather than left undrained on keep-alive. The server closes
            # without draining, so the client either reads the 400 or —
            # when the body outruns the kernel socket buffer — hits a
            # broken pipe mid-send; both prove the rejection.
            with pytest.raises(urllib.error.URLError) as e:
                urllib.request.urlopen(
                    urllib.request.Request(
                        f"http://127.0.0.1:{server.port}"
                        "/debug/profile?seconds=1",
                        data=b"x" * ((1 << 20) + 1),
                        method="POST",
                    ),
                    timeout=10,
                )
            if isinstance(e.value, urllib.error.HTTPError):
                assert e.value.code == 400

            # root-gated: a non-root process gets 403, not a trace dir
            profiler.process_index = 1
            with pytest.raises(urllib.error.HTTPError) as e:
                post_profile("seconds=1")
            assert e.value.code == 403
        finally:
            server.shutdown()

    @pytest.mark.slow
    def test_profile_capture_real(self, tmp_path):
        """One real jax.profiler capture through the endpoint (slow: the
        first capture in a process pays profiler initialization)."""
        server = ServingServer(
            FakeServingEngine(), port=0, max_delay_ms=5,
            profiler=ProfilerCapture(out_dir=str(tmp_path / "prof")),
        ).start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/debug/profile?seconds=0.2",
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=300) as resp:
                payload = json.loads(resp.read())
            assert resp.status == 200
            import pathlib

            trace_dir = pathlib.Path(payload["trace_dir"])
            assert trace_dir.is_dir()
            assert list(trace_dir.rglob("*")), "profiler wrote nothing"
        finally:
            server.shutdown()

    def test_profiler_single_flight_direct(self):
        p = ProfilerCapture(out_dir="unused")
        assert p._lock.acquire(blocking=False)
        try:
            with pytest.raises(ProfilerBusy):
                p.capture(0.1)
        finally:
            p._lock.release()

    def test_debug_traces_empty_without_traffic(self):
        server = ServingServer(
            FakeServingEngine(), port=0, max_delay_ms=5,
            tracer=Tracer(enabled=False),
        ).start()
        try:
            status, body = _get(server.port, "/debug/traces")
            assert status == 200
            assert json.loads(body)["traceEvents"] == []
        finally:
            server.shutdown()

    def test_debug_traces_n_param_and_metrics_query_parsing(self):
        server = ServingServer(
            FakeServingEngine(), port=0, max_delay_ms=5,
        ).start()
        try:
            for prompt in ("first", "second"):
                status, _ = _post(server.port, {"prompt": prompt})
                assert status == 200
            # ?n= bounds the export to the most recent n traces
            status, body = _get(server.port, "/debug/traces?n=1")
            assert status == 200
            tids = {
                e["tid"] for e in json.loads(body)["traceEvents"]
            }
            assert len(tids) == 1
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(server.port, "/debug/traces?n=0")
            assert e.value.code == 400
            # exemplar opt-in parses the query: neither an unrelated
            # param nor a non-flag value flips /metrics to OpenMetrics
            for q in ("?disable_exemplars=1", "?exemplars=10"):
                status, body = _get(server.port, f"/metrics{q}")
                assert status == 200 and "trace_id" not in body
        finally:
            server.shutdown()


# --------------------------------------- error paths keep /metrics honest


class TestErrorPathStageObservations:
    """Every stage observes into stage_seconds on its error path too, so
    /metrics and the traces agree whatever the outcome."""

    def test_micro_generate_error_observes_stage(self):
        from test_serving import FakeEngine, spec as micro_spec

        eng = FakeEngine(fail=True)
        b = MicroBatcher(eng, registry=MetricsRegistry())
        trace = Tracer().start_trace()
        req = b.submit([micro_spec(0)], trace=trace)
        with pytest.raises(RuntimeError):
            req.future.result(timeout=10)
        b.shutdown()
        trace.finish("error")
        assert dict(b.stage_seconds.items())["generate"].count == 1
        assert "generate" in trace.stage_seconds()

    def test_chunk_error_observes_stage(self):
        eng = FakeContinuousEngine(fail_chunks=True)
        b = ContinuousBatcher(eng, registry=eng.registry)
        trace = Tracer().start_trace()
        req = b.submit([spec(0)], trace=trace)
        with pytest.raises(RuntimeError):
            req.future.result(timeout=10)
        b.shutdown()
        trace.finish("error")
        # one observation per FAILED dispatch: the original chunk plus
        # the one bounded retry the recovery path grants the request
        assert dict(b.stage_seconds.items())["chunk"].count == 2
        assert (
            b.registry.get("dalle_serving_dispatch_retries_total").value == 1
        )

    def test_queued_timeout_observes_queue_stage(self):
        gate = threading.Event()
        eng = FakeContinuousEngine(block_event=gate)
        b = ContinuousBatcher(eng, registry=eng.registry)
        tr = Tracer()
        first = b.submit([spec(0)], trace=tr.start_trace())
        assert eng.chunk_entered.wait(10.0)
        doomed = b.submit([spec(1)], timeout_s=0.05, trace=tr.start_trace())
        time.sleep(0.2)
        gate.set()
        first.future.result(timeout=10)
        with pytest.raises(Exception):
            doomed.future.result(timeout=10)
        b.shutdown()
        # both the admitted AND the expired-in-queue request observed
        assert dict(b.stage_seconds.items())["queue"].count == 2
