"""Mesh-sharded serving: partition-spec rules, the sharded flash-decode
kernel, and `ShardedContinuousEngine` parity with the single-device
engine on the virtual 8-device CPU mesh (conftest forces
--xla_force_host_platform_device_count=8).

The load-bearing contract extends PR 2's decode-composition invariance
ACROSS THE MESH: a request's tokens are bit-identical whether the
engine's params/KV cache live on one device or are spread over a
`make_mesh` tp axis. It holds because every split the serving partition
rules make is reduction-free at the point of the split — heads are
independent in attention, vocab columns are independent in the logits
head — and the flash kernel's head split runs the unmodified
single-device kernel per shard.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dalle_pytorch_tpu.models.dalle import (
    DALLE,
    init_paged_slot_state,
    init_slot_state,
)
from dalle_pytorch_tpu.parallel.serving_partition import (
    decode_state_shardings,
    serving_variables_shardings,
)
from dalle_pytorch_tpu.serving.engine import (
    ContinuousEngine,
    PagedContinuousEngine,
    SampleSpec,
)
from dalle_pytorch_tpu.serving.sharded import (
    ShardedContinuousEngine,
    ShardedPagedContinuousEngine,
    build_serving_mesh,
    parse_mesh_shape,
)
from dalle_pytorch_tpu.training.metrics import MetricsRegistry

TEXT_SEQ = 8
FMAP = 4
IMG_SEQ = FMAP * FMAP


def _model(**kw):
    base = dict(
        dim=32, depth=2, heads=2, dim_head=8,
        num_image_tokens=32, image_fmap_size=FMAP,
        num_text_tokens=64, text_seq_len=TEXT_SEQ,
        shift_tokens=True, rotary_emb=True,
    )
    base.update(kw)
    return DALLE(**base)


def _params(model):
    text = jnp.zeros((1, TEXT_SEQ), jnp.int32)
    toks = jnp.zeros((1, model.image_seq_len), jnp.int32)
    return jax.jit(model.init)(jax.random.PRNGKey(42), text, toks)


def spec(seed, temperature=1.0, top_k=0.9):
    ids = np.zeros(TEXT_SEQ, np.int32)
    ids[:3] = (5, 6, 7)
    return SampleSpec(ids, seed=seed, temperature=temperature, top_k=top_k)


def _drain(engine, max_chunks=32):
    for _ in range(max_chunks):
        pos, act = engine.step_chunk()
        if (pos[act] >= engine.image_seq_len).all():
            return pos, act
    raise AssertionError("decode never finished")


def _flat_specs(shardings):
    return {
        "/".join(str(getattr(p, "key", p)) for p in path): s.spec
        for path, s in jax.tree_util.tree_flatten_with_path(shardings)[0]
    }


# ------------------------------------------------------ partition rules


class TestServingPartitionRules:
    def test_kv_heads_shard_over_tp(self):
        model = _model(heads=2)
        mesh = build_serving_mesh({"tp": 2})
        state = init_slot_state(model, 4)
        flat = _flat_specs(decode_state_shardings(state, mesh))
        k = next(v for p, v in flat.items() if p.endswith("attn/k"))
        assert k == P(None, "tp")  # [B, H, L, dh]: heads split
        v = next(v for p, v in flat.items() if p.endswith("attn/v"))
        assert v == P(None, "tp")

    def test_scan_executor_adds_depth_axis(self):
        model = _model(heads=2, executor="scan")
        mesh = build_serving_mesh({"tp": 2})
        state = init_slot_state(model, 4)
        flat = _flat_specs(decode_state_shardings(state, mesh))
        k = next(v for p, v in flat.items() if p.endswith("attn/k"))
        assert k == P(None, None, "tp")  # [depth, B, H, L, dh]

    def test_paged_pool_heads_shard_pages_stay_whole(self):
        """Paged layout: the page axis must NOT shard (the host page
        table addresses physical pages globally); heads still split."""
        model = _model(heads=2)
        mesh = build_serving_mesh({"tp": 2})
        state = init_paged_slot_state(model, 4, n_pages=8, page_size=8)
        flat = _flat_specs(decode_state_shardings(state, mesh))
        k = next(v for p, v in flat.items() if p.endswith("attn/k"))
        assert k == P(None, "tp")  # [P, H, page, dh]: pages replicated

    def test_nondivisible_heads_fall_back_to_replicated(self):
        """A 2-head model on a 8-way tp axis cannot split heads — the
        divisibility fallback drops to replicated instead of erroring."""
        model = _model(heads=2)
        mesh = build_serving_mesh({"tp": 8})
        state = init_slot_state(model, 4)
        flat = _flat_specs(decode_state_shardings(state, mesh))
        k = next(v for p, v in flat.items() if p.endswith("attn/k"))
        assert k == P()

    def test_row_scalars_replicated(self):
        """Per-row control state must replicate: the chunk-boundary host
        snapshot (img_pos, active) is the retirement decision's input and
        must stay a local read."""
        model = _model()
        mesh = build_serving_mesh({"tp": 2})
        state = init_slot_state(model, 4)
        flat = _flat_specs(decode_state_shardings(state, mesh))
        for key in ("img_pos", "active", "seeds", "temps", "keep_k",
                    "img_tokens"):
            assert flat[key] == P(), key
        idx = next(v for p, v in flat.items() if p.endswith("attn/index"))
        assert idx == P()

    def test_pending_logits_vocab_sharded(self):
        model = _model()  # total_tokens = 64 + 8 + 32 = 104, % 2 == 0
        mesh = build_serving_mesh({"tp": 2})
        state = init_slot_state(model, 4)
        flat = _flat_specs(decode_state_shardings(state, mesh))
        assert flat["row"] == P(None, "tp")

    def test_variables_follow_partition_rules(self):
        model = _model()
        mesh = build_serving_mesh({"tp": 2})
        variables = _params(model)
        flat = _flat_specs(serving_variables_shardings(variables, mesh))
        qkv = next(v for p, v in flat.items() if "to_qkv/kernel" in p)
        assert qkv == P("fsdp", "tp")


# ----------------------------------------------------------- mesh flags


class TestMeshFlags:
    def test_parse_axis_pairs(self):
        assert parse_mesh_shape("dp=2,tp=4") == {"dp": 2, "tp": 4}
        assert parse_mesh_shape(" tp=-1 ") == {"tp": -1}
        assert parse_mesh_shape(None) == {"tp": -1}

    def test_parse_rejects_unknown_axis(self):
        with pytest.raises(AssertionError):
            parse_mesh_shape("pp=2")
        with pytest.raises(AssertionError):
            parse_mesh_shape("2,4")

    def test_parse_rejects_nonpositive_sizes(self):
        """tp=0 or tp=-2 must die at parse time — build_serving_mesh's
        device-prefix math would otherwise accept an empty mesh and blow
        up only after the checkpoint loads."""
        with pytest.raises(AssertionError):
            parse_mesh_shape("tp=0")
        with pytest.raises(AssertionError):
            parse_mesh_shape("tp=-2")
        with pytest.raises(AssertionError):
            build_serving_mesh({"tp": 0})

    def test_build_uses_prefix_of_devices(self):
        mesh = build_serving_mesh({"tp": 2})
        assert dict(mesh.shape) == {"dp": 1, "fsdp": 1, "tp": 2, "sp": 1}
        assert mesh.devices.size == 2

    def test_build_absorbs_remaining_devices(self):
        mesh = build_serving_mesh("dp=2,tp=-1")
        n = len(jax.devices())
        assert dict(mesh.shape)["tp"] == n // 2

    def test_build_rejects_oversized_mesh(self):
        with pytest.raises(AssertionError):
            build_serving_mesh({"tp": 2 * len(jax.devices())})

    def test_mesh_axes_in_lockstep_with_parallel_mesh(self):
        """sharded.py re-declares the axis vocabulary so parse_mesh_shape
        stays importable without a jax init — it must track MESH_AXES."""
        from dalle_pytorch_tpu.parallel import mesh as pmesh
        from dalle_pytorch_tpu.serving import sharded

        assert tuple(sharded.MESH_AXES) == tuple(pmesh.MESH_AXES)


# -------------------------------------------------- sharded flash kernel


class TestShardedFlashDecode:
    def test_bitwise_match_and_fallback(self):
        """Head-split kernel == unsharded kernel BITWISE (each device
        runs the unmodified kernel on its own heads); heads that don't
        divide the axis fall back to the unsharded call."""
        from dalle_pytorch_tpu.ops.pallas_decode import (
            flash_decode_attention,
            sharded_flash_decode_attention,
        )

        mesh = build_serving_mesh({"tp": 2})
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
        b, h, s, d = 3, 4, 32, 8
        q = jax.random.normal(k1, (b, h, 1, d))
        k = jax.random.normal(k2, (b, h, s, d))
        v = jax.random.normal(k3, (b, h, s, d))
        lengths = jnp.asarray([5, 17, 32], jnp.int32)
        want = np.asarray(flash_decode_attention(q, k, v, lengths))
        got = np.asarray(
            sharded_flash_decode_attention(mesh, q, k, v, lengths)
        )
        assert np.array_equal(want, got)

        odd = np.asarray(sharded_flash_decode_attention(
            mesh, q[:, :3], k[:, :3], v[:, :3], lengths
        ))
        assert np.array_equal(want[:, :3], odd)


# ------------------------------------------------------- engine parity


@pytest.fixture(scope="module")
def engines():
    """(single-device continuous, sharded tp=2) over ONE set of weights —
    the same toy geometry as tests/test_continuous.py, so the unsharded
    programs come out of the shared jit cache."""
    model = _model()
    params = _params(model)
    cont = ContinuousEngine(
        model=model, variables=params, max_batch=4, chunk_tokens=8,
        registry=MetricsRegistry(),
    )
    sharded = ShardedContinuousEngine(
        model=model, variables=params, max_batch=4, chunk_tokens=8,
        registry=MetricsRegistry(), mesh=build_serving_mesh({"tp": 2}),
    )
    return cont, sharded


class TestShardedParity:
    def test_state_actually_sharded(self, engines):
        _, sharded = engines
        k = sharded._state["cache"]["layer_0"]["attn"]["k"]
        assert k.sharding.spec == P(None, "tp")
        assert len({s.device for s in k.addressable_shards}) == 2
        assert sharded._state["img_pos"].sharding.spec == P()

    def test_bit_identical_tokens_incl_midflight_admission(self, engines):
        """The acceptance pin: same specs/seeds through both engines —
        heterogeneous per-row sampling params, plus a mid-flight
        admission after the first chunk — produce bit-identical tokens."""
        cont, sharded = engines
        first = [spec(7, 1.0, 0.9), spec(11, 0.7, 0.95), spec(13, 1.3, 0.8)]
        late = spec(17, 0.9, 0.85)
        results = []
        for e in (cont, sharded):
            for i, s in enumerate(first):
                e.prefill_slot(i, s)
            e.step_chunk()  # rows mid-flight...
            e.prefill_slot(3, late)  # ...when the late row is admitted
            _drain(e)
            results.append(e.harvest([0, 1, 2, 3]))
            e.release([0, 1, 2, 3])
        assert np.array_equal(results[0], results[1])

    def test_mesh_detail_names_every_shard(self, engines):
        _, sharded = engines
        dump = sharded.state_dump()
        mesh = dump["mesh"]
        assert mesh["axes"]["tp"] == 2
        assert mesh["devices"] == 2
        per_dev = mesh["per_device_state_bytes"]
        assert len(per_dev) == 2
        # replicated leaves weigh the same everywhere; the sharded KV
        # splits evenly — so the two shards' totals must match
        assert len(set(per_dev.values())) == 1
        assert all(v > 0 for v in per_dev.values())

    def test_healthz_carries_mesh_block(self, engines):
        from dalle_pytorch_tpu.serving.server import ServingServer

        _, sharded = engines
        server = ServingServer(sharded, port=0)
        try:
            healthy, detail = server.health()
            assert healthy
            assert detail["mesh"]["axes"]["tp"] == 2
            assert detail["mesh"]["model_axis"] == "tp"
        finally:
            server.shutdown(drain=False)


# ---------------------------------------------------- paged sharded engine


@pytest.fixture(scope="module", params=[None, "int8"], ids=["bf16", "int8"])
def paged_pair(request):
    """(single-device paged, sharded paged tp=2) over ONE set of weights,
    both resume-enabled, parametrized over the KV dtype: the parity and
    resume contracts must hold for the int8 pool too — and they hold
    BITWISE, because both engines run the identical quantize/dequant code
    on the identical values; the mesh only splits the head axis."""
    model = _model()
    params = _params(model)
    kw = dict(
        model=model, variables=params, max_batch=4, chunk_tokens=8,
        prefill_batch=2, page_size=4, resume_enabled=True,
        kv_dtype=request.param,
    )
    cont = PagedContinuousEngine(registry=MetricsRegistry(), **kw)
    shard = ShardedPagedContinuousEngine(
        registry=MetricsRegistry(), mesh=build_serving_mesh({"tp": 2}), **kw,
    )
    return cont, shard


class TestShardedPagedEngine:
    def test_pool_heads_sharded_pages_whole(self, paged_pair):
        """The physical page pool splits over heads (each device holds
        its heads' slice of EVERY page) — the page axis stays whole so
        the host page table keeps addressing pages globally. int8 scale
        sidecars follow their payload's head split."""
        _, shard = paged_pair
        attn = shard._state["cache"]["layer_0"]["attn"]
        assert attn["k"].sharding.spec == P(None, "tp")
        assert len({s.device for s in attn["k"].addressable_shards}) == 2
        if "k_scale" in attn:
            assert attn["k_scale"].sharding.spec == P(None, "tp")
            assert attn["v_scale"].sharding.spec == P(None, "tp")
        assert shard._state["img_pos"].sharding.spec == P()

    def test_bit_identical_tokens_incl_midflight_admission(self, paged_pair):
        """The paged acceptance pin: same specs/seeds through the
        single-device and tp=2 paged engines — heterogeneous sampling
        params plus a mid-flight admission (a prefix-cache HIT, all rows
        share a prompt) — produce bit-identical tokens."""
        cont, shard = paged_pair
        first = [spec(7, 1.0, 0.9), spec(11, 0.7, 0.95), spec(13, 1.3, 0.8)]
        late = spec(17, 0.9, 0.85)
        results = []
        for e in (cont, shard):
            for i, s in enumerate(first):
                e.prefill_slot(i, s)
            e.step_chunk()  # rows mid-flight...
            e.prefill_slot(3, late)  # ...when the late row is admitted
            _drain(e)
            results.append(e.harvest([0, 1, 2, 3]))
            e.release([0, 1, 2, 3])
        assert np.array_equal(results[0], results[1])

    def test_resume_at_position_bit_identical_and_leak_free(self, paged_pair):
        """Preempt at a chunk boundary, release the pages, resume the
        prefix on the SHARDED engine via the pinned resume program —
        final tokens equal the single-device engine's uninterrupted
        decode, and the page pool leaks nothing."""
        cont, shard = paged_pair
        specs = [spec(21, 0.8, 0.9), spec(23, 1.1, 0.85)]
        for i, s in enumerate(specs):
            cont.prefill_slot(i, s)
        _drain(cont)
        ref = cont.harvest([0, 1])
        cont.release([0, 1])

        for i, s in enumerate(specs):
            shard.prefill_slot(i, s)
        pos, _ = shard.step_chunk()  # one chunk: mid-decode
        prefix = shard.snapshot_rows([0, 1])
        cut = [int(pos[i]) for i in (0, 1)]
        assert all(0 < c < IMG_SEQ for c in cut)
        shard.release([0, 1])  # preemption returns the pages

        resumed = [
            (i, SampleSpec(
                s.text_ids, seed=s.seed, temperature=s.temperature,
                top_k=s.top_k, resume_tokens=prefix[i, :cut[i]].copy(),
                resume_pos=cut[i],
            ))
            for i, s in enumerate(specs)
        ]
        shard.resume_slots(resumed)
        _drain(shard)
        got = shard.harvest([0, 1])
        shard.release([0, 1])
        np.testing.assert_array_equal(got, ref)
        assert shard.kv.leak_check() == []


@pytest.mark.slow  # fresh resume-enabled sharded engine = its own compiles
class TestShardedSlottedResume:
    def test_resume_at_position_bit_identical(self, engines):
        """Slot-layout sharded resume: preempt mid-decode, resume at
        position on a fresh resume-enabled tp=2 engine — tokens equal
        the single-device uninterrupted decode. (The fast tier pins
        sharded at-position resume on the PAGED engine; this slotted
        variant rides the slow tier to protect the tier-1 budget.)"""
        cont, _ = engines
        specs = [spec(31, 0.9, 0.9), spec(33, 1.2, 0.85)]
        for i, s in enumerate(specs):
            cont.prefill_slot(i, s)
        _drain(cont)
        ref = cont.harvest([0, 1])
        cont.release([0, 1])

        shard = ShardedContinuousEngine(
            model=cont.model, variables=cont.variables, max_batch=4,
            chunk_tokens=8, registry=MetricsRegistry(),
            mesh=build_serving_mesh({"tp": 2}), resume_enabled=True,
        )
        for i, s in enumerate(specs):
            shard.prefill_slot(i, s)
        pos, _ = shard.step_chunk()
        prefix = shard.snapshot_rows([0, 1])
        cut = [int(pos[i]) for i in (0, 1)]
        assert all(0 < c < IMG_SEQ for c in cut)
        shard.release([0, 1])
        shard.resume_slots([
            (i, SampleSpec(
                s.text_ids, seed=s.seed, temperature=s.temperature,
                top_k=s.top_k, resume_tokens=prefix[i, :cut[i]].copy(),
                resume_pos=cut[i],
            ))
            for i, s in enumerate(specs)
        ])
        _drain(shard)
        got = shard.harvest([0, 1])
        shard.release([0, 1])
        np.testing.assert_array_equal(got, ref)


# ------------------------------------------------------------ slow tier


@pytest.mark.slow  # full warmup of the sharded program set + flash
class TestShardedWarmServer:
    def test_warm_sharded_cycle_compiles_nothing(self):
        """Post-warmup sharded serve cycle (admit -> chunk -> mid-flight
        admit -> harvest -> pixels -> release) compiles ZERO programs:
        the out_shardings pin makes the donated state's sharding a fixed
        point, so the jit cache never re-keys on a drifted sharding."""
        from dalle_pytorch_tpu.models.dvae import DiscreteVAE
        from dalle_pytorch_tpu.utils.compile_guard import assert_no_recompiles

        model = _model(num_image_tokens=64)
        params = _params(model)
        vae = DiscreteVAE(
            image_size=4 * FMAP, num_layers=2, num_tokens=64,
            codebook_dim=32, hidden_dim=16,
        )
        vae_params = jax.jit(vae.init)(
            jax.random.PRNGKey(1), jnp.zeros((1, 4 * FMAP, 4 * FMAP, 3))
        )["params"]
        engine = ShardedContinuousEngine(
            model=model, variables=params, vae=vae, vae_params=vae_params,
            max_batch=4, chunk_tokens=8, registry=MetricsRegistry(),
            mesh=build_serving_mesh({"tp": 2}),
        )
        engine.warmup()
        with assert_no_recompiles():
            engine.prefill_slots([(0, spec(3)), (1, spec(4))])
            engine.step_chunk()
            engine.prefill_slot(2, spec(5))
            _drain(engine)
            toks = engine.harvest([0, 1, 2])
            engine.decode_pixels(toks)
            engine.release([0, 1, 2])

    def test_flash_impl_sharded_parity(self):
        """attn_impl="flash" routes the cached path through the
        shard_map-wrapped kernel (models/attention.py decode_mesh) — and
        stays bit-identical to the single-device flash engine."""
        model = _model(shift_tokens=False, attn_impl="flash")
        params = _params(model)
        cont = ContinuousEngine(
            model=model, variables=params, max_batch=2, chunk_tokens=8,
            registry=MetricsRegistry(),
        )
        sharded = ShardedContinuousEngine(
            model=model, variables=params, max_batch=2, chunk_tokens=8,
            registry=MetricsRegistry(), mesh=build_serving_mesh({"tp": 2}),
        )
        # the engine handed the mesh AND the head axis to the attention
        # dispatch (the kernel must split over the KV shardings' axis)
        assert sharded.model.decode_mesh is not None
        assert sharded.model.decode_heads_axis == sharded.model_axis
        results = []
        for e in (cont, sharded):
            e.prefill_slot(0, spec(9))
            _drain(e)
            results.append(e.harvest([0]))
        assert np.array_equal(results[0], results[1])


@pytest.mark.slow  # full warmup of the sharded PAGED program ladder
class TestShardedPagedWarmServer:
    def test_warm_sharded_paged_cycle_compiles_nothing(self):
        """Post-warmup sharded PAGED serve cycle — admit(miss) -> chunk
        -> mid-flight admit(hit) -> harvest -> pixels -> release ->
        preempt -> resume — compiles ZERO programs: every program in the
        paged ladder is re-jitted with out_shardings pinned, so the
        donated state's sharding is a fixed point of every dispatch."""
        from dalle_pytorch_tpu.models.dvae import DiscreteVAE
        from dalle_pytorch_tpu.utils.compile_guard import assert_no_recompiles

        model = _model(num_image_tokens=64)
        params = _params(model)
        vae = DiscreteVAE(
            image_size=4 * FMAP, num_layers=2, num_tokens=64,
            codebook_dim=32, hidden_dim=16,
        )
        vae_params = jax.jit(vae.init)(
            jax.random.PRNGKey(1), jnp.zeros((1, 4 * FMAP, 4 * FMAP, 3))
        )["params"]
        engine = ShardedPagedContinuousEngine(
            model=model, variables=params, vae=vae, vae_params=vae_params,
            max_batch=4, chunk_tokens=8, prefill_batch=2, page_size=4,
            resume_enabled=True, registry=MetricsRegistry(),
            mesh=build_serving_mesh({"tp": 2}),
        )
        engine.warmup()
        with assert_no_recompiles():
            engine.prefill_slots([(0, spec(3)), (1, spec(4))])
            engine.step_chunk()
            engine.prefill_slot(2, spec(5))  # mid-flight prefix HIT
            _drain(engine)
            toks = engine.harvest([0, 1, 2])
            engine.decode_pixels(toks)
            engine.release([0, 1, 2])
            assert engine.last_admission_stats["prefix_hits"] >= 1
            # preempt -> resume inside the same warm window
            engine.prefill_slot(3, spec(9))
            pos, _ = engine.step_chunk()
            prefix = engine.snapshot_rows([3])
            cut = int(pos[3])
            engine.release([3])
            engine.resume_slots([(3, SampleSpec(
                spec(9).text_ids, seed=9,
                resume_tokens=prefix[0, :cut].copy(), resume_pos=cut,
            ))])
            _drain(engine)
            engine.release([3])
        assert engine.kv.leak_check() == []
